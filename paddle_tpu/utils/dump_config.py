"""Model-config dump tools (reference python/paddle/utils/dump_config.py
and dump_v2_config.py). The reference printed the TrainerConfig protobuf
parsed from a config file; here the canonical model description is the
fluid Program, so the dump is its JSON serialization."""

import json

__all__ = ["dump_config", "dump_v2_config"]


def dump_v2_config(topology, save_path=None, binary=False):
    """Serialize a v2 topology's inference Program (reference
    dump_v2_config.py:24 — there, the ModelConfig protobuf). Returns the
    serialized text; writes it to save_path when given."""
    from ..v2.topology import Topology
    if not isinstance(topology, Topology):
        topology = Topology(topology)
    text = topology.proto()
    if binary:
        text = text.encode("utf-8") if isinstance(text, str) else text
    if save_path:
        mode = "wb" if binary else "w"
        with open(save_path, mode) as f:
            f.write(text)
    return text


def dump_config(config_path=None, module=None, config_arg_str=""):
    """Execute a v1/v2 config file and dump the resulting network
    (reference dump_config.py: parsed the file into TrainerConfig).
    The config script must expose the output layer(s) via a top-level
    `net`/`cost`/`outputs` variable."""
    import runpy
    if module is not None:
        env = vars(module)
    else:
        env = runpy.run_path(config_path)
    for key in ("outputs", "net", "cost", "prediction"):
        if key in env:
            return dump_v2_config(env[key])
    raise ValueError(
        "config %r defines none of outputs/net/cost/prediction"
        % (config_path or module))


def _program_summary(program):
    """Human-oriented op/var counts per block (debug aid)."""
    out = []
    for i, blk in enumerate(program.blocks):
        ops = {}
        for op in blk.ops:
            ops[op.type] = ops.get(op.type, 0) + 1
        out.append({"block": i, "n_vars": len(blk.vars), "ops": ops})
    return json.dumps(out, indent=2, sort_keys=True)
