"""Merge a v2 network + trained parameters into ONE deployable file
(reference python/paddle/utils/merge_model.py merge_v2_model: config
proto + each parameter, length-framed). Format here: a tar containing
'__topology__.json' (the inference Program) and the parameters in the
v2 tar layout — loadable with load_merged_model."""

import os
import tarfile
import tempfile

__all__ = ["merge_v2_model", "load_merged_model"]


def merge_v2_model(net, param_file, output_file):
    """net: output layer(s) of the inference network; param_file: a
    Parameters tar saved by `parameters.to_tar` (reference took the
    .tar.gz path); output_file: merged artifact path."""
    from ..v2.topology import Topology
    from ..v2.parameters import Parameters

    assert not os.path.exists(output_file), \
        "%r already exists" % output_file
    topo = net if isinstance(net, Topology) else Topology(net)
    blob = topo.proto()

    with open(param_file, "rb") as f:
        params = Parameters.from_tar(f)

    with tarfile.open(output_file, "w") as tar:
        if isinstance(blob, str):
            blob = blob.encode("utf-8")
        _add_bytes(tar, "__topology__.json", blob)
        with tempfile.NamedTemporaryFile(delete=False) as tmp:
            params.to_tar(tmp)
            tmp_path = tmp.name
        tar.add(tmp_path, arcname="__parameters__.tar")
        os.unlink(tmp_path)


def load_merged_model(path):
    """(program, Parameters) from a merge_v2_model artifact."""
    from ..fluid.framework import Program
    from ..v2.parameters import Parameters

    with tarfile.open(path, "r") as tar:
        blob = tar.extractfile("__topology__.json").read()
        program = Program.parse_from_string(blob.decode("utf-8"))
        pf = tar.extractfile("__parameters__.tar")
        import io
        params = Parameters.from_tar(io.BytesIO(pf.read()))
    return program, params


def _add_bytes(tar, name, blob):
    import io
    info = tarfile.TarInfo(name)
    info.size = len(blob)
    tar.addfile(info, io.BytesIO(blob))
