"""Retry policy: bounded attempts, exponential backoff, jitter.

One policy object serves every fault-tolerance retry surface — the
master client's re-dial loop (distributed/elastic.py MasterClient._call),
the pserver readiness poll (distributed/rpc.py wait_server_ready), the
RPC client's idempotent-command reconnect, and the data-layer
`retry_reader` decorator (reader/decorator.py) — so backoff behavior is
tuned in exactly one place (FLAGS.rpc_retry_times /
FLAGS.rpc_retry_backoff provide the distributed defaults).

Jitter matters operationally: when a master or pserver restarts, every
worker notices at the same instant; synchronized retries stampede the
recovering endpoint.  Each delay is multiplied by a uniform factor in
[1-jitter, 1+jitter].
"""

import random
import time

__all__ = ["RetryPolicy", "default_rpc_policy"]


class RetryPolicy:
    """`max_attempts` total tries (>=1); between tries, sleep
    ``base_delay * multiplier**k`` capped at `max_delay`, jittered.
    A policy object is stateless across uses — `delays()` returns a
    fresh iterator, `call()` runs a callable under the policy."""

    def __init__(self, max_attempts=5, base_delay=0.05, max_delay=2.0,
                 multiplier=2.0, jitter=0.5, retry_on=(OSError,),
                 sleep=time.sleep, rng=None):
        self.max_attempts = max(int(max_attempts), 1)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = min(max(float(jitter), 0.0), 1.0)
        self.retry_on = tuple(retry_on)
        self._sleep = sleep
        self._rng = rng or random.Random()

    def delays(self):
        """Yield the sleep duration before each RETRY (so at most
        max_attempts - 1 values)."""
        for k in range(self.max_attempts - 1):
            d = min(self.base_delay * (self.multiplier ** k),
                    self.max_delay)
            if self.jitter:
                d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
            yield max(d, 0.0)

    def sleep(self, delay):
        self._sleep(delay)

    def call(self, fn, retry_on=None, on_retry=None, deadline=None):
        """Run `fn()` with retries on `retry_on` (defaults to the
        policy's own).  `on_retry(exc, attempt)` runs before each sleep
        (cleanup hook: close a dead socket, log).  A monotonic
        `deadline` stops retrying early — the last exception re-raises.
        """
        retry_on = self.retry_on if retry_on is None else tuple(retry_on)
        it = self.delays()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except retry_on as e:
                try:
                    delay = next(it)
                except StopIteration:
                    raise e
                if deadline is not None and \
                        time.monotonic() + delay > deadline:
                    raise e
                if on_retry is not None:
                    on_retry(e, attempt)
                self.sleep(delay)


def default_rpc_policy(**overrides):
    """The distributed control plane's shared policy, parameterized by
    FLAGS.rpc_retry_times / FLAGS.rpc_retry_backoff."""
    from ..flags import FLAGS
    kw = dict(max_attempts=FLAGS.rpc_retry_times,
              base_delay=FLAGS.rpc_retry_backoff,
              retry_on=(ConnectionError, OSError, EOFError))
    kw.update(overrides)
    return RetryPolicy(**kw)
