"""paddle_tpu — a TPU-native deep learning framework with the capabilities of
PaddlePaddle Fluid (reference: /root/reference, powermano/Paddle).

Layout (SURVEY.md §7):
  fluid/     Fluid-compatible user API: Program IR, layers, autodiff,
             Executor/ParallelExecutor over XLA jit
  ops/       the op registry — each op is a pure JAX lowering (the "kernel
             layer"; XLA replaces per-device kernel dispatch)
  parallel/  device meshes, collectives, distributed bootstrap
  models/    reference model zoo (benchmark/fluid parity)
  utils/     support code
"""

__version__ = "0.1.0"

import warnings as _warnings

# Design-intended behavior, not a defect: the framework runs with jax
# x64 disabled (TPU-native int32/float32 words), so reference-API int64
# vars deliberately ride int32 on device. jax warns on every such
# conversion; silence exactly that message (fluid/core.py keeps true
# int64 on the numpy/serde side).
_warnings.filterwarnings(
    "ignore",
    message=r"Explicitly requested dtype .*int64.* is not available")

from . import compile_cache  # noqa: F401,E402  (stdlib-only at import)
from . import fluid  # noqa: F401,E402
from . import reader  # noqa: F401,E402
from . import dataset  # noqa: F401,E402
from . import compat  # noqa: F401,E402
from .batch import batch  # noqa: F401,E402
