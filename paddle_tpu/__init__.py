"""paddle_tpu — a TPU-native deep learning framework with the capabilities of
PaddlePaddle Fluid (reference: /root/reference, powermano/Paddle).

Layout (SURVEY.md §7):
  fluid/     Fluid-compatible user API: Program IR, layers, autodiff,
             Executor/ParallelExecutor over XLA jit
  ops/       the op registry — each op is a pure JAX lowering (the "kernel
             layer"; XLA replaces per-device kernel dispatch)
  parallel/  device meshes, collectives, distributed bootstrap
  models/    reference model zoo (benchmark/fluid parity)
  utils/     support code
"""

__version__ = "0.1.0"

from . import fluid  # noqa: F401
