"""Quantized-inference op lowerings (QUANTIZE.md).

Reference analogue: the contrib quantize_transpiler's fake-quant ops
(fluid/contrib/quantize_transpiler.py) simulate int8 during TRAINING;
here the ops are the real post-training serving path: the PTQ pass
(paddle_tpu/inference/quantize.py) rewrites an inference artifact's
matmul-class ops to these types, the weight vars become int8, and a
per-output-channel fp32 scale var rides alongside.

Numerics contract (shared by every op here and pinned by the parity
tests): activations are cast to bfloat16 before the contraction (the
MLPerf TPU-v3 pods paper grounds bf16-activation numerics at scale),
the int8 weight dequantizes THROUGH the activation dtype in-register,
accumulation is fp32, the per-channel scale applies to the fp32
accumulator, and the result casts back to the op's recorded output
dtype so the rest of the graph is untouched.  On TPU the contraction is
the Pallas fused dequant-matmul kernel (ops/pallas_kernels.py —
int8 weight tiles streamed from HBM, never materialized as float);
elsewhere (and for conv/gather shapes) the plain-XLA composition with
identical semantics serves as fallback and oracle.

These lowerings are ordinary registry entries, so the PR 9 verifier's
``verify_shapes_pass`` abstractly evaluates them like any other op —
quantized artifacts lint clean with no ``unregistered-op`` findings and
no ``_EVAL_SKIP_TYPES`` exemption (analysis/verifier.py).
"""

import numpy as np

from .registry import register_op

__all__ = ["QUANT_OP_TYPES", "quantized_op_for"]

# forward op type -> quantized op type (the PTQ pass's rewrite table)
QUANT_OP_TYPES = {
    "mul": "dequant_mul",
    "conv2d": "dequant_conv2d",
    "lookup_table": "dequant_lookup_table",
}


def quantized_op_for(op_type):
    """The quantized twin of a forward op type, or None."""
    return QUANT_OP_TYPES.get(op_type)


def _jnp():
    import jax.numpy as jnp
    return jnp


def _act(x, ctx):
    """Cast a float activation to the artifact's activation dtype
    (bf16 unless the PTQ pass recorded otherwise)."""
    jnp = _jnp()
    act_dtype = ctx.attr("act_dtype", "bfloat16")
    if jnp.issubdtype(x.dtype, jnp.floating) and \
            str(x.dtype) != act_dtype:
        return x.astype(act_dtype)
    return x


def _out_dtype(ctx, slot_name, default=np.float32):
    """The recorded dtype of the op's output var — the graph downstream
    keeps seeing what it saw before quantization."""
    names = ctx.op.outputs.get(slot_name, [])
    if names:
        v = ctx.op.block._find_var_recursive(names[0])
        if v is not None and v.dtype is not None:
            return v.np_dtype
    return default


@register_op("dequant_mul")
def _dequant_mul(ctx):
    """Quantized `mul`: X [.., K] float, Y [K, N] int8, Scale [N] f32.
    Same flatten semantics as the mul op; the contraction is the fused
    dequant-matmul kernel (XLA fallback for untileable shapes)."""
    from .pallas_kernels import dequant_matmul
    jnp = _jnp()
    x, w = ctx.input("X"), ctx.input("Y")
    scale = ctx.input("Scale")
    xd = ctx.attr("x_num_col_dims", 1)
    if ctx.lod_len("X") is not None:
        xd += 1  # padded ragged input: one extra leading dim (see mul)
    lead = int(np.prod(x.shape[:xd])) if xd > 0 else 1
    x2 = _act(jnp.reshape(x, (lead, -1)), ctx)
    out = dequant_matmul(x2, w, scale,
                         out_dtype=_out_dtype(ctx, "Out"))
    return {"Out": jnp.reshape(out, x.shape[:xd] + (w.shape[1],))}


@register_op("dequant_conv2d")
def _dequant_conv2d(ctx):
    """Quantized conv2d: Filter [O, I, kh, kw] int8, Scale [O] f32
    per-output-channel.  The scale distributes over the whole reduction
    (I x kh x kw), so it applies to the conv's fp32 accumulator per
    output channel; the int8->bf16 weight convert is left to XLA, which
    fuses it into the conv's operand read on TPU."""
    import jax
    jnp = _jnp()
    x, w = ctx.input("Input"), ctx.input("Filter")
    scale = ctx.input("Scale")
    strides = ctx.attr("strides", [1, 1])
    pads = ctx.attr("paddings", [0, 0])
    dilations = ctx.attr("dilations", [1, 1])
    groups = ctx.attr("groups", 1) or 1
    layout = "NHWC" if ctx.attr("data_format", "NCHW") == "NHWC" \
        else "NCHW"
    if isinstance(strides, int):
        strides = [strides, strides]
    if isinstance(pads, int):
        pads = [pads, pads]
    if isinstance(dilations, int):
        dilations = [dilations, dilations]
    x = _act(x, ctx)
    out = jax.lax.conv_general_dilated(
        x, w.astype(x.dtype),
        window_strides=tuple(strides),
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=tuple(dilations), feature_group_count=groups,
        dimension_numbers=(layout, "OIHW", layout),
        preferred_element_type=jnp.float32)
    sshape = (1, -1, 1, 1) if layout != "NHWC" else (1, 1, 1, -1)
    out = out * scale.astype(jnp.float32).reshape(sshape)
    if ctx.has_input("Bias"):
        out = out + ctx.input("Bias").astype(jnp.float32).reshape(sshape)
    return {"Output": out.astype(_out_dtype(ctx, "Output"))}


@register_op("dequant_lookup_table")
def _dequant_lookup_table(ctx):
    """Quantized embedding gather: W [V, D] int8, Scale [V] f32 per ROW
    (each vocabulary row quantizes independently — the per-channel axis
    of a gather is the gathered axis).  Only the gathered rows ever
    dequantize, so the HBM read per token is D int8 bytes + one f32."""
    jnp = _jnp()
    ids = ctx.input("Ids")
    w, scale = ctx.input("W"), ctx.input("Scale")
    # same trailing-[.., 1] squeeze as the fp32 lookup_table lowering —
    # the rewrite must not move a single output shape
    squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
    flat_ids = (ids.reshape(ids.shape[:-1]) if squeeze_last
                else ids).astype(jnp.int32)
    rows = (jnp.take(w, flat_ids, axis=0).astype("bfloat16")
            * jnp.take(scale.astype(jnp.float32), flat_ids,
                       axis=0)[..., None])
    padding_idx = ctx.attr("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        rows = rows * (flat_ids != padding_idx)[..., None].astype(
            rows.dtype)
    return {"Out": rows.astype(_out_dtype(ctx, "Out"))}
