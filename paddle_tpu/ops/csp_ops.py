"""CSP op lowerings: go / channel_{create,send,recv,close}.

Reference analogues: operators/csp/go_op.cc (GoOp::RunImpl spawns a
detached thread executing the sub-block via a nested Executor) and the
era's CHANNEL variable machinery (framework.proto VarType CHANNEL).

All host ops (functionalizer.HOST_OPS): channels are synchronized
queues, `go` interprets its sub-block on a daemon thread over a shallow
env snapshot — channel objects are shared by reference, giving the
goroutine-style communicate-by-channel semantics."""

import threading
import warnings

import numpy as np

from .registry import register_op


class Channel:
    """Closable bounded queue. capacity=0 = unbuffered handoff (size-1
    slot, like a Go unbuffered channel's rendezvous up to one pending
    item)."""

    def __init__(self, capacity=0):
        self.capacity = max(int(capacity), 1)
        self._items = []
        self._closed = False
        self._cv = threading.Condition()

    def send(self, value):
        with self._cv:
            while len(self._items) >= self.capacity and not self._closed:
                self._cv.wait(timeout=0.1)
            if self._closed:
                return False          # send on closed channel
            self._items.append(value)
            self._cv.notify_all()
            return True

    def recv(self):
        with self._cv:
            while not self._items and not self._closed:
                self._cv.wait(timeout=0.1)
            if self._items:
                v = self._items.pop(0)
                self._cv.notify_all()
                return v, True
            return None, False        # closed and drained

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()


@register_op("channel_create")
def _channel_create(ctx):
    return {"Out": Channel(ctx.attr("capacity", 0))}


@register_op("channel_send")
def _channel_send(ctx):
    ch = ctx.input("Channel")
    assert isinstance(ch, Channel), "channel_send on a non-channel var"
    ok = ch.send(np.asarray(ctx.input("X")))
    return {"Status": np.asarray([ok])}


@register_op("channel_recv")
def _channel_recv(ctx):
    import jax.numpy as jnp
    ch = ctx.input("Channel")
    assert isinstance(ch, Channel), "channel_recv on a non-channel var"
    v, ok = ch.recv()
    out = {"Status": np.asarray([ok])}
    if v is not None:
        out["Out"] = jnp.asarray(v)
    return out


@register_op("channel_close")
def _channel_close(ctx):
    ctx.input("Channel").close()
    return {}


@register_op("go")
def _go(ctx):
    """go_op.cc RunImpl: execute the sub-block concurrently. The thread
    interprets over a shallow env snapshot — values captured at spawn,
    Channel objects shared by reference."""
    import jax
    from ..fluid import functionalizer
    block = ctx.attr("sub_block")
    env = ctx.env
    assert env is not None, "go op needs the interpreter env (eager path)"
    if any(isinstance(v, jax.core.Tracer) for v in env.values()):
        raise RuntimeError("go blocks cannot be traced under jit — run "
                           "the program through the Executor's eager path")
    snapshot = dict(env)
    step, seed = ctx.step, ctx.seed

    # channels this block TOUCHES (sends to OR receives from, transitively
    # through its sub-blocks): only these may be force-closed on failure.
    # Closing its send targets unblocks downstream consumers; closing its
    # recv sources unblocks upstream producers parked in a rendezvous
    # send. Channels of unrelated pipelines stay open.
    def touched_channels(blk, acc, seen):
        for op in blk.ops:
            if op.type in ("channel_send", "channel_recv"):
                acc.update(op.inputs.get("Channel", []))
            sub = op.attrs.get("sub_block")
            if sub is not None and id(sub) not in seen:
                seen.add(id(sub))
                touched_channels(sub, acc, seen)
        return acc

    produced = touched_channels(block, set(), set())

    def run():
        try:
            functionalizer.run_block(block, snapshot, step=step, seed=seed)
        except Exception as e:          # detached thread: surface loudly
            warnings.warn("go block failed: %s" % e)
            # fail fast: close the channels this producer feeds so
            # main-program channel_recv calls unblock with Status=False
            # instead of hanging on a producer that died mid-way
            for name in produced:
                v = snapshot.get(name)
                if isinstance(v, Channel):
                    v.close()

    threading.Thread(target=run, daemon=True).start()
    return {}
