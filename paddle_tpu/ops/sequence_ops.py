"""Sequence (LoD/ragged) op lowerings + recurrent ops.

Reference analogues: paddle/fluid/operators/sequence_ops/ (17 op families, all
honoring the packed LoD layout), lstm_op.cc (dynamic LSTM: gate columns
{c, i, f, o} per math/detail/lstm_cpu_kernel.h:44-47, optional peepholes),
gru_op.cc ({u, r, c} columns, out = (1-u)*prev + u*cand), and the
math/sequence2batch machinery that re-batches ragged rows per timestep.

TPU encoding (SURVEY.md §5 long-context): a ragged var is a padded dense
[B, T, ...] array + an int32 lengths vector [B] carried as a companion env
entry (functionalizer.LOD_LEN_SUFFIX). The reference's sequence2batch
reordering disappears: recurrences are lax.scan over the padded time axis
with per-step masks — static shapes, MXU-friendly batched matmuls, and the
whole scan compiles into one fused loop. Padded positions are zeroed in op
outputs so downstream reductions need no special casing.
"""

import numpy as np

from .registry import register_op


def _jnp():
    import jax.numpy as jnp
    return jnp


def _mask(lens, T, dtype):
    """[B] lengths -> [B, T] 0/1 mask."""
    jnp = _jnp()
    return (jnp.arange(T)[None, :] < lens[:, None]).astype(dtype)


def _expand_mask(m, ref):
    """[B, T] -> [B, T, 1, ...] broadcastable to ref."""
    jnp = _jnp()
    return m.reshape(m.shape + (1,) * (ref.ndim - 2))


def _reverse_valid(x, lens):
    """Reverse each row's VALID prefix along the time axis, leaving the
    padded tail in place (the sequence-reverse gather shared by
    sequence_reverse, the reversed lstm/gru scans, and their output
    un-reversal)."""
    jnp = _jnp()
    B, T = x.shape[0], x.shape[1]
    t = jnp.arange(T)[None, :]
    idx = jnp.where(t < lens[:, None], lens[:, None] - 1 - t, t)
    return jnp.take_along_axis(
        x, idx.reshape((B, T) + (1,) * (x.ndim - 2)).astype(jnp.int32),
        axis=1)


# ---------------------------------------------------------------------------
# pooling / steps (sequence_pool_op.cc)
# ---------------------------------------------------------------------------

@register_op("sequence_pool")
def _sequence_pool(ctx):
    jnp = _jnp()
    x = ctx.input("X")          # [B, T, ...]
    lens = ctx.lod_len("X")
    ptype = ctx.attr("pooltype", "AVERAGE").upper()
    B, T = x.shape[0], x.shape[1]
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    m = _expand_mask(_mask(lens, T, x.dtype), x)
    xm = x * m
    denom = jnp.maximum(lens.astype(x.dtype), 1.0).reshape(
        (B,) + (1,) * (x.ndim - 2))
    if ptype == "AVERAGE":
        out = jnp.sum(xm, axis=1) / denom
    elif ptype == "SUM":
        out = jnp.sum(xm, axis=1)
    elif ptype == "SQRT":
        out = jnp.sum(xm, axis=1) / jnp.sqrt(denom)
    elif ptype == "MAX":
        neg = jnp.where(m > 0, x, jnp.full_like(x, -1e30))
        out = jnp.max(neg, axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(lens - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((B, 1) + (1,) * (x.ndim - 2)).astype(jnp.int32),
            axis=1)[:, 0]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise NotImplementedError("sequence_pool type %s" % ptype)
    return {"Out": out}


@register_op("sequence_last_step")
def _sequence_last_step(ctx):
    class _C:  # reuse pool lowering with LAST
        pass
    ctx.attrs = dict(ctx.attrs)
    ctx.attrs["pooltype"] = "LAST"
    return _sequence_pool(ctx)


@register_op("sequence_first_step")
def _sequence_first_step(ctx):
    ctx.attrs = dict(ctx.attrs)
    ctx.attrs["pooltype"] = "FIRST"
    return _sequence_pool(ctx)


# ---------------------------------------------------------------------------
# masked softmax / mask / reverse / expand / concat / pad / unpad
# ---------------------------------------------------------------------------

@register_op("sequence_softmax")
def _sequence_softmax(ctx):
    import jax
    jnp = _jnp()
    x = ctx.input("X")  # [B, T] or [B, T, 1]
    lens = ctx.lod_len("X")
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    xx = x[..., 0] if squeeze else x
    B, T = xx.shape[0], xx.shape[1]
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    m = _mask(lens, T, xx.dtype)
    logits = jnp.where(m > 0, xx, jnp.full_like(xx, -1e30))
    out = jax.nn.softmax(logits, axis=1) * m
    if squeeze:
        out = out[..., None]
    return {"Out": out}


@register_op("sequence_mask")
def _sequence_mask(ctx):
    import jax
    jnp = _jnp()
    x = ctx.input("X")  # lengths tensor
    maxlen = ctx.attr("maxlen", -1)
    if maxlen is None or maxlen < 0:
        # dynamic maxlen = max(x): a data-dependent OUTPUT SHAPE. Legal
        # when x is concrete (eager/host path); under jit it is an
        # XLA-static-shape limit (reference sequence_mask_op.cc computed
        # the max on the host at kernel time).
        if isinstance(x, jax.core.Tracer):
            raise NotImplementedError(
                "sequence_mask with maxlen=-1 has a data-dependent output "
                "shape and cannot be traced under jit — pass a static "
                "maxlen, or run the program eagerly")
        maxlen = int(np.max(np.asarray(x))) if np.asarray(x).size else 0
    from ..fluid import core as fcore
    dtype = fcore.convert_dtype_to_np(ctx.attr("out_dtype",
                                               fcore.VarDesc.VarType.INT64))
    flat = x.reshape(-1)
    m = (jnp.arange(maxlen)[None, :] < flat[:, None]).astype(dtype)
    return {"Y": m.reshape(tuple(x.shape) + (maxlen,))}


@register_op("sequence_length")
def _sequence_length(ctx):
    """Per-sequence valid lengths [B] from the @LOD_LEN companion; a
    dense input (no companion) is full-width by construction."""
    jnp = _jnp()
    x = ctx.input("X")
    lens = ctx.lod_len("X")
    if lens is None:
        lens = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    return {"Out": lens.astype(jnp.int64)}


@register_op("sequence_reverse")
def _sequence_reverse(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    lens = ctx.lod_len("X")
    B, T = x.shape[0], x.shape[1]
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    return {"Y": _reverse_valid(x, lens), "Y@LOD_LEN": lens}


@register_op("sequence_expand")
def _sequence_expand(ctx):
    jnp = _jnp()
    x, y = ctx.input("X"), ctx.input("Y")
    ylens = ctx.lod_len("Y")
    if ylens is None:
        ylens = jnp.full((y.shape[0],), y.shape[1], jnp.int32)
    if x.ndim == y.ndim:
        # ragged X: each x sequence repeats per Y's ref-level lod
        # (sequence_expand_op.h). The reference's output row count is
        # data-dependent; the static-shape encoding supports the common
        # beam-style case where Y holds a STATIC integer multiple of X's
        # rows (By = Bx * k): row i of X is tiled to output rows
        # i*k..i*k+k-1, each masked to Y's per-row length.
        xlens = ctx.lod_len("X")
        Bx, By = x.shape[0], y.shape[0]
        import jax

        def conform(out, out_lens, xa_ndim):
            """Pad/trim the time axis to Y's padded width Ty so downstream
            elementwise ops against Y line up; trimming may only remove
            padding (the reference's packed layout has no width notion).
            Eagerly, a real truncation raises; under jit the check is
            data-dependent, so overflowed ROWS are poisoned with NaN
            (float dtypes) instead of silently clipped — FLAGS.
            check_nan_inf or any downstream reduction surfaces it."""
            Ty = y.shape[1]
            if Ty >= out.shape[1]:
                pad = [(0, 0), (0, Ty - out.shape[1])] + \
                    [(0, 0)] * (xa_ndim - 2)
                return jnp.pad(out, pad), out_lens
            max_len = jnp.max(out_lens) if out_lens.shape[0] else 0
            if isinstance(max_len, jax.core.Tracer):
                trimmed = out[:, :Ty]
                if jnp.issubdtype(trimmed.dtype, jnp.floating):
                    bad = (out_lens > Ty).reshape(
                        (-1,) + (1,) * (xa_ndim - 1))
                    trimmed = jnp.where(bad, jnp.nan, trimmed)
                return trimmed, jnp.minimum(out_lens, Ty)
            if int(max_len) <= Ty:
                return out[:, :Ty], jnp.minimum(out_lens, Ty)
            raise ValueError(
                "sequence_expand: Y's padded width %d cannot hold the "
                "expanded sequences (max length %d)" % (Ty, int(max_len)))

        seg = ctx.lod_seg("Y")
        concrete_seg = (seg is not None
                        and not isinstance(x, jax.core.Tracer)
                        and not isinstance(seg, jax.core.Tracer))
        if concrete_seg:
            # general per-sequence repeat counts (the reference's
            # ref_level semantics, sequence_expand_op.h:109-118): Y is
            # nested, its outer counts say how often each X sequence
            # repeats; the output keeps X's OWN inner lengths, repeated.
            # Data-dependent row count -> concrete (host/eager) only.
            counts = np.asarray(seg).astype(np.int64)
            if len(counts) != Bx or counts.sum() != By:
                raise ValueError(
                    "sequence_expand: Y's outer counts %r do not match "
                    "X's %d sequences / Y's %d rows"
                    % (counts.tolist(), Bx, By))
            xa = np.asarray(x)
            xl = (np.asarray(xlens) if xlens is not None
                  else np.full((Bx,), xa.shape[1], np.int32))
            out = jnp.asarray(np.repeat(xa, counts, axis=0))
            out_lens = jnp.asarray(np.repeat(xl, counts).astype(np.int32))
        else:
            if By % Bx != 0:
                raise NotImplementedError(
                    "sequence_expand of ragged X needs a data-dependent "
                    "output row count (an XLA-static-shape limit) unless "
                    "Y's rows are a static multiple of X's (got X rows "
                    "%d, Y rows %d) — or run on the host path with a "
                    "nested Y carrying per-group repeat counts"
                    % (Bx, By))
            # static multiple (beam-style): row i of X tiles to output
            # rows i*k..i*k+k-1, keeping X's own lengths (the reference
            # builds out_lod from x_seq_len, sequence_expand_op.h:115)
            k = By // Bx
            out = jnp.repeat(x, k, axis=0)            # [By, Tx, ...]
            out_lens = (jnp.repeat(xlens, k, axis=0) if xlens is not None
                        else jnp.full((By,), x.shape[1], jnp.int32))
        out, out_lens = conform(out, out_lens, x.ndim)
        m = _expand_mask(_mask(out_lens, out.shape[1], out.dtype), out)
        return {"Out": out * m, "Out@LOD_LEN": out_lens}
    # dense X [B, D] -> ragged [B, Ty, D] tiling each row along time
    T = y.shape[1]
    out = jnp.broadcast_to(x[:, None], (x.shape[0], T) + x.shape[1:])
    m = _expand_mask(_mask(ylens, T, x.dtype), out)
    return {"Out": out * m, "Out@LOD_LEN": ylens}


@register_op("sequence_concat")
def _sequence_concat(ctx):
    jnp = _jnp()
    xs = ctx.inputs("X")
    lens = ctx._inputs.get("X@LOD_LEN") or [None] * len(xs)
    B = xs[0].shape[0]
    lens = [l if l is not None else
            jnp.full((B,), x.shape[1], jnp.int32)
            for x, l in zip(xs, lens)]
    T_out = sum(x.shape[1] for x in xs)
    out = jnp.zeros((B, T_out) + xs[0].shape[2:], xs[0].dtype)
    total = jnp.zeros((B,), jnp.int32)
    t_idx = jnp.arange(T_out)[None, :]
    for x, l in zip(xs, lens):
        # place x's valid rows at offset `total` per batch row
        src_t = jnp.clip(t_idx - total[:, None], 0, x.shape[1] - 1)
        gathered = jnp.take_along_axis(
            x, src_t.reshape((B, T_out) + (1,) * (x.ndim - 2)).astype(
                jnp.int32), axis=1)
        in_range = (t_idx >= total[:, None]) & \
            (t_idx < (total + l)[:, None])
        out = jnp.where(
            in_range.reshape((B, T_out) + (1,) * (x.ndim - 2)),
            gathered, out)
        total = total + l
    return {"Out": out, "Out@LOD_LEN": total}


@register_op("sequence_pad")
def _sequence_pad(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    lens = ctx.lod_len("X")
    if lens is None:
        lens = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    padded_length = ctx.attr("padded_length", -1)
    pad_value = ctx.input("PadValue")
    T = x.shape[1] if padded_length in (None, -1, 0) else padded_length
    out = x[:, :T]
    if T > x.shape[1]:
        out = jnp.pad(x, ((0, 0), (0, T - x.shape[1])) +
                      ((0, 0),) * (x.ndim - 2))
    m = _expand_mask(_mask(lens, T, x.dtype), out)
    if pad_value is not None:
        out = out * m + (1 - m) * pad_value.reshape(
            (1, 1) + (1,) * (out.ndim - 2))
    return {"Out": out, "Length": lens.astype(jnp.int64)}


@register_op("sequence_unpad")
def _sequence_unpad(ctx):
    jnp = _jnp()
    x, length = ctx.input("X"), ctx.input("Length")
    lens = length.reshape(-1).astype(jnp.int32)
    m = _expand_mask(_mask(lens, x.shape[1], x.dtype), x)
    return {"Out": x * m, "Out@LOD_LEN": lens}


@register_op("sequence_enumerate")
def _sequence_enumerate(ctx):
    jnp = _jnp()
    x = ctx.input("X")  # [B, T] int ids (or [B,T,1])
    lens = ctx.lod_len("X")
    win = ctx.attr("win_size")
    pad_value = ctx.attr("pad_value", 0)
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    xx = x[..., 0] if squeeze else x
    B, T = xx.shape
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    cols = []
    for k in range(win):
        idx = jnp.arange(T) + k
        valid = idx[None, :] < lens[:, None]
        g = jnp.take(xx, jnp.clip(idx, 0, T - 1), axis=1)
        cols.append(jnp.where(valid, g, pad_value))
    out = jnp.stack(cols, axis=-1)
    m = _mask(lens, T, out.dtype)[..., None]
    return {"Out": (out * m).astype(xx.dtype), "Out@LOD_LEN": lens}


@register_op("sequence_slice")
def _sequence_slice(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    offset = ctx.input("Offset").reshape(-1).astype(jnp.int32)
    length = ctx.input("Length").reshape(-1).astype(jnp.int32)
    B, T = x.shape[0], x.shape[1]
    t = jnp.arange(T)[None, :]
    idx = jnp.clip(offset[:, None] + t, 0, T - 1)
    out = jnp.take_along_axis(
        x, idx.reshape((B, T) + (1,) * (x.ndim - 2)), axis=1)
    m = _expand_mask(_mask(length, T, x.dtype), out)
    return {"Out": out * m, "Out@LOD_LEN": length}


@register_op("sequence_erase")
def _sequence_erase(ctx):
    """sequence_erase_op.cc: drop the listed tokens from each sequence,
    compacting the survivors left. Static-shape encoding: output keeps
    the padded [B, T] extent, survivors stable-compacted to the front,
    new per-row lengths in the LoD companion."""
    jnp = _jnp()
    x = ctx.input("X")
    tokens = ctx.attr("tokens", []) or []
    lens = ctx.lod_len("X")
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    xx = x[..., 0] if squeeze else x
    B, T = xx.shape[0], xx.shape[1]
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    valid = jnp.arange(T)[None, :] < lens[:, None]
    keep = valid
    for t in tokens:
        keep = keep & (xx != t)
    new_lens = keep.sum(axis=1).astype(jnp.int32)
    # stable argsort of (not keep): kept positions first, original order
    order = jnp.argsort(jnp.logical_not(keep), axis=1, stable=True)
    out = jnp.take_along_axis(xx, order, axis=1)
    out = jnp.where(jnp.arange(T)[None, :] < new_lens[:, None], out,
                    jnp.zeros_like(out))
    if squeeze:
        out = out[..., None]
    return {"Out": out, "Out@LOD_LEN": new_lens}


@register_op("sequence_reshape")
def _sequence_reshape(ctx):
    """sequence_reshape_op.h: each sequence's flat payload (seq_len *
    in_width row-major values) re-chunks into rows of new_dim; the
    reference only requires PER-SEQUENCE divisibility of seq_len *
    in_width by new_dim (in_width itself need not divide, and a
    narrowing reshape must not swallow padding between sequences).
    Padded-dense form: gather through the flat index remap
    out[t', d'] = seq_flat[t'*new_dim + d'], masked past each
    sequence's own payload. Pinned by
    tests/test_sequence_reshape_oracle.py."""
    jnp = _jnp()
    x = ctx.input("X")  # [B, T, D]
    lens = ctx.lod_len("X")
    new_dim = int(ctx.attr("new_dim"))
    B, T, D = x.shape
    if D == new_dim:
        r = {"Out": x}
        if lens is not None:
            r["Out@LOD_LEN"] = lens
        return r
    if lens is None and (T * D) % new_dim != 0:
        # dense path: every row is one full T-step sequence, so the
        # reference's per-sequence PADDLE_ENFORCE(seq_len * in_width %
        # new_dim == 0) applies to T*D directly — refuse rather than
        # silently padding a final partial row (sequence_reshape_op.h)
        raise ValueError(
            "sequence_reshape: T*D = %d*%d = %d not divisible by "
            "new_dim %d" % (T, D, T * D, new_dim))
    # static padded output length: the longest possible re-chunked row
    # count given T timesteps of D values
    T_out = -(-(T * D) // new_dim)
    flat_idx = (jnp.arange(T_out)[:, None] * new_dim
                + jnp.arange(new_dim)[None, :])          # [T_out, new_dim]
    t_old = flat_idx // D
    d_old = flat_idx % D
    out = x[:, jnp.clip(t_old, 0, T - 1), d_old]          # [B,T_out,new_dim]
    if lens is not None:
        valid = flat_idx[None] < (lens[:, None, None] * D)
        out = jnp.where(valid, out, 0)
        new_lens = (lens * D) // new_dim
        return {"Out": out, "Out@LOD_LEN": new_lens}
    return {"Out": out}


def _seq_context_matrix(x, lens, ctx_len, ctx_start):
    """Sliding context-window stack shared by sequence_conv and the fused
    seqconv op: masked [B, T, ctx_len*D] concat of shifted rows, plus the
    validity mask [B, T]."""
    jnp = _jnp()
    B, T, D = x.shape
    m = _mask(lens, T, x.dtype)
    xm = x * m[..., None]
    shifted = []
    t = jnp.arange(T)
    for k in range(ctx_len):
        src = t + ctx_start + k
        valid = (src >= 0) & (src < T)
        g = jnp.take(xm, jnp.clip(src, 0, T - 1), axis=1)
        shifted.append(jnp.where(valid[None, :, None], g, 0))
    return jnp.concatenate(shifted, axis=-1), m


@register_op("sequence_conv")
def _sequence_conv(ctx):
    """Context-window projection (sequence_conv_op.cc): for each timestep,
    concat rows [t+start, t+start+len) and multiply by Filter
    [ctx_len*D, M] — one big MXU matmul after an unrolled shift-stack."""
    jnp = _jnp()
    x = ctx.input("X")              # [B, T, D]
    w = ctx.input("Filter")         # [ctx_len*D, M]
    lens = ctx.lod_len("X")
    ctx_len = ctx.attr("contextLength", 3)
    ctx_start = ctx.attr("contextStart", 0)
    B, T, D = x.shape
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    stacked, m = _seq_context_matrix(x, lens, ctx_len, ctx_start)
    out = jnp.einsum("btd,dm->btm", stacked, w)
    return {"Out": out * m[..., None], "Out@LOD_LEN": lens}


# ---------------------------------------------------------------------------
# recurrent ops: dynamic LSTM / GRU via lax.scan (lstm_op.cc, gru_op.cc)
# ---------------------------------------------------------------------------

def _lstm_scan(x, lens, w, bias, h0, c0, use_peepholes, is_reverse):
    import jax
    jnp = _jnp()
    B, T, H4 = x.shape
    H = H4 // 4
    # the carry must match the body's promoted dtype: under AMP x is
    # bf16 while the weights stay fp32 masters, so the gate matmul
    # promotes to fp32 — a bf16-initialized carry then trips scan's
    # carry-type check at lowering time
    cdt = jnp.result_type(x.dtype, w.dtype)
    h0 = h0.astype(cdt)
    c0 = c0.astype(cdt)
    b_gate = bias[..., :4 * H].reshape(1, 4 * H)
    if use_peepholes:
        w_ic = bias[..., 4 * H:5 * H].reshape(1, H)
        w_fc = bias[..., 5 * H:6 * H].reshape(1, H)
        w_oc = bias[..., 6 * H:7 * H].reshape(1, H)
    m = _mask(lens, T, x.dtype)  # [B, T]
    xs = jnp.swapaxes(x, 0, 1)           # [T, B, 4H]
    ms = jnp.swapaxes(m, 0, 1)[..., None]  # [T, B, 1]
    if is_reverse:
        # reverse valid region: scan over reversed-valid-order indices
        xs = jnp.swapaxes(_reverse_valid(x, lens), 0, 1)

    def step(carry, inp):
        h, c = carry
        xt, mt = inp
        gates = xt + h @ w + b_gate
        # reference gate column layout: {candidate, input, forget,
        # output} (math/detail/lstm_cpu_kernel.h:44-47; lstm_op.cc
        # Weight doc "{W_ch, W_ih, W_fh, W_oh}")
        cand, i, f, o = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            i = i + c * w_ic
            f = f + c * w_fc
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        cand = jnp.tanh(cand)
        c_new = f * c + i * cand
        if use_peepholes:
            o = o + c_new * w_oc
        o = jax.nn.sigmoid(o)
        h_new = o * jnp.tanh(c_new)
        h = mt * h_new + (1 - mt) * h
        c = mt * c_new + (1 - mt) * c
        return (h, c), (h * mt, c * mt)

    (h_fin, c_fin), (hs, cs) = jax.lax.scan(step, (h0, c0), (xs, ms))
    hidden = jnp.swapaxes(hs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    if is_reverse:
        hidden = _reverse_valid(hidden, lens)
        cell = _reverse_valid(cell, lens)
    return hidden, cell


@register_op("lstm")
def _lstm(ctx):
    jnp = _jnp()
    x = ctx.input("Input")       # [B, T, 4H] (pre-projected, like reference)
    w = ctx.input("Weight")      # [H, 4H]
    bias = ctx.input("Bias")     # [1, 4H] or [1, 7H] with peepholes
    lens = ctx.lod_len("Input")
    B, T = x.shape[0], x.shape[1]
    H = x.shape[2] // 4
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    h0 = ctx.input("H0")
    c0 = ctx.input("C0")
    if h0 is None:
        h0 = jnp.zeros((B, H), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, H), x.dtype)
    use_peepholes = ctx.attr("use_peepholes", True) and \
        bias.shape[-1] == 7 * H
    hidden, cell = _lstm_scan(x, lens, w, bias, h0, c0, use_peepholes,
                              ctx.attr("is_reverse", False))
    return {"Hidden": hidden, "Cell": cell,
            "Hidden@LOD_LEN": lens, "Cell@LOD_LEN": lens}


def _gru_scan(x, lens, w, h0, is_reverse):
    """Shared GRU recurrence over pre-projected (+bias) gates x [B,T,3H]
    (fluid gate layout: update u, reset r, then candidate)."""
    import jax
    jnp = _jnp()
    H = x.shape[2] // 3
    T = x.shape[1]
    # same carry-dtype pinning as _lstm_scan: AMP keeps weights fp32
    h0 = h0.astype(jnp.result_type(x.dtype, w.dtype))
    if is_reverse:
        x = _reverse_valid(x, lens)
    m = _mask(lens, T, x.dtype)
    xs = jnp.swapaxes(x, 0, 1)
    ms = jnp.swapaxes(m, 0, 1)[..., None]
    w_rz = w[:, :2 * H]
    w_c = w[:, 2 * H:]

    def step(h, inp):
        xt, mt = inp
        xrz, xc = xt[:, :2 * H], xt[:, 2 * H:]
        rz = jax.nn.sigmoid(xrz + h @ w_rz)
        u, r = jnp.split(rz, 2, axis=-1)
        cand = jnp.tanh(xc + (r * h) @ w_c)
        # reference: out = prev - u*prev + u*cand
        # (math/detail/gru_kernel.h:62-63)
        h_new = (1 - u) * h + u * cand
        h = mt * h_new + (1 - mt) * h
        return h, h * mt

    _, hs = jax.lax.scan(step, h0, (xs, ms))
    hidden = jnp.swapaxes(hs, 0, 1)
    if is_reverse:
        hidden = _reverse_valid(hidden, lens)
    return hidden


@register_op("gru")
def _gru(ctx):
    jnp = _jnp()
    x = ctx.input("Input")     # [B, T, 3H]
    w = ctx.input("Weight")    # [H, 3H]: [:, :2H] update/reset, [:, 2H:] cand
    bias = ctx.input("Bias")   # [1, 3H]
    lens = ctx.lod_len("Input")
    B, T = x.shape[0], x.shape[1]
    H = x.shape[2] // 3
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    h0 = ctx.input("H0")
    if h0 is None:
        h0 = jnp.zeros((B, H), x.dtype)
    if bias is not None:
        x = x + bias.reshape(1, 1, 3 * H)
    hidden = _gru_scan(x, lens, w, h0, ctx.attr("is_reverse", False))
    return {"Hidden": hidden, "Hidden@LOD_LEN": lens,
            "BatchGate": x, "BatchResetHiddenPrev": hidden,
            "BatchHidden": hidden}


@register_op("nested_to_outer")
def _nested_to_outer(ctx):
    """Re-batch a nested var for OUTER-level iteration: inner sequences
    [N, T, ...] grouped by counts [B_outer] become [B_outer, S_max, T,
    ...] (zero-padded slots) with an inner-length matrix [B_outer,
    S_max]; both carry counts as their outer @LOD_LEN so a DynamicRNN
    over them iterates sub-sequences (SubsequenceInput). S_max is
    data-dependent -> host path."""
    import jax
    x = ctx.input("X")
    lens = ctx.lod_len("X")
    counts = ctx.lod_seg("X")
    if counts is None:
        raise ValueError("nested_to_outer needs a nested (lod_level-2) "
                         "input")
    if isinstance(x, jax.core.Tracer):
        raise NotImplementedError(
            "nested_to_outer has a data-dependent sub-sequence capacity "
            "— runs on the host path")
    x = np.asarray(x)
    counts = np.asarray(counts)
    lens = np.asarray(lens) if lens is not None else \
        np.full((x.shape[0],), x.shape[1], np.int32)
    B = len(counts)
    S = int(counts.max()) if B else 0
    out = np.zeros((B, S) + x.shape[1:], x.dtype)
    lmat = np.zeros((B, S), np.int32)
    start = 0
    for g in range(B):
        c = int(counts[g])
        out[g, :c] = x[start:start + c]
        lmat[g, :c] = lens[start:start + c]
        start += c
    return {"Out": out, "Out@LOD_LEN": counts.astype(np.int32),
            "OutLens": lmat, "OutLens@LOD_LEN": counts.astype(np.int32)}


@register_op("nested_to_outer_grad")
def _nested_to_outer_grad(ctx):
    """Explicit host-side gradient of nested_to_outer (the forward's
    numpy re-batching is not vjp-traceable): unpack the outer-major
    cotangent [B_outer, S_max, T, ...] back to inner rows [N, T, ...]."""
    d_out = ctx.input("GRAD:Out")
    counts = ctx.lod_seg("X")
    x = ctx.input("X")
    counts = np.asarray(counts)
    d_out = np.asarray(d_out)
    parts = [d_out[g, :int(c)] for g, c in enumerate(counts)]
    dx = (np.concatenate(parts, axis=0) if parts
          else np.zeros_like(np.asarray(x)))
    return {"GRAD:X": dx}


@register_op("attach_lod")
def _attach_lod(ctx):
    """Out = X with Lens attached as its @LOD_LEN companion — turns a
    dense per-step slice back into a ragged var inside a recurrent
    sub-block (the inner-sequence view of a SubsequenceInput step)."""
    jnp = _jnp()
    x = ctx.input("X")
    lens = ctx.input("Lens")
    return {"Out": x, "Out@LOD_LEN": lens.astype(jnp.int32)}


@register_op("kmax_seq_score")
def _kmax_seq_score(ctx):
    """Indices of the beam_size highest scores within each sequence's
    VALID prefix (reference legacy KmaxSeqScoreLayer) — padded positions
    are masked out before the top-k. For a NESTED input (a score per
    inner sequence), returns each outer group's top-k inner-sequence
    indices, local to the group (feeds sub_nested_seq)."""
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    if x.ndim == 3:
        x = x[..., 0]
    lens = ctx.lod_len("X")
    seg = ctx.lod_seg("X")
    k = int(ctx.attr("beam_size", 1))
    if seg is not None:
        # score of inner sequence i = its first element; rank inner
        # sequences within each outer group. Group count is
        # data-dependent -> host/eager evaluation (the reference layer
        # is CPU-only too).
        if isinstance(x, jax.core.Tracer) or \
                isinstance(seg, jax.core.Tracer):
            raise NotImplementedError(
                "nested kmax_seq_score has a data-dependent group count "
                "— run the program eagerly (reference "
                "KmaxSeqScoreLayer is host-side as well)")
        scores = np.asarray(x)[:, 0]
        counts = np.asarray(seg)          # [B_outer] inner-seq counts
        n_groups = len(counts)
        # unfilled slots pad with -1 (reference KmaxSeqScoreLayer);
        # sub_nested_seq skips negatives
        out = np.full((n_groups, k), -1, np.int64)
        start = 0
        for g in range(n_groups):
            local = scores[start:start + int(counts[g])]
            order = np.argsort(-local)[:k]
            out[g, :len(order)] = order
            start += int(counts[g])
        return {"Out": out}
    B, T = x.shape
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    valid = jnp.arange(T)[None, :] < lens[:, None]
    masked = jnp.where(valid, x, -jnp.inf)
    # reference KmaxSeqScoreLayer: output is ALWAYS [B, beam_size],
    # pre-filled with -1; only min(beam_size, seq_len) slots per row
    # hold real indices (consumers like sub_nested_seq skip negatives)
    idx = jnp.argsort(-masked, axis=1)[:, :k]     # [B, min(k, T)]
    slot = jnp.arange(idx.shape[1])[None, :]
    idx = jnp.where(slot < lens[:, None], idx, -1)
    if idx.shape[1] < k:
        idx = jnp.concatenate(
            [idx, jnp.full((B, k - idx.shape[1]), -1, idx.dtype)], axis=1)
    return {"Out": idx.astype(jnp.int64)}


@register_op("sub_nested_seq")
def _sub_nested_seq(ctx):
    """Select per-outer-group inner sequences of a nested LoD input by
    LOCAL indices [B_outer, K] (reference SubNestedSequenceLayer paired
    with kmax_seq_score). Output is a level-1 ragged var of B_outer*K
    inner sequences. Group starts are data-dependent -> host/eager."""
    import jax
    jnp = _jnp()
    x = ctx.input("X")              # [N, T, ...] padded inner seqs
    idx = ctx.input("Indices")      # [B_outer, K] local indices
    lens = ctx.lod_len("X")
    seg = ctx.lod_seg("X")
    if seg is None:
        raise ValueError("sub_nested_seq needs a nested (lod_level-2) "
                         "input — got a single-level sequence")
    if isinstance(x, jax.core.Tracer) or isinstance(seg, jax.core.Tracer):
        raise NotImplementedError(
            "sub_nested_seq selects data-dependent rows — run the "
            "program eagerly (the reference layer is host-side too)")
    x = np.asarray(x)
    idx = np.asarray(idx).astype(np.int64)
    counts = np.asarray(seg)              # [B_outer] inner-seq counts
    lens = np.asarray(lens) if lens is not None else \
        np.full((x.shape[0],), x.shape[1], np.int32)
    if len(idx) != len(counts):
        raise ValueError(
            "sub_nested_seq: Indices rows (%d) != outer groups (%d)"
            % (len(idx), len(counts)))
    starts = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    rows, out_counts = [], []
    for g in range(len(idx)):
        picked = [int(i) for i in idx[g] if i >= 0]   # -1 = unfilled
        bad = [i for i in picked if i >= int(counts[g])]
        if bad:
            raise ValueError(
                "sub_nested_seq: index %d out of range for outer group "
                "%d with %d inner sequences" % (bad[0], g,
                                                int(counts[g])))
        rows += [starts[g] + i for i in picked]
        out_counts.append(len(picked))
    rows = np.asarray(rows, np.int64)
    out = x[rows]
    out_lens = lens[rows].astype(np.int32)
    return {"Out": out, "Out@LOD_LEN": out_lens,
            "Out@LOD_SEG": np.asarray(out_counts, np.int32)}


@register_op("simple_rnn")
def _simple_rnn(ctx):
    """Elman recurrence h_t = act(x_t + h_{t-1} @ W) over a pre-projected
    sequence (reference legacy RecurrentLayer — the v1 recurrent_layer
    contract: input already carries the x @ U projection)."""
    import jax
    jnp = _jnp()
    x = ctx.input("Input")      # [B, T, H]
    w = ctx.input("Weight")     # [H, H]
    bias = ctx.input("Bias")
    lens = ctx.lod_len("Input")
    B, T, H = x.shape
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    if bias is not None:
        x = x + bias.reshape(1, 1, H)
    acts = {"tanh": jnp.tanh, "relu": jax.nn.relu,
            "sigmoid": jax.nn.sigmoid, "identity": lambda v: v,
            "abs": jnp.abs, "square": jnp.square, "exp": jnp.exp,
            "softsign": jax.nn.soft_sign}
    name = ctx.attr("activation", "tanh")
    if name not in acts:
        raise NotImplementedError(
            "simple_rnn activation %r (supported: %s)"
            % (name, sorted(acts)))
    act = acts[name]
    reverse = bool(ctx.attr("is_reverse", False))
    xs = _reverse_valid(x, lens) if reverse else x

    def step(h_prev, xt_t):
        xt, t = xt_t
        h = act(xt + h_prev @ w)
        valid = (t < lens)[:, None]
        h = jnp.where(valid, h, 0.0)
        return h, h

    # carry pinned to the body's promoted dtype (AMP: bf16 x, fp32 w)
    _, hs = jax.lax.scan(step,
                         jnp.zeros((B, H),
                                   jnp.result_type(x.dtype, w.dtype)),
                         (jnp.swapaxes(xs, 0, 1), jnp.arange(T)))
    out = jnp.swapaxes(hs, 0, 1)
    if reverse:
        out = _reverse_valid(out, lens)
    return {"Out": out, "Out@LOD_LEN": lens}


@register_op("lstm_unit")
def _lstm_unit(ctx):
    import jax
    jnp = _jnp()
    x = ctx.input("X")          # [B, 4H]
    c_prev = ctx.input("C_prev")
    forget_bias = ctx.attr("forget_bias", 0.0)
    # reference chunk order: {i, f, o, g} (lstm_unit_op.h:63-66)
    i, f, o, cand = jnp.split(x, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    cand = jnp.tanh(cand)
    o = jax.nn.sigmoid(o)
    c = f * c_prev + i * cand
    h = o * jnp.tanh(c)
    return {"C": c, "H": h}


# ---------------------------------------------------------------------------
# build-time shape inference on the packed (rank-2) convention: at build
# time ragged vars keep the reference's [total_rows, D] shapes while runtime
# values are padded [B, T, D] — eval_shape can't bridge that, so these ops
# get explicit InferShape functions (the one place the reference's per-op
# InferShape survives).
# ---------------------------------------------------------------------------

def _set_out(block, op, slot, shape, dtype=None):
    names = op.outputs.get(slot, [])
    for n in names:
        v = block._find_var_recursive(n)
        if v is not None:
            v.shape = tuple(shape)
            if dtype is not None:
                from ..fluid import core as fcore
                v.dtype = fcore.convert_np_dtype_to_dtype_(dtype)


def _in_shape(block, op, slot):
    names = op.inputs.get(slot, [])
    if not names:
        return None
    v = block._find_var_recursive(names[0])
    return None if v is None or v.shape is None else tuple(v.shape)


def _infer_lstm(op, block):
    s = _in_shape(block, op, "Input")
    if s:
        H = s[-1] // 4
        _set_out(block, op, "Hidden", (-1, H))
        _set_out(block, op, "Cell", (-1, H))


def _infer_gru(op, block):
    s = _in_shape(block, op, "Input")
    if s:
        H = s[-1] // 3
        _set_out(block, op, "Hidden", (-1, H))


def _infer_same(slot_in, slot_out):
    def fn(op, block):
        s = _in_shape(block, op, slot_in)
        if s:
            _set_out(block, op, slot_out, s)
    return fn


def _infer_seq_conv(op, block):
    s = _in_shape(block, op, "X")
    w = _in_shape(block, op, "Filter")
    if s and w:
        _set_out(block, op, "Out", tuple(s[:-1]) + (w[1],))


def _infer_seq_expand(op, block):
    s = _in_shape(block, op, "X")
    if s:
        _set_out(block, op, "Out", (-1,) + tuple(s[1:]))


def _infer_seq_mask(op, block):
    s = _in_shape(block, op, "X")
    if s:
        maxlen = op.attrs.get("maxlen", -1)
        _set_out(block, op, "Y", tuple(s) + (maxlen,))


from .registry import _REGISTRY as _R  # noqa: E402

_R["lstm"].custom_infer_shape = _infer_lstm
_R["gru"].custom_infer_shape = _infer_gru
_R["sequence_pool"].custom_infer_shape = _infer_same("X", "Out")
_R["sequence_first_step"].custom_infer_shape = _infer_same("X", "Out")
_R["sequence_last_step"].custom_infer_shape = _infer_same("X", "Out")
_R["sequence_softmax"].custom_infer_shape = _infer_same("X", "Out")
_R["sequence_reverse"].custom_infer_shape = _infer_same("X", "Y")
_R["sequence_conv"].custom_infer_shape = _infer_seq_conv
_R["sequence_expand"].custom_infer_shape = _infer_seq_expand
_R["sequence_mask"].custom_infer_shape = _infer_seq_mask
_R["sequence_pad"].custom_infer_shape = _infer_same("X", "Out")
_R["sequence_unpad"].custom_infer_shape = _infer_same("X", "Out")
_R["sequence_concat"].custom_infer_shape = _infer_same("X", "Out")
_R["sequence_slice"].custom_infer_shape = _infer_same("X", "Out")
_R["sequence_enumerate"].custom_infer_shape = _infer_same("X", "Out")
_R["sequence_reshape"].custom_infer_shape = _infer_same("X", "Out")


@register_op("gru_unit")
def _gru_unit(ctx):
    import jax
    jnp = _jnp()
    x = ctx.input("Input")          # [B, 3H]
    h_prev = ctx.input("HiddenPrev")
    w = ctx.input("Weight")         # [H, 3H]
    bias = ctx.input("Bias")
    H = h_prev.shape[-1]
    if bias is not None:
        x = x + bias.reshape(1, -1)
    acts = {"tanh": jnp.tanh, "relu": jax.nn.relu,
            "sigmoid": jax.nn.sigmoid, "identity": lambda v: v}
    act = acts[ctx.attr("activation", "tanh")]
    gate_act = acts[ctx.attr("gate_activation", "sigmoid")]
    xrz, xc = x[:, :2 * H], x[:, 2 * H:]
    rz = gate_act(xrz + h_prev @ w[:, :2 * H])
    u, r = jnp.split(rz, 2, axis=-1)
    cand = act(xc + (r * h_prev) @ w[:, 2 * H:])
    # reference: h = u*(c - h_prev) + h_prev (gru_unit_op.h:116)
    h = (1 - u) * h_prev + u * cand
    # Gate is the full [B, 3H] {u, r, c} block after activations
    # (gru_unit_op.h:99-113 activates all three slices in place)
    gate = jnp.concatenate([rz, cand], axis=-1)
    return {"Hidden": h, "Gate": gate, "ResetHiddenPrev": r * h_prev}


# ---------------------------------------------------------------------------
# LSTMP (lstmp_op.cc): LSTM with a recurrent projection layer — the
# recurrence runs on the projection r (dim P), gates on the hidden (dim D)
# ---------------------------------------------------------------------------

@register_op("lstmp")
def _lstmp(ctx):
    import jax
    jnp = _jnp()
    x = ctx.input("Input")          # [B, T, 4D] pre-projected gates from x
    w = ctx.input("Weight")         # [P, 4D] recurrent projection->gates
    w_proj = ctx.input("ProjWeight")  # [D, P]
    bias = ctx.input("Bias")        # [1, 4D] (+3D peephole)
    lens = ctx.lod_len("Input")
    B, T = x.shape[0], x.shape[1]
    D = x.shape[2] // 4
    P = w_proj.shape[1]
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    h0 = ctx.input("H0")            # ordered projection init [B, P]
    c0 = ctx.input("C0")
    if h0 is None:
        h0 = jnp.zeros((B, P), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, D), x.dtype)
    # carry pinned to the body's promoted dtype (AMP: bf16 x, fp32 w)
    cdt = jnp.result_type(x.dtype, w.dtype)
    h0, c0 = h0.astype(cdt), c0.astype(cdt)
    use_peepholes = ctx.attr("use_peepholes", True) and \
        bias.shape[-1] == 7 * D
    b_gate = bias[..., :4 * D].reshape(1, 4 * D)
    if use_peepholes:
        w_ic = bias[..., 4 * D:5 * D].reshape(1, D)
        w_fc = bias[..., 5 * D:6 * D].reshape(1, D)
        w_oc = bias[..., 6 * D:7 * D].reshape(1, D)
    proj_act = ctx.attr("proj_activation", "tanh")

    def proj_fn(v):
        return jnp.tanh(v) if proj_act == "tanh" else (
            jax.nn.sigmoid(v) if proj_act == "sigmoid" else v)

    is_reverse = ctx.attr("is_reverse", False)
    if is_reverse:
        x = _reverse_valid(x, lens)
    m = _mask(lens, T, x.dtype)
    xs = jnp.swapaxes(x, 0, 1)
    ms = jnp.swapaxes(m, 0, 1)[..., None]

    def step(carry, inp):
        r, c = carry               # projection [B, P], cell [B, D]
        xt, mt = inp
        gates = xt + r @ w + b_gate
        # same {c, i, f, o} gate columns as lstm (lstmp_op.h reuses the
        # lstm math functors)
        cand, i, f, o = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            i = i + c * w_ic
            f = f + c * w_fc
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        cand = jnp.tanh(cand)
        c_new = f * c + i * cand
        if use_peepholes:
            o = o + c_new * w_oc
        o = jax.nn.sigmoid(o)
        h_new = o * jnp.tanh(c_new)
        r_new = proj_fn(h_new @ w_proj)
        r2 = mt * r_new + (1 - mt) * r
        c2 = mt * c_new + (1 - mt) * c
        return (r2, c2), (r2 * mt, c2 * mt)

    (_, _), (rs, cs) = jax.lax.scan(step, (h0, c0), (xs, ms))
    proj = jnp.swapaxes(rs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    if is_reverse:
        proj = _reverse_valid(proj, lens)
        cell = _reverse_valid(cell, lens)
    return {"Projection": proj, "Cell": cell,
            "Projection@LOD_LEN": lens, "Cell@LOD_LEN": lens}


# ---------------------------------------------------------------------------
# fused RNNs (fused/fusion_lstm_op.cc, fused/fusion_gru_op.cc): the
# reference fuses the x-projection GEMM into the recurrence for CPU speed;
# under XLA the same effect falls out of jit fusion, so these lowerings
# simply do xx = x @ WeightX (+ bias) and reuse the scan cells.
# ---------------------------------------------------------------------------

@register_op("fusion_lstm")
def _fusion_lstm(ctx):
    jnp = _jnp()
    x = ctx.input("X")              # [B, T, M]
    wx = ctx.input("WeightX")       # [M, 4D]
    wh = ctx.input("WeightH")       # [D, 4D]
    bias = ctx.input("Bias")        # [1, 4D] (+3D peephole)
    lens = ctx.lod_len("X")
    B, T = x.shape[0], x.shape[1]
    D = wh.shape[0]
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    h0 = ctx.input("H0")
    c0 = ctx.input("C0")
    if h0 is None:
        h0 = jnp.zeros((B, D), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, D), x.dtype)
    xx = jnp.einsum("btm,mh->bth", x, wx)
    bias_x = ctx.input("BiasX")
    if bias_x is not None:
        # fc_lstm_fuse: the folded fc's bias applies to the x-projection
        xx = xx + bias_x.reshape(1, 1, -1)
    use_peepholes = ctx.attr("use_peepholes", True) and \
        bias.shape[-1] == 7 * D
    hidden, cell = _lstm_scan(xx, lens, wh, bias, h0, c0, use_peepholes,
                              ctx.attr("is_reverse", False))
    return {"Hidden": hidden, "Cell": cell, "XX": xx,
            "Hidden@LOD_LEN": lens, "Cell@LOD_LEN": lens}


@register_op("fusion_gru")
def _fusion_gru(ctx):
    import jax
    jnp = _jnp()
    x = ctx.input("X")              # [B, T, M]
    wx = ctx.input("WeightX")       # [M, 3D]
    wh = ctx.input("WeightH")       # [D, 3D]
    bias = ctx.input("Bias")        # [1, 3D]
    lens = ctx.lod_len("X")
    B, T = x.shape[0], x.shape[1]
    D = wh.shape[0]
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    h0 = ctx.input("H0")
    if h0 is None:
        h0 = jnp.zeros((B, D), x.dtype)
    xx = jnp.einsum("btm,mh->bth", x, wx)
    if bias is not None:
        xx = xx + bias.reshape(1, 1, 3 * D)
    hidden = _gru_scan(xx, lens, wh, h0, ctx.attr("is_reverse", False))
    return {"Hidden": hidden, "XX": xx, "Hidden@LOD_LEN": lens}


# ---------------------------------------------------------------------------
# attention LSTM (fused/attention_lstm_op.cc): per step, attend over the
# whole input sequence with the previous cell state, pool an lstm input,
# then a standard [x; h] LSTM step
# ---------------------------------------------------------------------------

@register_op("attention_lstm")
def _attention_lstm(ctx):
    import jax
    jnp = _jnp()
    x = ctx.input("X")              # [B, T, M]
    c0 = ctx.input("C0")            # [B, D]
    h0 = ctx.input("H0")
    att_w = ctx.input("AttentionWeight")      # [M+D, 1]
    att_b = ctx.input("AttentionBias")        # [1, 1] or None
    att_scalar = ctx.input("AttentionScalar")       # [1, 1] or None
    att_scalar_b = ctx.input("AttentionScalarBias")
    lstm_w = ctx.input("LSTMWeight")          # [D+M, 4D]
    lstm_b = ctx.input("LSTMBias")            # [1, 4D]
    lens = ctx.lod_len("X")
    B, T, M = x.shape
    D = c0.shape[1]
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    if h0 is None:
        h0 = jnp.zeros((B, D), x.dtype)
    # carry pinned to the body's promoted dtype (AMP: bf16 x, fp32 w)
    cdt = jnp.result_type(x.dtype, lstm_w.dtype)
    h0, c0 = h0.astype(cdt), c0.astype(cdt)
    valid = _mask(lens, T, x.dtype)           # [B, T]
    w_x, w_h = att_w[:M], att_w[M:]           # [M,1], [D,1]
    lw_x, lw_h = lstm_w[D:], lstm_w[:D]       # gates = [h; x] @ W
    # x's attention fc contribution is step-invariant: precompute
    att_x = jnp.einsum("btm,mo->bto", x, w_x)[..., 0]   # [B, T]

    def step(carry, t_idx):
        h, c = carry
        score = att_x + (c @ w_h)[..., 0][:, None]       # [B, T]
        if att_b is not None:
            score = score + att_b.reshape(())
        score = jax.nn.relu(score)
        if att_scalar is not None:
            score = score * att_scalar.reshape(())
        if att_scalar_b is not None:
            score = score + att_scalar_b.reshape(())
        score = jax.nn.relu(score)
        score = jnp.where(valid > 0, score, -1e30)
        alpha = jax.nn.softmax(score, axis=1) * valid    # [B, T]
        lstm_x = jnp.einsum("bt,btm->bm", alpha, x)      # [B, M]
        gates = h @ lw_h + lstm_x @ lw_x + lstm_b.reshape(1, -1)
        # reference weight layout: {W_forget, W_input, W_output, W_cell}
        # (attention_lstm_op.cc:166, kernel :382-397)
        f, i, o, cand = jnp.split(gates, 4, axis=-1)
        # reference attention_lstm uses sigmoid gates + tanh cand (the
        # fused kernel's default act_gate/act_cell/act_cand)
        i, f, o = (jax.nn.sigmoid(i), jax.nn.sigmoid(f),
                   jax.nn.sigmoid(o))
        c_new = f * c + i * jnp.tanh(cand)
        h_new = o * jnp.tanh(c_new)
        mt = (t_idx < lens).astype(x.dtype)[:, None]
        h2 = mt * h_new + (1 - mt) * h
        c2 = mt * c_new + (1 - mt) * c
        return (h2, c2), (h2 * mt, c2 * mt)

    (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), jnp.arange(T))
    hidden = jnp.swapaxes(hs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    return {"Hidden": hidden, "Cell": cell,
            "Hidden@LOD_LEN": lens, "Cell@LOD_LEN": lens}


def _infer_lstmp(op, block):
    s = _in_shape(block, op, "Input")
    pw = _in_shape(block, op, "ProjWeight")
    if s and pw:
        _set_out(block, op, "Projection", (-1, pw[1]))
        _set_out(block, op, "Cell", (-1, s[-1] // 4))


def _infer_fusion_lstm(op, block):
    wh = _in_shape(block, op, "WeightH")
    if wh:
        _set_out(block, op, "Hidden", (-1, wh[0]))
        _set_out(block, op, "Cell", (-1, wh[0]))


def _infer_fusion_gru(op, block):
    wh = _in_shape(block, op, "WeightH")
    if wh:
        _set_out(block, op, "Hidden", (-1, wh[0]))


def _infer_attention_lstm(op, block):
    c0 = _in_shape(block, op, "C0")
    if c0:
        _set_out(block, op, "Hidden", (-1, c0[-1]))
        _set_out(block, op, "Cell", (-1, c0[-1]))


_R["lstmp"].custom_infer_shape = _infer_lstmp
_R["fusion_lstm"].custom_infer_shape = _infer_fusion_lstm
_R["fusion_gru"].custom_infer_shape = _infer_fusion_gru
_R["attention_lstm"].custom_infer_shape = _infer_attention_lstm


# ---------------------------------------------------------------------------
# remaining fused/ family (reference operators/fused/): composite lowerings —
# one traced function each, fully fusable by XLA
# ---------------------------------------------------------------------------

@register_op("fusion_seqconv_eltadd_relu")
def _fusion_seqconv_eltadd_relu(ctx):
    """fused/fusion_seqconv_eltadd_relu_op.cc: sequence_conv + bias add +
    relu in one op."""
    jnp = _jnp()
    x = ctx.input("X")              # [B, T, D]
    w = ctx.input("Filter")         # [ctx_len*D, M]
    bias = ctx.input("Bias")        # [1, M]
    lens = ctx.lod_len("X")
    ctx_len = ctx.attr("contextLength", 3)
    ctx_start = ctx.attr("contextStart", 0)
    B, T, D = x.shape
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    col, m = _seq_context_matrix(x, lens, ctx_len, ctx_start)
    out = jnp.einsum("btd,dm->btm", col, w) + bias.reshape(1, 1, -1)
    out = jnp.maximum(out, 0) * m[..., None]
    return {"Out": out, "ColMat": col, "Out@LOD_LEN": lens}


@register_op("fusion_seqexpand_concat_fc")
def _fusion_seqexpand_concat_fc(ctx):
    """fused/fusion_seqexpand_concat_fc_op.cc: X[0] is the ragged sequence;
    X[1:] are one-row-per-sequence tensors broadcast (seq_expand) across
    timesteps; concat features -> fc -> activation."""
    import jax
    jnp = _jnp()
    xs = ctx.inputs("X")
    w = ctx.input("FCWeight")       # [sum(D_i), M]
    bias = ctx.input("FCBias")      # [1, M] or None
    lens = ctx.lod_lens("X")[0]
    seq = xs[0]                     # [B, T, D0]
    B, T = seq.shape[0], seq.shape[1]
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    feats = [seq]
    for extra in xs[1:]:            # [B, D_i] -> [B, T, D_i]
        feats.append(jnp.broadcast_to(
            extra[:, None, :], (B, T, extra.shape[-1])))
    cat = jnp.concatenate(feats, axis=-1)
    out = jnp.einsum("btd,dm->btm", cat, w)
    if bias is not None:
        out = out + bias.reshape(1, 1, -1)
    act = ctx.attr("fc_activation", "identity")
    if act == "relu":
        out = jnp.maximum(out, 0)
    elif act == "tanh":
        out = jnp.tanh(out)
    elif act == "sigmoid":
        out = jax.nn.sigmoid(out)
    m = _mask(lens, T, out.dtype)
    out = out * m[..., None]
    return {"Out": out, "FCOut": out, "Out@LOD_LEN": lens}


@register_op("fused_embedding_fc_lstm")
def _fused_embedding_fc_lstm(ctx):
    """fused/fused_embedding_fc_lstm_op.cc: the fc of fusion_lstm is
    pre-folded into the embedding table (Embeddings[V, 4D] = emb @ Wx,
    + fc bias folded by the pass), so the x-projection is one gather."""
    jnp = _jnp()
    ids = ctx.input("Ids")          # [B, T, 1] int
    emb = ctx.input("Embeddings")   # [V, 4D]
    wh = ctx.input("WeightH")       # [D, 4D]
    bias = ctx.input("Bias")        # [1, 4D] (+3D peephole)
    lens = ctx.lod_len("Ids")
    idx = ids.reshape(ids.shape[0], ids.shape[1]).astype("int32")
    B, T = idx.shape
    D = wh.shape[0]
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    xx = jnp.take(emb, jnp.clip(idx, 0, emb.shape[0] - 1), axis=0)
    h0, c0 = ctx.input("H0"), ctx.input("C0")
    if h0 is None:
        h0 = jnp.zeros((B, D), xx.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, D), xx.dtype)
    use_peepholes = ctx.attr("use_peepholes", True) and \
        bias.shape[-1] == 7 * D
    hidden, cell = _lstm_scan(xx, lens, wh, bias, h0, c0, use_peepholes,
                              ctx.attr("is_reverse", False))
    return {"Hidden": hidden, "Cell": cell, "XX": xx,
            "Hidden@LOD_LEN": lens, "Cell@LOD_LEN": lens}


def _infer_fused_emb_fc_lstm(op, block):
    wh = _in_shape(block, op, "WeightH")
    if wh:
        _set_out(block, op, "Hidden", (-1, wh[0]))
        _set_out(block, op, "Cell", (-1, wh[0]))


_R["fused_embedding_fc_lstm"].custom_infer_shape = _infer_fused_emb_fc_lstm
