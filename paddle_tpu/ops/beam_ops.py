"""Beam search ops.

Reference analogues: paddle/fluid/operators/beam_search_op.cc (per-source
top-k over candidate expansions with end_id pruning) and
beam_search_decode_op.cc (backtracking the saved per-step ids/parents into
full hypotheses).

TPU-first redesign: the reference keeps a *variable* number of live beams
per source encoded in LoD and shrinks finished beams out of the tensor; XLA
needs static shapes, so here every source keeps exactly `beam_size` rows at
all times. Finished beams (pre_id == end_id) contribute one candidate — the
end token carrying their frozen score — so they survive selection unchanged
while unfinished beams expand K candidates each. Inactive slots are seeded
with -inf scores by the caller at step 0 (see layers/beam_search). Decoding
is a reverse lax.scan over the stacked parent pointers instead of the
reference's per-sentence pointer chase.
"""

import numpy as np

from .registry import register_op

_NEG_INF = -1e9


def _jnp():
    import jax.numpy as jnp
    return jnp


@register_op("beam_search")
def _beam_search(ctx):
    """pre_ids/pre_scores [B*W, 1]; ids [B*W, K] (optional — defaults to
    0..K-1), scores [B*W, K] log-probs (accumulated if is_accumulated).
    Outputs selected_ids/selected_scores [B*W, 1] and parent_idx [B*W]
    (global row index of each selected beam's parent)."""
    jnp = _jnp()
    pre_ids = ctx.input("pre_ids")
    pre_scores = ctx.input("pre_scores")
    scores = ctx.input("scores")
    ids = ctx.input("ids")
    W = ctx.attr("beam_size")
    end_id = ctx.attr("end_id")
    rows, K = scores.shape
    B = rows // W
    if ids is None:
        ids = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int64)[None, :],
                               (rows, K))
    ids = ids.astype(jnp.int32)
    pre_ids_f = pre_ids.reshape(-1).astype(jnp.int32)
    pre_scores_f = pre_scores.reshape(-1).astype(jnp.float32)
    scores = scores.astype(jnp.float32)

    if not ctx.attr("is_accumulated", True):
        scores = jnp.log(jnp.maximum(scores, 1e-20)) + pre_scores_f[:, None]

    finished = pre_ids_f == end_id
    # unfinished beams expand K candidates; finished beams contribute one
    # frozen candidate (the end token at the parent's score)
    cand_scores = jnp.where(finished[:, None], _NEG_INF, scores)
    frozen = jnp.where(finished, pre_scores_f, _NEG_INF)[:, None]
    all_scores = jnp.concatenate([cand_scores, frozen], axis=1)  # [BW, K+1]
    all_ids = jnp.concatenate(
        [ids, jnp.full((rows, 1), end_id, jnp.int32)], axis=1)

    flat = all_scores.reshape(B, W * (K + 1))
    top_scores, top_idx = _topk(flat, W)
    parent_beam = top_idx // (K + 1)                    # [B, W]
    parent_row = (jnp.arange(B)[:, None] * W + parent_beam)  # [B, W] global
    sel_ids = jnp.take_along_axis(
        all_ids.reshape(B, W * (K + 1)), top_idx, axis=1)
    return {"selected_ids": sel_ids.reshape(-1, 1).astype(jnp.int64),
            "selected_scores": top_scores.reshape(-1, 1),
            "parent_idx": parent_row.reshape(-1).astype(jnp.int32)}


def _topk(x, k):
    import jax
    return jax.lax.top_k(x, k)


@register_op("beam_search_decode")
def _beam_search_decode(ctx):
    """Ids/ParentIdx stacked [T, B*W] (+ Scores [T, B*W]): backtrack parent
    pointers from the last step to reconstruct each surviving beam's token
    sequence. Outputs SentenceIds [B*W, T] (+lens up to and including
    end_id) and SentenceScores [B*W, 1] (final accumulated score)."""
    import jax
    jnp = _jnp()

    def stack_array(entries):
        """TensorArray input (custom-block decoders write a python list):
        stack per-step rows to [T, BW], beam-expanding any entry with
        fewer rows (the init row is [B, ...] while selections are
        [B*W, ...] — each source row repeats across its beam slots)."""
        rows = [jnp.reshape(e, (-1,)) for e in entries if e is not None]
        bw = max(r.shape[0] for r in rows)
        out = []
        for r in rows:
            if r.shape[0] != bw:
                if bw % r.shape[0]:
                    raise ValueError(
                        "beam_search_decode: array entry rows %d do not "
                        "tile into beam width %d" % (r.shape[0], bw))
                r = jnp.repeat(r, bw // r.shape[0])
            out.append(r)
        return jnp.stack(out, axis=0)

    ids = ctx.input("Ids")
    scores = ctx.input("Scores")
    if isinstance(ids, list):
        ids = stack_array(ids)
    if isinstance(scores, list):
        scores = stack_array(scores)
    ids = ids.astype(jnp.int32)                         # [T, BW]
    end_id = ctx.attr("end_id")
    T, BW = ids.shape
    parents = ctx.input("ParentIdx")                    # [T, BW] or absent
    if parents is None:
        # no reordering happened: each row is its own chain
        parents = jnp.broadcast_to(jnp.arange(BW, dtype=jnp.int32)[None, :],
                                   (T, BW))
    else:
        parents = parents.astype(jnp.int32)

    def back(cur_row, inp):
        ids_t, par_t = inp
        tok = jnp.take(ids_t, cur_row)
        prev = jnp.take(par_t, cur_row)
        return prev, tok

    start = jnp.arange(BW, dtype=jnp.int32)
    _, toks_rev = jax.lax.scan(back, start, (ids[::-1], parents[::-1]))
    seq = jnp.flip(toks_rev, axis=0).T                  # [BW, T]
    # length = first end_id position + 1 (end token kept, as the reference
    # appends end ids to finished hypotheses), else T
    is_end = seq == end_id
    first_end = jnp.argmax(is_end, axis=1)
    has_end = jnp.any(is_end, axis=1)
    lens = jnp.where(has_end, first_end + 1, T).astype(jnp.int32)
    final_scores = scores[-1].reshape(-1, 1)
    return {"SentenceIds": seq.astype(jnp.int64),
            "SentenceIds@LOD_LEN": lens,
            "SentenceScores": final_scores}
