"""Distributed (host-side RPC) ops.

Reference analogue: paddle/fluid/operators/distributed_ops/ — send_op,
recv_op, send_barrier_op, fetch_barrier_op, listen_and_serv_op
(listen_and_serv_op.cc:106 RunSyncLoop, :216 RunAsyncLoop, :318 RunImpl),
gen_nccl_id_op (gen_nccl_id_op.cc:31), checkpoint_notify_op.

These are HOST ops: they do socket IO / process bootstrap, so they never
appear inside a jitted computation. The executor detects them
(functionalizer.HOST_OPS) and runs the containing block eagerly; the dense
collective path (XLA psum over ICI) never produces these ops.

gen_collective_id is the gen_nccl_id analogue: NCCL's out-of-band unique-id
broadcast (ncclGetUniqueId + ephemeral RPC, gen_nccl_id_op.cc:59,:84) maps
to jax.distributed.initialize(coordinator, num_processes, process_id) which
performs the same rendezvous for the XLA collective runtime.
"""

import numpy as np

from .registry import register_op

__all__ = []


def _client():
    from ..distributed.rpc import global_client
    return global_client()


@register_op("send")
def _send(ctx):
    """send_op: push grads to their endpoints (rpc_client.h AsyncSendVar)."""
    names = ctx.op.input("X")
    epmap = ctx.attr("epmap", [])
    tid = int(ctx.attr("trainer_id", 0) or 0)
    c = _client()
    for (name, ep), val in zip(zip(names, epmap), ctx.inputs("X")):
        if val is not None:
            c.async_send_var(ep, name, np.asarray(val), trainer_id=tid)
    return {}


@register_op("send_barrier")
def _send_barrier(ctx):
    c = _client()
    for ep in ctx.attr("endpoints", []):
        c.async_send_barrier(ep)
    return {}


@register_op("recv")
def _recv(ctx):
    names = ctx.op.output("Out")
    epmap = ctx.attr("epmap", [])
    tid = int(ctx.attr("trainer_id", 0) or 0)
    c = _client()
    out = []
    for name, ep in zip(names, epmap):
        out.append(c.async_get_var(ep, name, trainer_id=tid))
    return {"Out": out}


@register_op("fetch_barrier")
def _fetch_barrier(ctx):
    c = _client()
    for ep in ctx.attr("endpoints", []):
        c.async_fetch_barrier(ep)
    return {}


@register_op("checkpoint_notify")
def _checkpoint_notify(ctx):
    c = _client()
    dirname = ctx.attr("dir", ctx.attr("dirname", "checkpoint"))
    for ep in ctx.attr("epmap", ctx.attr("endpoints", [])):
        c.checkpoint_notify(ep, dirname)
    return {}


@register_op("gen_collective_id")
def _gen_collective_id(ctx):
    """Multi-host collective bootstrap. With PADDLE_COORDINATOR set (or the
    standard JAX env), calls jax.distributed.initialize so all hosts join one
    XLA collective world; single-process runs are a no-op."""
    import os
    coordinator = os.environ.get("PADDLE_COORDINATOR")
    num = int(ctx.attr("num_trainers", 1) or 1)
    tid = int(ctx.attr("trainer_id", 0) or 0)
    if coordinator and num > 1:
        import jax
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num, process_id=tid)
        except RuntimeError:
            pass  # already initialized
    return {"Out": np.asarray([tid], np.int64)}


@register_op("listen_and_serv")
def _listen_and_serv(ctx):
    """Pserver event loop (listen_and_serv_op.cc:318 RunImpl). Blocks the
    executor, serving Send/Get/Barrier/Checkpoint until an exit message.

    The optimize sub-blocks run against the server's store through the same
    trace-time interpreter used for everything else — eagerly, on host."""
    from ..distributed.rpc import VariableServer
    from ..fluid import functionalizer

    op = ctx.op
    program = op.block.program
    endpoint = ctx.attr("endpoint")
    fanin = int(ctx.attr("Fanin", 1) or 1)
    sync_mode = bool(ctx.attr("sync_mode", True))
    param_names = list(ctx.attr("param_names", []))
    grad_names = list(ctx.attr("grad_names", []))
    block_ids = list(ctx.attr("optimize_blocks", []))
    block_by_param = {p: program.blocks[b]
                      for p, b in zip(param_names, block_ids)}
    grad_to_param = dict(zip(grad_names, param_names))
    lr_block_id = int(ctx.attr("lr_decay_block_id", -1))

    def optimize_fn(pname, gname, avg_grad, store):
        blk = block_by_param.get(pname)
        if blk is None:
            return
        env = dict(store)
        env[gname] = avg_grad
        functionalizer.run_block(blk, env)
        for k, v in env.items():
            store[k] = np.asarray(v)

    def pre_apply_fn(store):
        # LR schedule: once per global step (reference lr_decay block)
        if lr_block_id < 0:
            return
        env = dict(store)
        functionalizer.run_block(program.blocks[lr_block_id], env)
        for k, v in env.items():
            store[k] = np.asarray(v)

    server = VariableServer(endpoint, fanin=fanin, sync_mode=sync_mode,
                            optimize_fn=optimize_fn,
                            grad_to_param=grad_to_param,
                            pre_apply_fn=pre_apply_fn,
                            dc_asgd=bool(ctx.attr("dc_asgd", False)))
    # seed the store with every value the surrounding env already has
    # (params + optimizer state + @LR_DECAY_COUNTER@ created by the pserver
    # startup program); only the @LOD_LEN companion entries are internal
    from ..fluid.functionalizer import LOD_LEN_SUFFIX
    if ctx.env is not None:
        for k, v in list(ctx.env.items()):
            if v is not None and not k.endswith(LOD_LEN_SUFFIX):
                server.store[k] = np.asarray(v)
    server.start(background=False)  # blocks until exit
    # propagate final values back so save_persistables sees trained params
    if ctx.env is not None:
        for k, v in server.store.items():
            ctx.env[k] = v
    return {}


@register_op("prefetch")
def _prefetch(ctx):
    """Distributed lookup-table remote prefetch (reference
    distributed_ops/prefetch_op.cc): fetch embedding rows for a batch of
    ids from the pservers holding the row-sharded table (shard = id %
    num_endpoints, RoundRobin-on-ids). Host op: ids must be concrete."""
    import jax
    import jax.numpy as jnp
    ids = ctx.input("X")
    if isinstance(ids, jax.core.Tracer):
        raise RuntimeError(
            "prefetch is a host RPC op and cannot run under jit — it must "
            "be executed by the segmented host path")
    table = ctx.attr("table_name")
    eps = ctx.attr("epmap", ctx.attr("endpoints", []))
    ns = len(eps)
    if ns == 0:
        raise ValueError("prefetch op needs at least one endpoint "
                         "(epmap/endpoints attr is empty)")
    flat = np.asarray(ids).reshape(-1).astype(np.int64)
    c = _client()
    if flat.size == 0:
        # empty id batch: probe shard 0 for the row width
        probe = c.prefetch(eps[0], table, np.zeros((1,), np.int64),
                           num_shards=ns)
        out = np.zeros((0, probe.shape[-1]), probe.dtype)
    else:
        out = None
        for s, ep in enumerate(eps):
            sel = np.nonzero(flat % ns == s)[0]
            if sel.size == 0:
                continue
            rows = c.prefetch(ep, table, flat[sel], num_shards=ns)
            if out is None:
                out = np.zeros((flat.size, rows.shape[-1]), rows.dtype)
            out[sel] = rows
    shape = tuple(np.asarray(ids).shape)
    if shape and shape[-1] == 1:
        shape = shape[:-1]
    return {"Out": jnp.asarray(out.reshape(shape + (out.shape[-1],)))}


@register_op("sparse_table_push")
def _sparse_table_push(ctx):
    """Companion to prefetch: push sparse row gradients of a distributed
    lookup table back to its pserver shards (reference: split_ids +
    send of the SelectedRows grad, applied by the pserver's sparse
    optimize block)."""
    import jax
    ids = ctx.input("Ids")
    grads = ctx.input("Grad")
    if isinstance(ids, jax.core.Tracer) or isinstance(grads,
                                                      jax.core.Tracer):
        raise RuntimeError(
            "sparse_table_push is a host RPC op and cannot run under jit")
    table = ctx.attr("table_name")
    eps = ctx.attr("epmap", ctx.attr("endpoints", []))
    lr = float(ctx.attr("lr", 1.0))
    ns = len(eps)
    if ns == 0:
        raise ValueError("sparse_table_push needs at least one endpoint "
                         "(epmap/endpoints attr is empty)")
    flat = np.asarray(ids).reshape(-1).astype(np.int64)
    if flat.size == 0:
        return {}                    # nothing to push this step
    g = np.asarray(grads).reshape(flat.size, -1)
    c = _client()
    for s, ep in enumerate(eps):
        sel = np.nonzero(flat % ns == s)[0]
        if sel.size == 0:
            continue
        c.sparse_push(ep, table, flat[sel], g[sel], lr=lr, num_shards=ns)
    return {}
