"""Remaining reference op tail (final parity sweep).

Reference analogues, all under paddle/fluid/operators/: conv_fusion_op.cc,
add_position_encoding_op.cc, conv_shift_op.cc, cos_sim_op.cc,
maxout_op.cc, prelu_op.cc, minus_op.cc, modified_huber_loss_op.cc,
l1_norm_op.cc, multiplex_op.cc, fill_op.cc, fake_init_op.cc,
get_places_op.cc, interpolate_op.cc, pool_with_index_op.cc,
detection_map_op.cc, lod_rank_table_op.cc, reorder_lod_tensor_by_rank_op.cc,
split_lod_tensor_op.cc / merge_lod_tensor_op.cc (the IfElse pair),
split_selected_rows_op.cc, distributed_ops/{split_ids,merge_ids,
split_byref}_op.cc, lookup_sparse_table_op.cc, delete_var_op.cc,
tensor_array_to_tensor_op.cc, similarity_focus_op.cc.

Grad ops are NOT mirrored: the generic per-op vjp (registry.py) derives
them — each reference *_grad op registration is subsumed by autodiff.
"""

import numpy as np

from .registry import register_op, get_op_def as get_op


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# fused conv + epilogue (conv_fusion_op.cc — the cuDNN fused kernel)
# ---------------------------------------------------------------------------

@register_op("conv2d_fusion")
def _conv2d_fusion(ctx):
    jnp = _jnp()
    # the conv2d lowering already folds a Bias input when present
    out = get_op("conv2d").lower(ctx)["Output"]
    residual = ctx.input("ResidualData")
    if residual is not None:
        out = out + residual
    act = ctx.attr("activation", "relu")
    if act in ("relu",):
        out = jnp.maximum(out, 0)
    elif act in ("identity", "", None):
        pass
    elif act == "sigmoid":
        import jax
        out = jax.nn.sigmoid(out)
    elif act == "tanh":
        out = jnp.tanh(out)
    else:
        raise NotImplementedError("conv2d_fusion activation %r" % act)
    return {"Output": out}


@register_op("depthwise_conv2d_transpose")
def _depthwise_conv2d_transpose(ctx):
    ctx.attrs = dict(ctx.attrs)
    x = ctx.input("Input")
    layout = ctx.attr("data_format", "NCHW")
    channels = x.shape[1] if layout in ("NCHW", "AnyLayout") \
        else x.shape[-1]
    ctx.attrs["groups"] = channels
    return get_op("conv2d_transpose").lower(ctx)


# ---------------------------------------------------------------------------
# small math / activation tail
# ---------------------------------------------------------------------------

@register_op("add_position_encoding")
def _add_position_encoding(ctx):
    """out = alpha*x + beta*sinusoid(pos) (add_position_encoding_op.h).
    The reference's frequency exponent is k/(half_size-1) — reaching
    exactly 1/10000 at the last sin/cos pair — NOT the transformer
    paper's 2k/D; half_size == 1 divides by 10000 directly, and the
    encode size must be even (the reference ENFORCEs it). Positions
    restart at 0 per sequence, which the padded-dense layout gives for
    free. Pinned by tests/test_position_encoding_oracle.py."""
    jnp = _jnp()
    x = ctx.input("X")      # [B, T, D]
    alpha = ctx.attr("alpha", 1.0)
    beta = ctx.attr("beta", 1.0)
    B, T, D = x.shape
    if D % 2:
        raise ValueError(
            "add_position_encoding: encode size must be even "
            "(reference add_position_encoding_op.h:61), got %d" % D)
    half = D // 2
    pos = jnp.arange(T, dtype=jnp.float32)[:, None]
    k = jnp.arange(half, dtype=jnp.float32)[None, :]
    denom = jnp.power(10000.0, k / (half - 1)) if half > 1 else \
        jnp.full((1, 1), 10000.0, jnp.float32)
    val = pos / denom
    enc = jnp.concatenate([jnp.sin(val), jnp.cos(val)], axis=1)
    return {"Out": (alpha * x + beta * enc[None].astype(x.dtype))
            .astype(x.dtype)}


@register_op("conv_shift")
def _conv_shift(ctx):
    """Circular correlation (conv_shift_op.cc): out[b,i] =
    sum_j x[b,(i+j-half) mod N] * y[b,j] with half = (M-1)//2 — the
    reference's y_half_width floors (M-1)/2, which differs from M//2
    for EVEN filter widths."""
    jnp = _jnp()
    x, y = ctx.input("X"), ctx.input("Y")
    N, M = x.shape[1], y.shape[1]
    half = (M - 1) // 2
    out = jnp.zeros_like(x)
    for j in range(M):
        out = out + jnp.roll(x, half - j, axis=1) * y[:, j:j + 1]
    return {"Out": out}


@register_op("cos_sim")
def _cos_sim(ctx):
    jnp = _jnp()
    x, y = ctx.input("X"), ctx.input("Y")
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    dot = jnp.sum(x * y, axis=-1, keepdims=True)
    return {"Out": dot / jnp.maximum(xn * yn, 1e-12),
            "XNorm": xn, "YNorm": yn}


@register_op("maxout")
def _maxout(ctx):
    jnp = _jnp()
    x = ctx.input("X")      # [B, C, H, W]
    groups = ctx.attr("groups", 1)
    B, C, H, W = x.shape
    return {"Out": jnp.max(
        x.reshape(B, C // groups, groups, H, W), axis=2)}


@register_op("prelu")
def _prelu(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    alpha = ctx.input("Alpha")
    mode = ctx.attr("mode", "all")
    if mode == "channel":
        # channel dim is axis 1 for any rank >= 2 (prelu_op.cc)
        a = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    elif mode == "element":
        a = alpha.reshape((1,) + tuple(x.shape[1:]))
    else:
        a = alpha.reshape(())
    return {"Out": jnp.where(x >= 0, x, a * x)}


@register_op("minus")
def _minus(ctx):
    return {"Out": ctx.input("X") - ctx.input("Y")}


@register_op("modified_huber_loss")
def _modified_huber_loss(ctx):
    """modified_huber_loss_op.h: binary classification loss on y in {0,1};
    z = (2y-1)*pred; loss = max(0,1-z)^2 for z>=-1 else -4z."""
    jnp = _jnp()
    x = ctx.input("X")
    y = ctx.input("Y")
    z = (2.0 * y - 1.0) * x
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.square(jnp.maximum(1.0 - z, 0.0)))
    return {"Out": loss, "IntermediateVal": z}


@register_op("l1_norm")
def _l1_norm(ctx):
    jnp = _jnp()
    return {"Out": jnp.sum(jnp.abs(ctx.input("X"))).reshape(1)}


@register_op("fill")
def _fill(ctx):
    """fill_op.cc: fill the output from an attr-carried buffer."""
    jnp = _jnp()
    from ..fluid import core as fcore
    shape = [int(s) for s in ctx.attr("shape", [1])]
    dtype = fcore.convert_dtype_to_np(
        ctx.attr("dtype", fcore.VarDesc.VarType.FP32))
    value = np.asarray(ctx.attr("value", [0.0]), dtype=dtype)
    return {"Out": jnp.asarray(value.reshape(shape))}


@register_op("fake_init")
def _fake_init(ctx):
    """fake_init_op.cc: placeholder init for vars another process owns
    (pserver-side tables) — zeros of the declared shape."""
    jnp = _jnp()
    shape = [int(s) for s in ctx.attr("shape", [1])]
    return {"Out": jnp.zeros(shape, "float32")}


@register_op("get_places")
def _get_places(ctx):
    """get_places_op.cc: the visible device list, as indices."""
    import jax
    jnp = _jnp()
    n = ctx.attr("device_count", 0) or len(jax.devices())
    return {"Out": jnp.arange(n, dtype=jnp.int32)}


@register_op("interpolate")
def _interpolate(ctx):
    method = ctx.attr("interp_method", "bilinear")
    op = "bilinear_interp" if method == "bilinear" else "nearest_interp"
    out = get_op(op).lower(ctx)
    return out


@register_op("similarity_focus")
def _similarity_focus(ctx):
    """similarity_focus_op.h: per (axis, index) slice, greedily select the
    highest cells whose row AND column are both still unused — exactly
    min(H, W) ones per slice — and broadcast the mask across channels."""
    jnp = _jnp()
    x = ctx.input("X")      # [B, C, H, W]
    axis = ctx.attr("axis", 1)
    indexes = ctx.attr("indexes", [0])
    if axis != 1:
        raise NotImplementedError("similarity_focus: axis=1 only")
    B, C, H, W = x.shape
    neg = jnp.asarray(-np.inf, x.dtype)
    mask = jnp.zeros_like(x)
    for idx in indexes:
        sl = x[:, idx]                     # [B, H, W]
        m = jnp.zeros_like(sl)
        avail = sl
        for _step in range(min(H, W)):     # static greedy selection
            flat = avail.reshape(B, -1)
            best = jnp.argmax(flat, axis=1)          # [B]
            r, c = best // W, best % W
            hit = (jnp.arange(H)[None, :, None] == r[:, None, None]) & \
                  (jnp.arange(W)[None, None, :] == c[:, None, None])
            m = jnp.maximum(m, hit.astype(sl.dtype))
            row_used = jnp.arange(H)[None, :, None] == r[:, None, None]
            col_used = jnp.arange(W)[None, None, :] == c[:, None, None]
            avail = jnp.where(row_used | col_used, neg, avail)
        mask = jnp.maximum(mask, jnp.broadcast_to(m[:, None], mask.shape))
    return {"Out": mask}


# ---------------------------------------------------------------------------
# pooling with argmax indices (pool_with_index_op.cc)
# ---------------------------------------------------------------------------

def _pool_with_index(ctx, spatial):
    jnp = _jnp()
    x = ctx.input("X")                     # [B, C, *spatial]
    ksize = [int(k) for k in ctx.attr("ksize", [2] * spatial)]
    strides = [int(s) for s in ctx.attr("strides", [1] * spatial)]
    pads = [int(p) for p in ctx.attr("paddings", [0] * spatial)]
    if ctx.attr("global_pooling", False):
        # pool_with_index_op.cc: global pooling overrides ksize/paddings
        ksize = list(x.shape[2:])
        pads = [0] * spatial
    pad_cfg = [(0, 0), (0, 0)] + [(p, p) for p in pads]
    neg = jnp.finfo(x.dtype).min
    xp = jnp.pad(x, pad_cfg, constant_values=neg)
    in_spatial = x.shape[2:]
    out_spatial = [
        (in_spatial[d] + 2 * pads[d] - ksize[d]) // strides[d] + 1
        for d in range(spatial)]
    # stack all window offsets, track flat UNPADDED input index per cell
    cand_vals, cand_idx = [], []
    import itertools
    for off in itertools.product(*[range(k) for k in ksize]):
        idx_nd = []
        sl = xp
        for d in range(spatial):
            start = off[d]
            end = start + strides[d] * (out_spatial[d] - 1) + 1
            sl = jnp.take(sl, jnp.arange(start, end, strides[d]),
                          axis=2 + d)
            idx_nd.append(jnp.arange(out_spatial[d]) * strides[d]
                          + off[d] - pads[d])
        cand_vals.append(sl)
        flat = jnp.zeros((), jnp.int32)
        for d in range(spatial):
            shape = [1] * spatial
            shape[d] = out_spatial[d]
            flat = flat * in_spatial[d] + \
                jnp.clip(idx_nd[d], 0, in_spatial[d] - 1).reshape(shape)
        cand_idx.append(jnp.broadcast_to(flat, tuple(out_spatial)))
    vals = jnp.stack(cand_vals, axis=0)     # [K, B, C, *out]
    idxs = jnp.stack(cand_idx, axis=0)      # [K, *out]
    best = jnp.argmax(vals, axis=0)         # [B, C, *out]
    out = jnp.max(vals, axis=0)
    mask = jnp.take_along_axis(
        jnp.broadcast_to(idxs[:, None, None], vals.shape), best[None],
        axis=0)[0]
    return {"Out": out, "Mask": mask.astype(jnp.int32)}


@register_op("max_pool2d_with_index")
def _max_pool2d_with_index(ctx):
    return _pool_with_index(ctx, 2)


@register_op("max_pool3d_with_index")
def _max_pool3d_with_index(ctx):
    return _pool_with_index(ctx, 3)


# ---------------------------------------------------------------------------
# LoD rank table + reorder + IfElse split/merge
# ---------------------------------------------------------------------------

@register_op("lod_rank_table")
def _lod_rank_table(ctx):
    """lod_rank_table_op.cc: order sequences by length, descending (stable).
    Dense encoding: the table IS the permutation vector [B]."""
    jnp = _jnp()
    x = ctx.input("X")
    lens = ctx.lod_len("X")
    B = x.shape[0]
    if lens is None:
        lens = jnp.full((B,), x.shape[1] if x.ndim > 1 else 1, jnp.int32)
    # stable descending sort: argsort of (-len, index)
    perm = jnp.argsort(-lens.astype(jnp.int64) * B
                       - (B - 1 - jnp.arange(B)))
    return {"Out": perm.astype(jnp.int32)}


@register_op("reorder_lod_tensor_by_rank")
def _reorder_lod_tensor_by_rank(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    table = ctx.input("RankTable").astype("int32")
    out = jnp.take(x, table, axis=0)
    lens = ctx.lod_len("X")
    res = {"Out": out}
    if lens is not None:
        res["Out@LOD_LEN"] = jnp.take(lens, table, axis=0)
    return res


@register_op("split_lod_tensor")
def _split_lod_tensor(ctx):
    """split_lod_tensor_op.cc (the IfElse input split). Output row counts
    are data-dependent; the dense encoding keeps ALL rows in both outputs
    and masks the non-selected ones to zero — merge_lod_tensor composes
    exactly, which is the invariant IfElse needs."""
    jnp = _jnp()
    x = ctx.input("X")
    mask = ctx.input("Mask").reshape(-1).astype(bool)
    m = mask.reshape((-1,) + (1,) * (x.ndim - 1))
    return {"OutTrue": jnp.where(m, x, 0).astype(x.dtype),
            "OutFalse": jnp.where(m, 0, x).astype(x.dtype)}


@register_op("merge_lod_tensor")
def _merge_lod_tensor(ctx):
    jnp = _jnp()
    mask = ctx.input("Mask").reshape(-1).astype(bool)
    t, f = ctx.input("InTrue"), ctx.input("InFalse")
    m = mask.reshape((-1,) + (1,) * (t.ndim - 1))
    return {"Out": jnp.where(m, t, f)}


@register_op("lod_array_length")
def _lod_array_length(ctx):
    return get_op("array_length").lower(ctx)


@register_op("tensor_array_to_tensor")
def _tensor_array_to_tensor(ctx):
    """tensor_array_to_tensor_op.cc: stack/concat the array entries."""
    jnp = _jnp()
    xs = ctx.inputs("X")
    axis = ctx.attr("axis", 0)
    use_stack = ctx.attr("use_stack", False)
    out = jnp.stack(xs, axis=axis) if use_stack \
        else jnp.concatenate(xs, axis=axis)
    idx = jnp.array([x.shape[axis] if not use_stack else 1 for x in xs],
                    jnp.int32)
    return {"Out": out, "OutIndex": idx}


# ---------------------------------------------------------------------------
# distributed / sparse-table helpers
# ---------------------------------------------------------------------------

@register_op("lookup_sparse_table")
def _lookup_sparse_table(ctx):
    """lookup_sparse_table_op.cc: lookup_table over an auto-growing
    pserver table; dense substrate serves it with the same gather."""
    return get_op("lookup_table").lower(ctx)


@register_op("split_ids")
def _split_ids(ctx):
    """split_ids_op.cc: shard ids by id % n_parts, preserving each
    shard's original order. Output row counts are data-dependent —
    eager/host path only (the PS prefetch path, which runs eagerly)."""
    import jax
    jnp = _jnp()
    id_inputs = ctx.inputs("Ids")   # duplicable slot: concat all of them
    n = len(ctx.op.outputs.get("Out", []))
    if any(isinstance(i, jax.core.Tracer) for i in id_inputs):
        raise NotImplementedError(
            "split_ids has data-dependent output shapes — host path only")
    flat = np.concatenate([np.asarray(i).reshape(-1) for i in id_inputs])
    parts = [flat[flat % n == i].reshape(-1, 1) for i in range(n)]
    return {"Out": [jnp.asarray(p) for p in parts]}


@register_op("merge_ids")
def _merge_ids(ctx):
    """merge_ids_op.cc: restore per-shard prefetched rows to the original
    Ids order (host path, exact mirror of split_ids' sharding)."""
    import jax
    jnp = _jnp()
    ids = ctx.inputs("Ids")
    rows = ctx.inputs("X")
    if any(isinstance(v, jax.core.Tracer) for v in list(ids) + list(rows)):
        raise NotImplementedError("merge_ids runs on the host path only")
    n = len(rows)
    rows_np = [np.asarray(r) for r in rows]
    width = rows_np[0].shape[-1]
    # walk the Ids inputs in the same global order split_ids concatenated
    # them, emitting one Out per Ids input (both slots are duplicable,
    # merge_ids_op.cc)
    counters = [0] * n
    outs = []
    for id_in in ids:
        flat = np.asarray(id_in).reshape(-1)
        out = np.zeros((len(flat), width), rows_np[0].dtype)
        for k, idv in enumerate(flat):
            s = int(idv) % n
            out[k] = rows_np[s][counters[s]]
            counters[s] += 1
        outs.append(out)
    result = [jnp.asarray(o) for o in outs]
    return {"Out": result if len(result) > 1 else result[0]}


@register_op("split_byref")
def _split_byref(ctx):
    """split_byref_op.cc: split rows into height-sections (zero-copy in
    the reference; XLA slices here)."""
    jnp = _jnp()
    x = ctx.input("X")
    sections = ctx.attr("height_sections", None) or ctx.attr(
        "sections", None)
    n = len(ctx.op.outputs.get("Out", []))
    if not sections:
        # array_split semantics: earlier parts take the remainder rows —
        # nothing is silently dropped
        base, rem = divmod(x.shape[0], n)
        sections = [base + (1 if i < rem else 0) for i in range(n)]
    if sum(int(s) for s in sections) != x.shape[0]:
        raise ValueError(
            "split_byref: sections %s do not sum to height %d"
            % (sections, x.shape[0]))
    outs, off = [], 0
    for s in sections:
        outs.append(x[off:off + int(s)])
        off += int(s)
    return {"Out": outs}


@register_op("split_selected_rows")
def _split_selected_rows(ctx):
    return get_op("split_byref").lower(ctx)


@register_op("delete_var")
def _delete_var(ctx):
    """delete_var_op.cc: drop variables (host op — the executor removes
    the env entries; functional state threading makes this advisory)."""
    return {}


@register_op("gen_nccl_id")
def _gen_nccl_id(ctx):
    """gen_nccl_id_op.cc — alias of the collective-id bootstrap."""
    return get_op("gen_collective_id").lower(ctx)


# ---------------------------------------------------------------------------
# detection mAP metric (detection_map_op.cc) — host/eager evaluation
# ---------------------------------------------------------------------------

@register_op("detection_map")
def _detection_map(ctx):
    """11-point / integral mAP over (label, score, box-match) rows.
    Metric op: evaluated on concrete host arrays (metrics run outside the
    jitted step, reference detection_map_op.h)."""
    import jax
    jnp = _jnp()
    det = ctx.input("DetectRes")    # [M, 6]: label, score, xmin..ymax
    gt = ctx.input("Label")         # [N, 6]: label, xmin..ymax (+difficult)
    if isinstance(det, jax.core.Tracer) or isinstance(gt, jax.core.Tracer):
        raise NotImplementedError("detection_map runs on the host path")
    overlap_t = ctx.attr("overlap_threshold", 0.5)
    ap_type = ctx.attr("ap_type", "integral")
    evaluate_difficult = ctx.attr("evaluate_difficult", True)
    det = np.asarray(det)
    gt = np.asarray(gt)
    det_lens = ctx.lod_len("DetectRes")
    gt_lens = ctx.lod_len("Label")
    det_lens = (np.asarray(det_lens) if det_lens is not None
                else np.array([det.shape[0]]))
    gt_lens = (np.asarray(gt_lens) if gt_lens is not None
               else np.array([gt.shape[0]]))
    det = det.reshape(-1, det.shape[-1])
    gt = gt.reshape(-1, gt.shape[-1])
    # Label rows: 6 columns = [label, difficult, xmin, ymin, xmax, ymax]
    # (detection_map_op.h), 5 columns = no difficult flag
    has_difficult = gt.shape[-1] >= 6

    def gt_box(r):
        return r[2:6] if has_difficult else r[1:5]

    def gt_difficult(r):
        return bool(r[1]) if has_difficult else False

    def iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    # streaming accumulation (detection_map_op.h GetInputPos): rows of
    # [class, npos] / [class, score, count]; prior batches arrive via the
    # PosCount/TruePos/FalsePos inputs
    npos_of, tp_rows, fp_rows = {}, [], []
    # HasState gate (detection_map_op.h): 0 means the accumulator inputs
    # are uninitialized/stale and must be ignored this run
    has_state_in = ctx.input("HasState")
    use_prior = (has_state_in is None or
                 int(np.asarray(has_state_in).reshape(-1)[0]) != 0)
    prior_pos = ctx.input("PosCount") if use_prior else None
    prior_tp = ctx.input("TruePos") if use_prior else None
    prior_fp = ctx.input("FalsePos") if use_prior else None
    if prior_pos is not None:
        for c, n in np.asarray(prior_pos).reshape(-1, 2):
            npos_of[int(c)] = npos_of.get(int(c), 0) + int(n)
    if prior_tp is not None:
        tp_rows += [tuple(r) for r in np.asarray(prior_tp).reshape(-1, 3)]
    if prior_fp is not None:
        fp_rows += [tuple(r) for r in np.asarray(prior_fp).reshape(-1, 3)]

    # classes seen in EITHER labels or detections: a detection of a class
    # with no ground truth anywhere in the batch must still count as a
    # false positive (detection_map_op.h CalcTrueAndFalsePositive).
    # The background class never scores.
    background = int(ctx.attr("background_label", 0))
    classes = sorted((set(gt[:, 0].astype(int))
                      | set(det[:, 0].astype(int))) - {background})
    d_off = np.concatenate([[0], np.cumsum(det_lens)]).astype(int)
    g_off = np.concatenate([[0], np.cumsum(gt_lens)]).astype(int)
    for c in classes:
        for i in range(len(gt_lens)):
            grows = [r for r in gt[g_off[i]:g_off[i + 1]]
                     if int(r[0]) == c]
            drows = det[d_off[i]:d_off[i + 1]]
            gboxes = [gt_box(r) for r in grows]
            counted = [evaluate_difficult or not gt_difficult(r)
                       for r in grows]
            npos_of[c] = npos_of.get(c, 0) + sum(counted)
            taken = [False] * len(gboxes)
            dc = sorted([r for r in drows if int(r[0]) == c],
                        key=lambda r: -r[1])
            for r in dc:
                best, bi = 0.0, -1
                for j, gb in enumerate(gboxes):
                    o = iou(r[2:6], gb)
                    if o > best:
                        best, bi = o, j
                # STRICT > like the reference (detection_map_op.h:391)
                if best > overlap_t and bi >= 0:
                    if not counted[bi]:
                        # matched a difficult gt under
                        # evaluate_difficult=False: ignored entirely --
                        # no TP, no FP, and the box stays unvisited
                        # (detection_map_op.h:392-404)
                        continue
                    if not taken[bi]:
                        taken[bi] = True
                        tp_rows.append((c, float(r[1]), 1))
                    else:
                        fp_rows.append((c, float(r[1]), 1))
                else:
                    fp_rows.append((c, float(r[1]), 1))

    aps = []
    for c, npos in npos_of.items():
        if npos == 0:
            continue
        scored = [(s, 1) for cc, s, n in tp_rows if int(cc) == c] + \
                 [(s, 0) for cc, s, n in fp_rows if int(cc) == c]
        if not scored:
            # a class with positives but no detections anywhere has no
            # true_pos entry in the reference and is EXCLUDED from the
            # mAP average, not scored 0 (detection_map_op.h:437-440)
            continue
        scored.sort(key=lambda t: -t[0])
        tps = np.cumsum([t[1] for t in scored])
        fps = np.cumsum([1 - t[1] for t in scored])
        rec = tps / npos
        prec = tps / np.maximum(tps + fps, 1e-12)
        if ap_type == "11point":
            ap = np.mean([
                max([p for r_, p in zip(rec, prec) if r_ >= th] or [0.0])
                for th in np.arange(0, 1.01, 0.1)])
        else:
            ap = 0.0
            prev_r = 0.0
            for r_, p in zip(rec, prec):
                ap += (r_ - prev_r) * p
                prev_r = r_
        aps.append(float(ap))
    m_ap = float(np.mean(aps)) if aps else 0.0
    pos_arr = np.array(sorted((c, n) for c, n in npos_of.items()),
                       np.int32).reshape(-1, 2)
    tp_arr = np.array(tp_rows, np.float32).reshape(-1, 3)
    fp_arr = np.array(fp_rows, np.float32).reshape(-1, 3)
    return {"MAP": jnp.asarray([m_ap], jnp.float32),
            "AccumPosCount": jnp.asarray(pos_arr),
            "AccumTruePos": jnp.asarray(tp_arr),
            "AccumFalsePos": jnp.asarray(fp_arr)}
