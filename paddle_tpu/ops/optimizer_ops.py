"""Optimizer update op lowerings.

Reference analogues: paddle/fluid/operators/optimizers/{sgd,momentum,adam,
adagrad,adamax,adadelta,rmsprop,ftrl,decayed_adagrad,proximal_*,lars_momentum}
_op.cc (+ .cu kernels). Each reference op has CPU+CUDA kernels and in-place
Param/Moment outputs; here each is one pure update function — the executor's
functional state-threading makes "in-place" an XLA buffer-donation concern,
not an op concern.

Sparse (SelectedRows) gradients: when an embedding was built with
is_sparse=True, its grad arrives as a SelectedRowsValue (rows + values —
fluid/selected_rows.py) and sgd/momentum/adam/adagrad take a row-wise
scatter-update path whose cost scales with the touched rows, mirroring the
reference's SelectedRows kernels (adam lazy mode included).
"""

import numpy as np

from .registry import register_op


def _jnp():
    import jax.numpy as jnp
    return jnp


def _lr(ctx):
    lr = ctx.input("LearningRate")
    return lr.reshape(()) if hasattr(lr, "reshape") else lr


def _is_sparse(g):
    from ..fluid.selected_rows import SelectedRowsValue
    return isinstance(g, SelectedRowsValue)


@register_op("sgd", stateful=True)
def _sgd(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    lr = _lr(ctx).astype(p.dtype)
    if _is_sparse(g):
        g = g.merged()
        # merged() pads rows with the out-of-range id `height`;
        # mode="drop" keeps those padding entries off real rows.
        return {"ParamOut": p.at[g.rows].add(
            -lr * g.values.astype(p.dtype), mode="drop")}
    return {"ParamOut": p - lr * g.astype(p.dtype)}


@register_op("momentum", stateful=True)
def _momentum(ctx):
    p, g, v = ctx.input("Param"), ctx.input("Grad"), ctx.input("Velocity")
    mu = ctx.attr("mu")
    lr = _lr(ctx).astype(p.dtype)
    if _is_sparse(g):
        g = g.to_dense()   # velocity state is dense; reference densifies too
    v_out = mu * v + g
    if ctx.attr("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": p_out, "VelocityOut": v_out}


@register_op("lars_momentum", stateful=True)
def _lars_momentum(ctx):
    jnp = _jnp()
    p, g, v = ctx.input("Param"), ctx.input("Grad"), ctx.input("Velocity")
    mu = ctx.attr("mu")
    lars_coeff = ctx.attr("lars_coeff", 0.001)
    lars_weight_decay = ctx.attr("lars_weight_decay", 0.0005)
    lr = _lr(ctx).astype(p.dtype)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * lars_coeff * p_norm / (g_norm + lars_weight_decay * p_norm),
        lr)
    v_out = mu * v + local_lr * (g + lars_weight_decay * p)
    return {"ParamOut": p - v_out, "VelocityOut": v_out}


@register_op("adam", stateful=True)
def _adam(ctx):
    jnp = _jnp()
    p, g = ctx.input("Param"), ctx.input("Grad")
    m1, m2 = ctx.input("Moment1"), ctx.input("Moment2")
    b1p, b2p = ctx.input("Beta1Pow"), ctx.input("Beta2Pow")
    beta1, beta2 = ctx.attr("beta1", 0.9), ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    lr = _lr(ctx)
    if _is_sparse(g):
        # lazy sparse adam (adam_op.h SelectedRows path, lazy_mode): only
        # the touched rows' moments and params move
        sr = g.merged()
        rows, vals = sr.rows, sr.values
        # padding rows are out of range: gathers clip (read garbage that is
        # never written back), scatters with mode="drop" discard them.
        m1r = beta1 * m1[rows] + (1 - beta1) * vals
        m2r = beta2 * m2[rows] + (1 - beta2) * jnp.square(vals)
        lr_t = lr * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
        p_new = p.at[rows].add(-lr_t.astype(p.dtype) * (
            m1r / (jnp.sqrt(m2r) + eps)).astype(p.dtype), mode="drop")
        return {"ParamOut": p_new,
                "Moment1Out": m1.at[rows].set(m1r, mode="drop"),
                "Moment2Out": m2.at[rows].set(m2r, mode="drop")}
    m1_out = beta1 * m1 + (1 - beta1) * g
    m2_out = beta2 * m2 + (1 - beta2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    p_out = p - lr_t.astype(p.dtype) * (
        m1_out / (jnp.sqrt(m2_out) + eps)).astype(p.dtype)
    return {"ParamOut": p_out, "Moment1Out": m1_out, "Moment2Out": m2_out}


@register_op("adamax", stateful=True)
def _adamax(ctx):
    jnp = _jnp()
    p, g = ctx.input("Param"), ctx.input("Grad")
    m, inf = ctx.input("Moment"), ctx.input("InfNorm")
    b1p = ctx.input("Beta1Pow")
    beta1, beta2 = ctx.attr("beta1", 0.9), ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    lr = _lr(ctx)
    m_out = beta1 * m + (1 - beta1) * g
    # reference folds epsilon INSIDE the max (adamax_op.h:68-69):
    # inf_out = max(|g|, beta2*inf + eps); denominator takes no extra eps
    inf_out = jnp.maximum(jnp.abs(g), beta2 * inf + eps)
    lr_t = lr / (1 - b1p.reshape(()))
    p_out = p - lr_t.astype(p.dtype) * m_out / inf_out
    return {"ParamOut": p_out, "MomentOut": m_out, "InfNormOut": inf_out}


@register_op("adagrad", stateful=True)
def _adagrad(ctx):
    jnp = _jnp()
    p, g, m = ctx.input("Param"), ctx.input("Grad"), ctx.input("Moment")
    eps = ctx.attr("epsilon", 1e-6)
    if _is_sparse(g):
        sr = g.merged()
        rows, vals = sr.rows, sr.values
        mr = m[rows] + jnp.square(vals)
        p_new = p.at[rows].add(
            -_lr(ctx).astype(p.dtype) * vals / (jnp.sqrt(mr) + eps),
            mode="drop")
        return {"ParamOut": p_new,
                "MomentOut": m.at[rows].set(mr, mode="drop")}
    m_out = m + jnp.square(g)
    p_out = p - _lr(ctx).astype(p.dtype) * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out, "MomentOut": m_out}


@register_op("decayed_adagrad", stateful=True)
def _decayed_adagrad(ctx):
    jnp = _jnp()
    p, g, m = ctx.input("Param"), ctx.input("Grad"), ctx.input("Moment")
    decay = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    m_out = decay * m + (1 - decay) * jnp.square(g)
    p_out = p - _lr(ctx).astype(p.dtype) * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out, "MomentOut": m_out}


@register_op("adadelta", stateful=True)
def _adadelta(ctx):
    jnp = _jnp()
    p, g = ctx.input("Param"), ctx.input("Grad")
    avg_sq_g, avg_sq_u = ctx.input("AvgSquaredGrad"), \
        ctx.input("AvgSquaredUpdate")
    rho = ctx.attr("rho", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    avg_sq_g_out = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_u + eps) / (avg_sq_g_out + eps)) * g
    avg_sq_u_out = rho * avg_sq_u + (1 - rho) * jnp.square(update)
    return {"ParamOut": p + update, "AvgSquaredGradOut": avg_sq_g_out,
            "AvgSquaredUpdateOut": avg_sq_u_out}


@register_op("rmsprop", stateful=True)
def _rmsprop(ctx):
    jnp = _jnp()
    p, g = ctx.input("Param"), ctx.input("Grad")
    ms, mom = ctx.input("MeanSquare"), ctx.input("Moment")
    rho = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    momentum = ctx.attr("momentum", 0.0)
    lr = _lr(ctx).astype(p.dtype)
    outs = {}
    ms_out = rho * ms + (1 - rho) * jnp.square(g)
    if ctx.attr("centered", False):
        mg = ctx.input("MeanGrad")
        mg_out = rho * mg + (1 - rho) * g
        denom = ms_out - jnp.square(mg_out) + eps
        outs["MeanGradOut"] = mg_out
    else:
        denom = ms_out + eps
    mom_out = momentum * mom + lr * g / jnp.sqrt(denom)
    outs.update({"ParamOut": p - mom_out, "MeanSquareOut": ms_out,
                 "MomentOut": mom_out})
    return outs


@register_op("ftrl", stateful=True)
def _ftrl(ctx):
    jnp = _jnp()
    p, g = ctx.input("Param"), ctx.input("Grad")
    sq_accum, lin_accum = ctx.input("SquaredAccumulator"), \
        ctx.input("LinearAccumulator")
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    lr_power = ctx.attr("lr_power", -0.5)
    lr = _lr(ctx).astype(p.dtype)
    new_accum = sq_accum + jnp.square(g)
    if lr_power == -0.5:
        lin_delta = g - (jnp.sqrt(new_accum) - jnp.sqrt(sq_accum)) / lr * p
    else:
        lin_delta = g - (new_accum ** (-lr_power) -
                         sq_accum ** (-lr_power)) / lr * p
    lin_out = lin_accum + lin_delta
    # reference shrink denominator carries 2*l2 (ftrl_op.h:87-95)
    if lr_power == -0.5:
        x = 2 * l2 + jnp.sqrt(new_accum) / lr
    else:
        x = 2 * l2 + new_accum ** (-lr_power) / lr
    pre_shrink = (jnp.sign(lin_out) * l1 - lin_out) / x
    p_out = jnp.where(jnp.abs(lin_out) > l1, pre_shrink,
                      jnp.zeros_like(p))
    return {"ParamOut": p_out, "SquaredAccumOut": new_accum,
            "LinearAccumOut": lin_out}


@register_op("proximal_gd", stateful=True)
def _proximal_gd(ctx):
    jnp = _jnp()
    p, g = ctx.input("Param"), ctx.input("Grad")
    l1, l2 = ctx.attr("l1", 0.0), ctx.attr("l2", 0.0)
    lr = _lr(ctx).astype(p.dtype)
    prox = p - lr * g
    p_out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / (
        1.0 + lr * l2)
    return {"ParamOut": p_out}


# ---------------------------------------------------------------------------
# gradient accumulation / multi-batch merge (reference
# ir/multi_batch_merge_pass.cc + test_dist_mnist_batch_merge): when the
# multi_batch_merge_pass has annotated an optimizer op with merge_n=N, the
# op accumulates grads into a persistable buffer for N micro-steps and
# applies ONE update from the averaged grad on every Nth step. The gate is
# a jnp.where over the op's in-place outputs — branch-free and jittable,
# the TPU-idiomatic encoding of the reference's repeated-subgraph rewrite.
# ---------------------------------------------------------------------------

MERGEABLE_OPT_OPS = (
    "sgd", "momentum", "lars_momentum", "adam", "adamax", "adagrad",
    "decayed_adagrad", "adadelta", "rmsprop", "ftrl", "proximal_gd",
)

# in-place alias convention of the reference optimizer ops: output slot
# "<X>Out" writes input slot "<X>" (ParamOut<-Param, VelocityOut<-Velocity,
# MomentOut<-Moment, Beta1PowOut<-Beta1Pow, ...)
_OUT_ALIASES = {
    "SquaredAccumOut": "SquaredAccumulator",
    "LinearAccumOut": "LinearAccumulator",
    "AvgSquaredGradOut": "AvgSquaredGrad",
    "AvgSquaredUpdateOut": "AvgSquaredUpdate",
    "MeanSquareOut": "MeanSquare",
    "MeanGradOut": "MeanGrad",
}


def _alias_input(ctx, slot):
    if slot in _OUT_ALIASES:
        return ctx.input(_OUT_ALIASES[slot])
    if slot.endswith("Out") and ctx.has_input(slot[:-3]):
        return ctx.input(slot[:-3])
    return None


def _merge_gated(lower):
    import functools

    @functools.wraps(lower)
    def wrapped(ctx):
        n = int(ctx.attr("merge_n", 0) or 0)
        if n <= 1:
            return lower(ctx)
        jnp = _jnp()
        from .registry import ExecContext
        g = ctx.input("Grad")
        if _is_sparse(g):
            raise NotImplementedError(
                "multi_batch_merge with sparse (SelectedRows) gradients — "
                "densify the embedding grad (is_sparse=False) to combine "
                "with gradient accumulation")
        acc = ctx.input("GradAcc")
        if acc is None:
            acc = jnp.zeros_like(g)
        acc_new = acc + g
        step = jnp.asarray(ctx.step, jnp.uint32)
        apply = ((step + jnp.uint32(1)) % jnp.uint32(n)) == 0
        new_inputs = dict(ctx._inputs)
        new_inputs["Grad"] = [acc_new / jnp.asarray(n, acc_new.dtype)]
        c2 = ExecContext(ctx.op, new_inputs, step=ctx.step, seed=ctx.seed,
                         mesh=ctx.mesh, env=ctx.env)
        outs = lower(c2)
        gated = {}
        for slot, val in outs.items():
            old = _alias_input(ctx, slot)
            gated[slot] = val if old is None else jnp.where(apply, val, old)
        gated["GradAccOut"] = jnp.where(
            apply, jnp.zeros_like(acc_new), acc_new)
        return gated
    return wrapped


def _gated_inplace(lower):
    """Gate an in-place helper op (increment of the LR-decay counter,
    scale of adam/adamax beta-pow accumulators) so its state advances once
    per EFFECTIVE batch: under merge_n=N the update lands only on apply
    steps (reference batch-merge kept per-iteration cadence for these)."""
    import functools

    @functools.wraps(lower)
    def wrapped(ctx):
        n = int(ctx.attr("merge_n", 0) or 0)
        outs = lower(ctx)
        if n <= 1:
            return outs
        jnp = _jnp()
        step = jnp.asarray(ctx.step, jnp.uint32)
        apply = ((step + jnp.uint32(1)) % jnp.uint32(n)) == 0
        x = ctx.input("X")
        return {s: jnp.where(apply, v, x) for s, v in outs.items()}
    return wrapped


def _install_merge_gates():
    from . import registry as _reg
    for t in MERGEABLE_OPT_OPS:
        od = _reg._REGISTRY.get(t)
        if od is not None and not getattr(od.lower, "_merge_gated", False):
            od.lower = _merge_gated(od.lower)
            od.lower._merge_gated = True
    for t in ("increment", "scale"):
        od = _reg._REGISTRY.get(t)
        if od is not None and not getattr(od.lower, "_merge_gated", False):
            od.lower = _gated_inplace(od.lower)
            od.lower._merge_gated = True


_install_merge_gates()


# ---------------------------------------------------------------------------
# average_accumulates (operators/average_accumulates_op.h): the sliding-
# window parameter-average accumulator behind ModelAverage. Three sum
# buffers avoid precision loss: sum_1 accumulates each step, rolls into
# sum_2 every kMaxNumAccumulates steps, and when the window exceeds
# min/max/rate bounds everything rolls into sum_3 and the window restarts.
# Branch-free jnp.where encoding of the reference's host branches.
# ---------------------------------------------------------------------------

@register_op("average_accumulates", stateful=True)
def _average_accumulates(ctx):
    jnp = _jnp()
    p = ctx.input("param")
    s1 = ctx.input("in_sum_1")
    s2 = ctx.input("in_sum_2")
    s3 = ctx.input("in_sum_3")
    num_acc = ctx.input("in_num_accumulates").reshape(()).astype(jnp.int32)
    old_num = ctx.input("in_old_num_accumulates").reshape(()) \
        .astype(jnp.int32)
    num_upd = ctx.input("in_num_updates").reshape(()).astype(jnp.int32)
    avg_window = ctx.attr("average_window", 0.0)
    max_w = int(ctx.attr("max_average_window", 10000))
    min_w = int(ctx.attr("min_average_window", 10000))
    k_max = 16384            # kMaxNumAccumulates, average_accumulates_op.h

    num_upd = num_upd + 1
    num_acc = num_acc + 1
    s1 = s1 + p.astype(s1.dtype)
    move = (num_upd % k_max) == 0
    s2 = jnp.where(move, s2 + s1, s2)
    s1 = jnp.where(move, jnp.zeros_like(s1), s1)
    # the reference's std::min<int64_t>(max_window, num_updates *
    # average_window) TRUNCATES the float product toward zero before
    # the compare, so the roll fires at num_acc == floor(product) —
    # one step earlier than a float compare would
    window = jnp.minimum(
        jnp.asarray(max_w, jnp.int32),
        jnp.floor(num_upd.astype(jnp.float32)
                  * np.float32(avg_window)).astype(jnp.int32))
    roll = (num_acc >= min_w) & (num_acc >= window)
    s3 = jnp.where(roll, s1 + s2, s3)
    s1 = jnp.where(roll, jnp.zeros_like(s1), s1)
    s2 = jnp.where(roll, jnp.zeros_like(s2), s2)
    old_num = jnp.where(roll, num_acc, old_num)
    num_acc = jnp.where(roll, 0, num_acc)
    return {"out_sum_1": s1, "out_sum_2": s2, "out_sum_3": s3,
            "out_num_accumulates": num_acc.reshape((1,)),
            "out_old_num_accumulates": old_num.reshape((1,)),
            "out_num_updates": num_upd.reshape((1,))}
