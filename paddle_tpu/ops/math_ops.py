"""Math / elementwise / reduction / activation op lowerings.

Reference analogues: paddle/fluid/operators/activation_op.cc (~30 functors),
elementwise/*.cc, reduce_ops/*.cc, mul_op.cc, matmul_op.cc, sum_op.cc,
scale_op.cc, softmax_op.cc, cast_op.cc, clip_op.cc, cumsum_op.cc, topk_op.cc.

Each op is one pure jnp/lax function; XLA fuses chains of these into single
kernels on TPU, which replaces the reference's hand-fused kernels
(fused_elemwise_activation etc.) and the xbyak JIT codegen in operators/math.
Gradients come from the registry's generic jax.vjp maker.
"""

import functools

import numpy as np

from .registry import register_op


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# activations (activation_op.cc)
# ---------------------------------------------------------------------------

def _register_activation(name, fn):
    def lower(ctx, _fn=fn):
        return {"Out": _fn(ctx, ctx.input("X"))}
    register_op(name, lower)


def _act(fn):
    return lambda ctx, x: fn(x)


def _make_activations():
    import jax
    import jax.numpy as jnp
    from jax import nn as jnn
    acts = {
        "sigmoid": _act(jax.nn.sigmoid),
        "logsigmoid": _act(jax.nn.log_sigmoid),
        "exp": _act(jnp.exp),
        "relu": _act(jax.nn.relu),
        "tanh": _act(jnp.tanh),
        "tanh_shrink": _act(lambda x: x - jnp.tanh(x)),
        "sqrt": _act(jnp.sqrt),
        "rsqrt": _act(lambda x: 1.0 / jnp.sqrt(x)),
        "abs": _act(jnp.abs),
        "ceil": _act(jnp.ceil),
        "floor": _act(jnp.floor),
        "cos": _act(jnp.cos),
        "sin": _act(jnp.sin),
        "round": _act(jnp.round),
        "reciprocal": _act(lambda x: 1.0 / x),
        "log": _act(jnp.log),
        "square": _act(jnp.square),
        "softplus": _act(jnn.softplus),
        "softsign": _act(jnn.soft_sign),
        "softshrink": lambda ctx, x: _softshrink(x, ctx.attr("lambda", 0.5)),
        "hard_shrink": lambda ctx, x: jnp.where(
            jnp.abs(x) > ctx.attr("threshold", 0.5), x, 0.0).astype(x.dtype),
        "brelu": lambda ctx, x: jnp.clip(x, ctx.attr("t_min", 0.0),
                                         ctx.attr("t_max", 24.0)),
        "leaky_relu": lambda ctx, x: jnn.leaky_relu(
            x, ctx.attr("alpha", 0.02)),
        "soft_relu": lambda ctx, x: jnp.log1p(
            jnp.exp(jnp.clip(x, -ctx.attr("threshold", 40.0),
                             ctx.attr("threshold", 40.0)))),
        "elu": lambda ctx, x: jnn.elu(x, ctx.attr("alpha", 1.0)),
        "relu6": lambda ctx, x: jnp.clip(x, 0.0, ctx.attr("threshold", 6.0)),
        "pow": lambda ctx, x: jnp.power(x, ctx.attr("factor", 1.0)).astype(
            x.dtype),
        "stanh": lambda ctx, x: ctx.attr("scale_b", 1.7159) * jnp.tanh(
            ctx.attr("scale_a", 2.0 / 3.0) * x),
        "hard_sigmoid": lambda ctx, x: jnp.clip(
            ctx.attr("slope", 0.2) * x + ctx.attr("offset", 0.5), 0.0, 1.0),
        "swish": lambda ctx, x: x * jax.nn.sigmoid(ctx.attr("beta", 1.0) * x),
        "thresholded_relu": lambda ctx, x: jnp.where(
            x > ctx.attr("threshold", 1.0), x, 0.0).astype(x.dtype),
        # default is the exact erf form (torch's default; the 2018
        # reference has no gelu op) — later-era programs may carry an
        # 'approximate' attr requesting the tanh form
        "gelu": lambda ctx, x: jax.nn.gelu(
            x, approximate=bool(ctx.attr("approximate", False))),
        "erf": _act(jax.scipy.special.erf),
        "sign": _act(jnp.sign),
        "logical_not": _act(jnp.logical_not),
    }
    for name, fn in acts.items():
        _register_activation(name, fn)


def _softshrink(x, lam):
    jnp = _jnp()
    return jnp.where(x > lam, x - lam, jnp.where(x < -lam, x + lam, 0.0)
                     ).astype(x.dtype)


_make_activations()


# ---------------------------------------------------------------------------
# elementwise binary ops with fluid `axis` broadcasting (elementwise/*.cc)
# ---------------------------------------------------------------------------

def _broadcast_y(x, y, axis):
    """Fluid semantics: Y's shape matches a contiguous sub-sequence of X's
    shape starting at `axis` (axis == -1 aligns trailing dims)."""
    jnp = _jnp()
    if x.ndim == y.ndim:
        return y
    if axis is None:
        axis = -1
    if axis == -1:
        axis = x.ndim - y.ndim
    # strip trailing size-1 dims the reference tolerates (e.g. [N,1] bias)
    yshape = list(y.shape)
    while len(yshape) > 1 and yshape[-1] == 1 and \
            axis + len(yshape) > x.ndim:
        yshape = yshape[:-1]
    new_shape = [1] * axis + list(yshape) + \
        [1] * (x.ndim - axis - len(yshape))
    return jnp.reshape(y, new_shape)


def _register_elementwise(name, fn):
    def lower(ctx, _fn=fn):
        x, y = ctx.input("X"), ctx.input("Y")
        axis = ctx.attr("axis", -1)
        if ctx.lod_len("X") is not None and axis is not None and axis >= 1:
            axis += 1  # padded ragged layout inserts the time dim at 1
        y = _broadcast_y(x, y, axis)
        return {"Out": _fn(x, y)}
    register_op(name, lower)


def _make_elementwise():
    import jax.numpy as jnp
    for name, fn in {
        "elementwise_add": jnp.add,
        "elementwise_sub": jnp.subtract,
        "elementwise_mul": jnp.multiply,
        "elementwise_div": jnp.divide,
        "elementwise_min": jnp.minimum,
        "elementwise_max": jnp.maximum,
        "elementwise_pow": jnp.power,
        "elementwise_mod": jnp.mod,
        "elementwise_floordiv": jnp.floor_divide,
    }.items():
        _register_elementwise(name, fn)


_make_elementwise()


def _register_compare():
    import jax.numpy as jnp
    for name, fn in {
        "less_than": jnp.less, "less_equal": jnp.less_equal,
        "greater_than": jnp.greater, "greater_equal": jnp.greater_equal,
        "equal": jnp.equal, "not_equal": jnp.not_equal,
        "logical_and": jnp.logical_and, "logical_or": jnp.logical_or,
        "logical_xor": jnp.logical_xor,
    }.items():
        def lower(ctx, _fn=fn):
            x, y = ctx.input("X"), ctx.input("Y")
            if y is not None and x.ndim != y.ndim:
                y = _broadcast_y(x, y, ctx.attr("axis", -1))
            return {"Out": _fn(x, y) if y is not None else _fn(x)}
        register_op(name, lower)


_register_compare()


# ---------------------------------------------------------------------------
# matmul family (mul_op.cc, matmul_op.cc) — these hit the MXU; keep them as
# single dot_generals so XLA tiles them onto the systolic array.
# ---------------------------------------------------------------------------

def _flatten2d(x, num_col_dims):
    jnp = _jnp()
    lead = int(np.prod(x.shape[:num_col_dims])) if num_col_dims > 0 else 1
    return jnp.reshape(x, (lead, -1))


@register_op("mul")
def _mul(ctx):
    jnp = _jnp()
    x, y = ctx.input("X"), ctx.input("Y")
    xd = ctx.attr("x_num_col_dims", 1)
    yd = ctx.attr("y_num_col_dims", 1)
    if ctx.lod_len("X") is not None:
        # ragged input arrives padded [B, T, ...] (one extra leading dim vs
        # the build-time packed [rows, ...] convention) — shift the split
        xd += 1
    x2 = _flatten2d(x, xd)
    y2 = _flatten2d(y, yd)
    out = jnp.matmul(x2, y2)
    out_shape = x.shape[:xd] + y.shape[yd:]
    return {"Out": jnp.reshape(out, out_shape)}


@register_op("matmul")
def _matmul(ctx):
    jnp = _jnp()
    x, y = ctx.input("X"), ctx.input("Y")
    if ctx.attr("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if ctx.attr("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y)
    alpha = ctx.attr("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": out}


@register_op("sum")
def _sum(ctx):
    xs = ctx.inputs("X")
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": out}


@register_op("scale")
def _scale(ctx):
    x = ctx.input("X")
    s, b = ctx.attr("scale", 1.0), ctx.attr("bias", 0.0)
    if ctx.attr("bias_after_scale", True):
        return {"Out": x * s + b}
    return {"Out": (x + b) * s}


@register_op("clip")
def _clip(ctx):
    jnp = _jnp()
    return {"Out": jnp.clip(ctx.input("X"), ctx.attr("min"), ctx.attr("max"))}


@register_op("clip_by_norm")
def _clip_by_norm(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    max_norm = ctx.attr("max_norm")
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.minimum(max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": x * scale}


# ---------------------------------------------------------------------------
# reductions (reduce_ops/*, mean_op.cc, argmax, topk, cumsum)
# ---------------------------------------------------------------------------

def _register_reduce(name, fn):
    def lower(ctx, _fn=fn):
        x = ctx.input("X")
        if ctx.attr("reduce_all", False):
            dim = None
        else:
            dim = ctx.attr("dim", [0])
            if isinstance(dim, int):
                dim = [dim]
            dim = tuple(d % x.ndim for d in dim)
        out = _fn(x, axis=dim, keepdims=ctx.attr("keep_dim", False))
        return {"Out": out}
    register_op(name, lower)


def _make_reduces():
    import jax.numpy as jnp
    for name, fn in {
        "reduce_sum": jnp.sum, "reduce_mean": jnp.mean,
        "reduce_max": jnp.max, "reduce_min": jnp.min,
        "reduce_prod": jnp.prod,
        "reduce_all": jnp.all, "reduce_any": jnp.any,
    }.items():
        _register_reduce(name, fn)


_make_reduces()


@register_op("mean")
def _mean(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    lens = ctx.lod_len("X")
    if lens is not None:
        # ragged mean = mean over real rows only (packed semantics):
        # padded positions are excluded from both sum and count
        B, T = x.shape[0], x.shape[1]
        m = (jnp.arange(T)[None, :] < lens[:, None]).astype(x.dtype)
        m = m.reshape((B, T) + (1,) * (x.ndim - 2))
        per_step = int(np.prod(x.shape[2:])) if x.ndim > 2 else 1
        return {"Out": jnp.sum(x * m) /
                jnp.maximum(jnp.sum(lens).astype(x.dtype) * per_step, 1)}
    return {"Out": jnp.mean(x)}


@register_op("arg_max")
def _arg_max(ctx):
    jnp = _jnp()
    return {"Out": jnp.argmax(ctx.input("X"),
                              axis=ctx.attr("axis", -1)).astype(jnp.int64)}


@register_op("arg_min")
def _arg_min(ctx):
    jnp = _jnp()
    return {"Out": jnp.argmin(ctx.input("X"),
                              axis=ctx.attr("axis", -1)).astype(jnp.int64)}


@register_op("argsort")
def _argsort(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    idx = jnp.argsort(x, axis=axis)
    return {"Out": jnp.sort(x, axis=axis), "Indices": idx.astype(jnp.int64)}


@register_op("top_k")
def _top_k(ctx):
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    k = ctx.attr("k", 1)
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": vals, "Indices": idx.astype(jnp.int64)}


@register_op("cumsum")
def _cumsum(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    reverse = ctx.attr("reverse", False)
    y = jnp.flip(x, axis) if reverse else x
    out = jnp.cumsum(y, axis=axis, dtype=x.dtype)
    if ctx.attr("exclusive", False):
        out = out - y  # exclusive prefix = inclusive - self
    if reverse:
        out = jnp.flip(out, axis)
    return {"Out": out}


# ---------------------------------------------------------------------------
# softmax / normalization-ish math (softmax_op.cc w/ cudnn variant — on TPU a
# single jax.nn.softmax lowers to a fused stable exp-normalise)
# ---------------------------------------------------------------------------

@register_op("softmax")
def _softmax(ctx):
    import jax
    return {"Out": jax.nn.softmax(ctx.input("X"), axis=-1)}


@register_op("log_softmax")
def _log_softmax(ctx):
    import jax
    return {"Out": jax.nn.log_softmax(ctx.input("X"), axis=-1)}


@register_op("cast")
def _cast(ctx):
    from ..fluid import core as fcore
    out_dtype = fcore.convert_dtype_to_np(ctx.attr("out_dtype"))
    return {"Out": ctx.input("X").astype(out_dtype)}


@register_op("isfinite")
def _isfinite(ctx):
    jnp = _jnp()
    # reference isfinite_op reduces to a single bool: "is every element finite"
    return {"Out": jnp.all(jnp.isfinite(ctx.input("X"))).reshape((1,))}


@register_op("isinf")
def _isinf(ctx):
    jnp = _jnp()
    return {"Out": jnp.any(jnp.isinf(ctx.input("X"))).reshape((1,))}


@register_op("isnan")
def _isnan(ctx):
    jnp = _jnp()
    return {"Out": jnp.any(jnp.isnan(ctx.input("X"))).reshape((1,))}


@register_op("l2_normalize")
def _l2_normalize(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    eps = ctx.attr("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    y = x / jnp.maximum(norm, eps)
    return {"Out": y, "Norm": norm}


@register_op("squared_l2_norm")
def _squared_l2_norm(ctx):
    jnp = _jnp()
    return {"Out": jnp.sum(jnp.square(ctx.input("X"))).reshape((1,))}


@register_op("increment")
def _increment(ctx):
    x = ctx.input("X")
    return {"Out": x + np.asarray(ctx.attr("step", 1.0), dtype=x.dtype)}
