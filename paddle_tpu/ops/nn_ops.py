"""Neural-net op lowerings: conv/pool/norm/dropout/embedding/losses/metrics.

Reference analogues: conv_op.cc + conv_cudnn_op.cu, pool_op.cc, batch_norm_op,
layer_norm_op, dropout_op, lookup_table_op, cross_entropy_op,
softmax_with_cross_entropy_op, sigmoid_cross_entropy_with_logits_op,
accuracy_op (metrics/), one_hot_op, lrn_op, grid ops.

TPU notes: convs lower to lax.conv_general_dilated which XLA tiles onto the
MXU; the cuDNN-vs-plain kernel split in the reference collapses into one
lowering. Data layout is kept NCHW at the IR level for fluid API parity —
XLA's layout assignment transposes to the TPU-preferred layout internally.
"""

import numpy as np

from .registry import register_op


def _jnp():
    import jax.numpy as jnp
    return jnp


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


# ---------------------------------------------------------------------------
# convolution (conv_op.cc; cudnn variant conv_cudnn_op.cu)
# ---------------------------------------------------------------------------

def _img_layout(ctx):
    """Activation layout attr: NCHW (fluid default) or NHWC (TPU-preferred,
    channels-last — BN/elementwise chains keep the channel dim in the lane
    dimension of the (8,128) tile, reference conv_op.cc `data_format` /
    batch_norm_op.cc `data_layout`)."""
    return ctx.attr("data_format", None) or ctx.attr("data_layout", None) \
        or "NCHW"


def _grouped_conv(strides, padding, dilations, groups, layout):
    """Feature-grouped conv with a custom VJP.

    jax's builtin filter-gradient for a feature-grouped conv is a
    `batch_group_count` convolution, which XLA lowers pathologically:
    measured 9.1s vs 0.14s for the dense equivalent on a (2,256,56,56)
    NCHW input with groups=32 (the SE-ResNeXt cardinality) — ~64x, and
    the reason SE-ResNeXt training ran at 4.5 s/step on the TPU. The
    input gradient is itself a plain feature-grouped conv (fast), so
    only dW is replaced: extract the conv's input patches once and
    contract them against the cotangent as one group-batched einsum
    (maps to MXU batched matmul; fp32 accumulation), ~38x faster than
    the builtin form. Reference analogue: conv_grad kernels pick a
    grouped algo in cuDNN (conv_cudnn_op.cu) — the reshape trick is the
    TPU-native equivalent."""
    import jax
    import jax.numpy as jnp
    dn = (layout, "OIHW", layout)

    def base(x, w):
        return jax.lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            rhs_dilation=dilations, feature_group_count=groups,
            dimension_numbers=dn)

    @jax.custom_vjp
    def conv(x, w):
        return base(x, w)

    def fwd(x, w):
        return base(x, w), (x, w)

    def bwd(res, dy):
        x, w = res
        _, pull = jax.vjp(lambda x_: base(x_, w), x)
        dx, = pull(dy)
        o, ipg, kh, kw = w.shape
        n = x.shape[0]
        og, ik = o // groups, ipg * kh * kw
        # patches feature dim unravels (c, kh, kw) with c outermost, so
        # each group's ipg*kh*kw taps are one contiguous block
        p = jax.lax.conv_general_dilated_patches(
            x, (kh, kw), strides, padding, rhs_dilation=dilations,
            dimension_numbers=dn)
        if layout != "NHWC":
            s = p.shape[2] * p.shape[3]
            dw = jnp.einsum(
                "ngis,ngos->goi",
                p.reshape(n, groups, ik, s),
                dy.reshape(n, groups, og, s),
                preferred_element_type=jnp.float32)
        else:  # NHWC
            s = p.shape[1] * p.shape[2]
            dw = jnp.einsum(
                "nsgi,nsgo->goi",
                p.reshape(n, s, groups, ik),
                dy.reshape(n, s, groups, og),
                preferred_element_type=jnp.float32)
        dw = dw.reshape(o, ipg, kh, kw).astype(w.dtype)
        return dx, dw

    conv.defvjp(fwd, bwd)
    return conv


@register_op("conv2d")
def _conv2d(ctx):
    import jax
    x, w = ctx.input("Input"), ctx.input("Filter")
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = _pair(ctx.attr("paddings", [0, 0]))
    dilations = _pair(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", 1) or 1
    layout = _img_layout(ctx)
    padding = [(pads[0], pads[0]), (pads[1], pads[1])]
    # filters stay OIHW in either layout so parameters/checkpoints are
    # layout-independent; XLA transposes once during layout assignment.
    # NOTE: no explicit preferred_element_type — the TPU MXU already
    # accumulates bf16 inputs in fp32 internally, and an explicit fp32
    # output type breaks jax's conv transpose rule under AMP (the f32
    # cotangent meets the bf16 residual operand)
    if groups > 1:
        out = _grouped_conv(strides, padding, dilations, groups, layout)(x, w)
    else:
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            rhs_dilation=dilations, feature_group_count=groups,
            dimension_numbers=(layout, "OIHW", layout))
    out = out.astype(x.dtype)
    if ctx.has_input("Bias"):
        bshape = (1, -1, 1, 1) if layout != "NHWC" else (1, 1, 1, -1)
        out = out + ctx.input("Bias").reshape(bshape)
    # named checkpoint: identity in normal execution; lets a rematerialized
    # step (jax.checkpoint + save_only_these_names("conv_out")) keep conv
    # outputs and recompute the cheap BN/activation tail in backward —
    # the HBM-traffic lever in ROOFLINE.md
    from jax.ad_checkpoint import checkpoint_name
    out = checkpoint_name(out, "conv_out")
    return {"Output": out}


@register_op("depthwise_conv2d")
def _depthwise_conv2d(ctx):
    return _conv2d(ctx)


@register_op("conv2d_dynamic_filter")
def _conv2d_dynamic_filter(ctx):
    """Per-SAMPLE dynamic filters (reference ConvOperator,
    legacy/gserver ConvOp with a filter produced by another layer):
    Input [B, C, H, W] is convolved with Filter [B, nf*C*fy*fx] — each
    sample uses its own filter values. Lowered as ONE grouped conv via
    the feature-group trick: x -> [1, B*C, H, W], filters ->
    [B*nf, C, fy, fx], feature_group_count=B, so group b convolves
    sample b's channels with sample b's filters on the MXU (no python
    loop over the batch)."""
    import jax
    jnp = _jnp()
    x, f = ctx.input("Input"), ctx.input("Filter")
    nf = int(ctx.attr("num_filters"))
    fy = int(ctx.attr("filter_size_y"))
    fx = int(ctx.attr("filter_size_x"))
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = _pair(ctx.attr("paddings", [0, 0]))
    B, C = x.shape[0], x.shape[1]
    w = f.reshape(B * nf, C, fy, fx).astype(x.dtype)
    xg = x.reshape(1, B * C, x.shape[2], x.shape[3])
    out = jax.lax.conv_general_dilated(
        xg, w, window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        feature_group_count=B,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    out = out.reshape(B, nf, out.shape[2], out.shape[3]).astype(x.dtype)
    return {"Output": out}


@register_op("conv3d")
def _conv3d(ctx):
    import jax
    x, w = ctx.input("Input"), ctx.input("Filter")
    strides = _pair(ctx.attr("strides", [1, 1, 1]), 3)
    pads = _pair(ctx.attr("paddings", [0, 0, 0]), 3)
    dilations = _pair(ctx.attr("dilations", [1, 1, 1]), 3)
    groups = ctx.attr("groups", 1) or 1
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(p, p) for p in pads],
        rhs_dilation=dilations, feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    return {"Output": out.astype(x.dtype)}


@register_op("conv2d_transpose")
def _conv2d_transpose(ctx):
    import jax
    x, w = ctx.input("Input"), ctx.input("Filter")  # w: [in_c, out_c/g, kh, kw]
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = _pair(ctx.attr("paddings", [0, 0]))
    dilations = _pair(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", 1) or 1
    # jax conv_transpose applies `padding` directly to the dilated input;
    # the reference's deconv padding p (output = (H-1)s + d(k-1) - 2p + 1)
    # maps to jax padding d*(k-1) - p per side
    jpads = [(dilations[i] * (w.shape[2 + i] - 1) - pads[i],) * 2
             for i in range(2)]

    def one_group(xg, wg):
        return jax.lax.conv_transpose(
            xg, wg, strides=strides, padding=jpads,
            rhs_dilation=dilations,
            # with transpose_kernel=True the rhs spec describes the
            # FORWARD conv kernel, so storage [in_c, out_c/g, kh, kw]
            # maps to OIHW (O=in_c); torch-verified in test_op_tail
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            transpose_kernel=True)

    if groups == 1:
        out = one_group(x, w)
    else:
        # grouped deconv (conv_transpose_op.cc `groups`): filter is
        # [in_c, out_c/g, kh, kw]; slice input channels per group and
        # concat the per-group outputs on the channel axis
        if x.shape[1] % groups != 0:
            raise ValueError(
                "conv2d_transpose: input channels (%d) must be divisible "
                "by groups (%d)" % (x.shape[1], groups))
        icg = x.shape[1] // groups
        out = _jnp().concatenate(
            [one_group(x[:, g * icg:(g + 1) * icg],
                       w[g * icg:(g + 1) * icg]) for g in range(groups)],
            axis=1)
    return {"Output": out.astype(x.dtype)}


@register_op("conv3d_transpose")
def _conv3d_transpose(ctx):
    import jax
    x, w = ctx.input("Input"), ctx.input("Filter")  # w: [in_c, out_c/g,...]
    strides = _pair(ctx.attr("strides", [1, 1, 1]), 3)
    pads = _pair(ctx.attr("paddings", [0, 0, 0]), 3)
    dilations = _pair(ctx.attr("dilations", [1, 1, 1]), 3)
    groups = ctx.attr("groups", 1) or 1
    jpads = [(dilations[i] * (w.shape[2 + i] - 1) - pads[i],) * 2
             for i in range(3)]

    def one_group(xg, wg):
        return jax.lax.conv_transpose(
            xg, wg, strides=strides, padding=jpads,
            rhs_dilation=dilations,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
            transpose_kernel=True)

    if groups == 1:
        out = one_group(x, w)
    else:
        if x.shape[1] % groups != 0:
            raise ValueError(
                "conv3d_transpose: input channels (%d) must be divisible "
                "by groups (%d)" % (x.shape[1], groups))
        icg = x.shape[1] // groups
        out = _jnp().concatenate(
            [one_group(x[:, g * icg:(g + 1) * icg],
                       w[g * icg:(g + 1) * icg]) for g in range(groups)],
            axis=1)
    return {"Output": out.astype(x.dtype)}


# ---------------------------------------------------------------------------
# pooling (pool_op.cc)
# ---------------------------------------------------------------------------

def ceil_extra_pad(extent, k, s, p):
    """Extra high-side padding so lax.reduce_window (floor semantics)
    reproduces the reference's ceil_mode output size (pool_op.h
    OutputSizePool with ceil)."""
    out_ceil = (extent + 2 * p - k + s - 1) // s + 1
    return max((out_ceil - 1) * s + k - (extent + 2 * p), 0)


@register_op("pool2d")
def _pool2d(ctx):
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    ptype = ctx.attr("pooling_type", "max")
    ksize = _pair(ctx.attr("ksize", [2, 2]))
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = _pair(ctx.attr("paddings", [0, 0]))
    ceil_mode = bool(ctx.attr("ceil_mode", False))
    layout = _img_layout(ctx)
    hw = (2, 3) if layout != "NHWC" else (1, 2)
    if ctx.attr("global_pooling", False):
        ksize = (x.shape[hw[0]], x.shape[hw[1]])
        strides = ksize
        pads = (0, 0)
        ceil_mode = False
    if ctx.attr("adaptive", False) and tuple(ksize) == (1, 1):
        # adaptive 1x1 == global pooling
        ksize = (x.shape[hw[0]], x.shape[hw[1]])
        strides, pads = ksize, (0, 0)
        ceil_mode = False
    extras = [ceil_extra_pad(x.shape[hw[i]], ksize[i], strides[i], pads[i])
              if ceil_mode else 0 for i in range(2)]
    if layout != "NHWC":
        window = (1, 1) + ksize
        stride = (1, 1) + strides
        padding = ((0, 0), (0, 0), (pads[0], pads[0] + extras[0]),
                   (pads[1], pads[1] + extras[1]))
    else:
        window = (1,) + ksize + (1,)
        stride = (1,) + strides + (1,)
        padding = ((0, 0), (pads[0], pads[0] + extras[0]),
                   (pads[1], pads[1] + extras[1]), (0, 0))
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, np.asarray(init, x.dtype), jax.lax.max,
                                    window, stride, padding)
    else:
        summed = jax.lax.reduce_window(
            x, np.asarray(0, x.dtype), jax.lax.add, window, stride, padding)
        if ctx.attr("exclusive", True) and (pads[0] or pads[1] or
                                            any(extras)):
            ones = jnp.ones(x.shape, x.dtype)
            counts = jax.lax.reduce_window(
                ones, np.asarray(0, x.dtype), jax.lax.add, window, stride,
                padding)
            out = summed / counts
        else:
            out = summed / np.asarray(ksize[0] * ksize[1], x.dtype)
    return {"Out": out}


# ---------------------------------------------------------------------------
# normalization (batch_norm_op.cc, layer_norm_op.cc, group_norm_op.cc, lrn)
# ---------------------------------------------------------------------------

@register_op("batch_norm", stateful=True)
def _batch_norm(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    mean, var = ctx.input("Mean"), ctx.input("Variance")
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    is_test = ctx.attr("is_test", False)
    # NHWC: channel is the LAST dim at any rank (reference batch_norm_op.cc
    # uses data_layout to pick dim C for both 3-d and 4-d inputs)
    c_axis = (x.ndim - 1) if _img_layout(ctx) == "NHWC" else 1
    axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = tuple(-1 if i == c_axis else 1 for i in range(x.ndim))
    if is_test or ctx.attr("use_global_stats", False):
        use_mean, use_var = mean, var
        saved_mean = mean
        saved_inv_std = 1.0 / jnp.sqrt(var + eps)
        mean_out, var_out = mean, var
    else:
        # one fused pass over x: sum and sum-of-squares reduce together
        # (multi-output fusion), where jnp.var would add a second reduction
        # that depends on the mean — an extra HBM round trip per BN layer,
        # ~20% of a ResNet-50 train step at batch 128
        xf = x.astype(jnp.float32)
        n = np.prod([x.shape[i] for i in axes]).astype(np.float32)
        s1 = jnp.sum(xf, axis=axes)
        s2 = jnp.sum(jnp.square(xf), axis=axes)
        use_mean = s1 / n
        use_var = jnp.maximum(s2 / n - jnp.square(use_mean), 0.0)
        mean_out = mean * momentum + use_mean * (1.0 - momentum)
        var_out = var * momentum + use_var * (1.0 - momentum)
        saved_mean = use_mean
        saved_inv_std = 1.0 / jnp.sqrt(use_var + eps)
    xhat = (x - use_mean.reshape(bshape).astype(x.dtype)) * \
        saved_inv_std.reshape(bshape).astype(x.dtype)
    y = xhat * scale.reshape(bshape).astype(x.dtype) + \
        bias.reshape(bshape).astype(x.dtype)
    return {"Y": y, "MeanOut": mean_out, "VarianceOut": var_out,
            "SavedMean": saved_mean, "SavedVariance": saved_inv_std}


@register_op("layer_norm")
def _layer_norm(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    begin = ctx.attr("begin_norm_axis", 1)
    eps = ctx.attr("epsilon", 1e-5)
    axes = tuple(range(begin, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    inv = 1.0 / jnp.sqrt(var + eps)
    y = (x - mean) * inv
    shape = x.shape[begin:]
    if ctx.has_input("Scale"):
        y = y * ctx.input("Scale").reshape(shape)
    if ctx.has_input("Bias"):
        y = y + ctx.input("Bias").reshape(shape)
    red = tuple(range(begin))
    return {"Y": y, "Mean": jnp.reshape(mean, [int(np.prod(x.shape[:begin]))]),
            "Variance": jnp.reshape(var, [int(np.prod(x.shape[:begin]))])}


@register_op("group_norm")
def _group_norm(ctx):
    jnp = _jnp()
    x = ctx.input("X")  # NCHW
    groups = ctx.attr("groups", 32)
    eps = ctx.attr("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, groups, c // groups) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) / jnp.sqrt(var + eps)).reshape(x.shape)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    if ctx.has_input("Scale"):
        y = y * ctx.input("Scale").reshape(bshape)
    if ctx.has_input("Bias"):
        y = y + ctx.input("Bias").reshape(bshape)
    return {"Y": y, "Mean": mean.reshape((n, groups)),
            "Variance": var.reshape((n, groups))}


@register_op("lrn")
def _lrn(ctx):
    import jax
    jnp = _jnp()
    x = ctx.input("X")  # NCHW
    n = ctx.attr("n", 5)
    k = ctx.attr("k", 2.0)
    alpha = ctx.attr("alpha", 1e-4)
    beta = ctx.attr("beta", 0.75)
    sq = jnp.square(x)
    # channel window [c - (n-1)//2, c + n-1 - (n-1)//2] — asymmetric
    # for even n (lrn_op.cc pre_pad = (n-1)/2)
    pre = (n - 1) // 2
    acc = jax.lax.reduce_window(
        sq, np.asarray(0, x.dtype), jax.lax.add,
        (1, n, 1, 1), (1, 1, 1, 1),
        ((0, 0), (pre, n - 1 - pre), (0, 0), (0, 0)))
    # MidOut is the PRE-power scale k + alpha*sum (the reference's grad
    # kernel consumes it in that form); the power lives only in Out
    mid = k + alpha * acc
    return {"Out": x * mid ** (-beta), "MidOut": mid}


# ---------------------------------------------------------------------------
# dropout (dropout_op.cc) — per-step PRNG threaded by the executor
# ---------------------------------------------------------------------------

@register_op("dropout")
def _dropout(ctx):
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    p = ctx.attr("dropout_prob", 0.5)
    if ctx.attr("is_test", False) or p == 0.0:
        imp = ctx.attr("dropout_implementation", "downgrade_in_infer")
        if imp == "downgrade_in_infer" and ctx.attr("is_test", False):
            return {"Out": x * np.asarray(1.0 - p, x.dtype),
                    "Mask": jnp.ones_like(x)}
        return {"Out": x, "Mask": jnp.ones_like(x)}
    keep = jax.random.bernoulli(ctx.rng_key(), 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    imp = ctx.attr("dropout_implementation", "downgrade_in_infer")
    if imp == "upscale_in_train":
        out = x * mask / np.asarray(1.0 - p, x.dtype)
    else:
        out = x * mask
    return {"Out": out, "Mask": mask}


# ---------------------------------------------------------------------------
# embedding (lookup_table_op.cc). Sparse-grad (SelectedRows) path is realised
# as a dense scatter-add under vjp — XLA turns it into an efficient TPU
# scatter; the sharded-table path lives in parallel/.
# ---------------------------------------------------------------------------

@register_op("lookup_table")
def _lookup_table(ctx):
    jnp = _jnp()
    w, ids = ctx.input("W"), ctx.input("Ids")
    squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
    flat_ids = ids.reshape(ids.shape[:-1]) if squeeze_last else ids
    padding_idx = ctx.attr("padding_idx", -1)
    out = jnp.take(w, flat_ids.astype(jnp.int32), axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (flat_ids != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    return {"Out": out}


@register_op("one_hot")
def _one_hot(ctx):
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    depth = ctx.attr("depth")
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x.reshape(x.shape[:-1])
    return {"Out": jax.nn.one_hot(x.astype(jnp.int32), depth,
                                  dtype=jnp.float32)}


# ---------------------------------------------------------------------------
# losses (cross_entropy_op.cc, softmax_with_cross_entropy_op.cc, ...)
# ---------------------------------------------------------------------------

@register_op("cross_entropy")
def _cross_entropy(ctx):
    jnp = _jnp()
    x, label = ctx.input("X"), ctx.input("Label")
    eps = 1e-8
    if ctx.attr("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, eps)), axis=-1,
                        keepdims=True)
    else:
        if label.ndim == x.ndim:
            label = label.reshape(label.shape[:-1])
        picked = jnp.take_along_axis(
            x, label[..., None].astype(jnp.int32), axis=-1)
        loss = -jnp.log(jnp.maximum(picked, eps))
    return {"Y": loss}


@register_op("softmax_with_cross_entropy")
def _softmax_with_ce(ctx):
    import jax
    jnp = _jnp()
    logits, label = ctx.input("Logits"), ctx.input("Label")
    logp = jax.nn.log_softmax(logits, axis=-1)
    if ctx.attr("soft_label", False):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        if label.ndim == logits.ndim:
            lab = label.reshape(label.shape[:-1])
        else:
            lab = label
        picked = jnp.take_along_axis(logp, lab[..., None].astype(jnp.int32),
                                     axis=-1)
        ignore = ctx.attr("ignore_index", -100)
        loss = -picked
        if ignore >= 0:
            loss = jnp.where(lab[..., None] == ignore, 0.0, loss)
    return {"Softmax": jnp.exp(logp), "Loss": loss}


@register_op("sigmoid_cross_entropy_with_logits")
def _sigmoid_ce(ctx):
    import jax
    jnp = _jnp()
    x, label = ctx.input("X"), ctx.input("Label")
    # stable: max(x,0) - x*z + log(1+exp(-|x|))
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = ctx.attr("ignore_index", -100)
    if ignore >= 0:
        loss = jnp.where(label == ignore, 0.0, loss)
    return {"Out": loss}


@register_op("square_error_cost")
def _square_error_cost(ctx):
    jnp = _jnp()
    return {"Out": jnp.square(ctx.input("X") - ctx.input("Y"))}


@register_op("huber_loss")
def _huber_loss(ctx):
    jnp = _jnp()
    x, y = ctx.input("X"), ctx.input("Y")
    delta = ctx.attr("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r,
                     delta * (ar - 0.5 * delta))
    return {"Out": loss, "Residual": r}


@register_op("smooth_l1_loss")
def _smooth_l1(ctx):
    jnp = _jnp()
    x, y = ctx.input("X"), ctx.input("Y")
    sigma = ctx.attr("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    if ctx.has_input("InsideWeight"):
        diff = diff * ctx.input("InsideWeight")
    ad = jnp.abs(diff)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff, ad - 0.5 / s2)
    if ctx.has_input("OutsideWeight"):
        loss = loss * ctx.input("OutsideWeight")
    out = jnp.sum(loss.reshape(loss.shape[0], -1), axis=1, keepdims=True)
    return {"Out": out, "Diff": diff}


@register_op("log_loss")
def _log_loss(ctx):
    jnp = _jnp()
    p, label = ctx.input("Predicted"), ctx.input("Labels")
    eps = ctx.attr("epsilon", 1e-4)
    loss = -label * jnp.log(p + eps) - (1 - label) * jnp.log(1 - p + eps)
    return {"Loss": loss}


@register_op("hinge_loss")
def _hinge_loss(ctx):
    jnp = _jnp()
    logits, labels = ctx.input("Logits"), ctx.input("Labels")
    return {"Loss": jnp.maximum(0.0, 1.0 - (2 * labels - 1) * logits)}


@register_op("margin_rank_loss")
def _margin_rank_loss(ctx):
    jnp = _jnp()
    x1, x2, label = ctx.input("X1"), ctx.input("X2"), ctx.input("Label")
    margin = ctx.attr("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": out, "Activated": (out > 0).astype(x1.dtype)}


# ---------------------------------------------------------------------------
# metrics (metrics/accuracy_op.cc, auc_op.cc)
# ---------------------------------------------------------------------------

@register_op("accuracy")
def _accuracy(ctx):
    jnp = _jnp()
    pred_idx = ctx.input("Indices")
    label = ctx.input("Label")
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label.reshape(-1)
    correct = jnp.any(pred_idx == label[:, None], axis=1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = np.float32(pred_idx.shape[0])
    return {"Accuracy": (num_correct / total).reshape((1,)),
            "Correct": num_correct.astype(jnp.int32).reshape((1,)),
            "Total": jnp.asarray([total], jnp.int32)}


# ---------------------------------------------------------------------------
# image ops used by detection/vision models
# ---------------------------------------------------------------------------

def _interp_grid(ctx, in_sz, out_sz):
    """Source grid for the interpolate family. The 2018 reference op is
    unconditionally align-corners (interpolate_op.h:171-174: ratio =
    (in-1)/(out-1), src = ratio*dst) and has no attr; the layer API also
    accepts the later-era align_corners=False with align_mode 0
    (half-pixel: src = ratio*(dst+0.5)-0.5) / 1 (src = ratio*dst,
    ratio = in/out), honored here."""
    jnp = _jnp()
    dst = jnp.arange(out_sz, dtype=jnp.float32)
    if ctx.attr("align_corners", True):
        ratio = (in_sz - 1) / (out_sz - 1) if out_sz > 1 else 0.0
        return dst * jnp.float32(ratio)
    ratio = in_sz / out_sz
    if ctx.attr("align_mode", 1) == 0:
        return jnp.maximum(dst * jnp.float32(ratio)
                           + jnp.float32(0.5 * ratio - 0.5), 0.0)
    return dst * jnp.float32(ratio)


@register_op("bilinear_interp")
def _bilinear_interp(ctx):
    jnp = _jnp()
    x = ctx.input("X")  # NCHW
    out_h = ctx.attr("out_h")
    out_w = ctx.attr("out_w")
    H, W = x.shape[2], x.shape[3]
    y = _interp_grid(ctx, H, out_h)
    xw = _interp_grid(ctx, W, out_w)
    y0 = jnp.minimum(jnp.floor(y).astype(jnp.int32), H - 1)
    x0 = jnp.minimum(jnp.floor(xw).astype(jnp.int32), W - 1)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    dy = (y - y0).astype(x.dtype)[:, None]        # [out_h, 1]
    dx = (xw - x0).astype(x.dtype)[None, :]       # [1, out_w]
    # gather the four corner planes at [B, C, out_h, out_w] directly
    # (full-width row intermediates would be W/out_w times larger)
    yg0, yg1 = y0[:, None], y1[:, None]           # [out_h, 1]
    xg0, xg1 = x0[None, :], x1[None, :]           # [1, out_w]
    tl, tr = x[:, :, yg0, xg0], x[:, :, yg0, xg1]
    bl, br = x[:, :, yg1, xg0], x[:, :, yg1, xg1]
    top = tl * (1 - dx) + tr * dx
    bot = bl * (1 - dx) + br * dx
    return {"Out": (top * (1 - dy) + bot * dy).astype(x.dtype)}


@register_op("nearest_interp")
def _nearest_interp(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    out_h, out_w = ctx.attr("out_h"), ctx.attr("out_w")
    H, W = x.shape[2], x.shape[3]
    # reference rounds the source grid (interpolate_op.h:33)
    yi = jnp.clip((_interp_grid(ctx, H, out_h) + 0.5).astype(jnp.int32),
                  0, H - 1)
    xi = jnp.clip((_interp_grid(ctx, W, out_w) + 0.5).astype(jnp.int32),
                  0, W - 1)
    return {"Out": x[:, :, yi][..., xi]}


@register_op("pad2d")
def _pad2d(ctx):
    """pad2d_op.cc: [top, bottom, left, right] spatial padding in
    constant/reflect/edge mode, honoring data_format (the NHWC kernel
    pads axes 1-2, not 2-3)."""
    jnp = _jnp()
    x = ctx.input("X")
    p = ctx.attr("paddings", [0, 0, 0, 0])
    mode = ctx.attr("mode", "constant")
    value = ctx.attr("pad_value", 0.0)
    fmt = ctx.attr("data_format", "NCHW")
    hw = ((p[0], p[1]), (p[2], p[3]))
    pads = ((0, 0), (0, 0)) + hw if fmt != "NHWC" else \
        ((0, 0),) + hw + ((0, 0),)
    if mode == "constant":
        return {"Out": jnp.pad(x, pads, constant_values=value)}
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": jnp.pad(x, pads, mode=jmode)}


@register_op("pad")
def _pad(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    p = ctx.attr("paddings")
    pads = tuple((p[2 * i], p[2 * i + 1]) for i in range(x.ndim))
    return {"Out": jnp.pad(x, pads,
                           constant_values=ctx.attr("pad_value", 0.0))}


# ---------------------------------------------------------------------------
# sparse embedding gradients (SelectedRows — selected_rows.h; sparse kernel
# of lookup_table_grad, lookup_table_op.cc is_sparse path)
# ---------------------------------------------------------------------------

@register_op("lookup_table_sparse_grad")
def _lookup_table_sparse_grad(ctx):
    """Sparse grad: emit a SelectedRowsValue (rows = the looked-up ids,
    values = the output cotangent rows) instead of a dense scatter into the
    full table."""
    jnp = _jnp()
    from ..fluid.selected_rows import SelectedRowsValue
    w = ctx.input("W")
    ids = ctx.input("Ids")
    d_out = ctx.input("GRAD:Out")
    squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
    flat_ids = (ids.reshape(ids.shape[:-1]) if squeeze_last
                else ids).reshape(-1).astype(jnp.int32)
    D = w.shape[-1]
    values = d_out.reshape(-1, D)
    padding_idx = ctx.attr("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        values = values * (flat_ids != padding_idx)[:, None].astype(
            values.dtype)
    return {"GRAD:W": SelectedRowsValue(flat_ids, values, w.shape[0])}


def _lookup_table_grad_maker(op, block, grad_map, no_grad_set,
                             bw_ctx=None):
    """Emit the sparse grad op when is_sparse is set; decline (None) for
    the dense default so the generic vjp path runs."""
    if not op.attrs.get("is_sparse", False):
        return None
    from ..fluid.framework import grad_var_name
    w_name = op.inputs["W"][0]
    out_name = op.outputs["Out"][0]
    if w_name in no_grad_set or out_name not in grad_map:
        return None
    # shared tables need grad accumulation across ALL consumers — not just
    # other lookups: a tied softmax head (mul on the same W) contributes a
    # dense partial grad that would silently overwrite the sparse one.
    # Decline to the dense path, whose fan-in summing machinery handles it,
    # whenever W feeds any other op.
    consumers = sum(1 for o in block.ops
                    if o is not op and
                    any(w_name in names for names in o.inputs.values()))
    if consumers > 0:
        return None
    gname = grad_var_name(w_name)
    w_var = block._find_var_recursive(w_name)
    gvar = block.create_var(name=gname, dtype=w_var.dtype,
                            shape=w_var.shape, stop_gradient=True)
    gvar.is_selected_rows = True
    block.append_op(
        type="lookup_table_sparse_grad",
        inputs={"W": [w_name], "Ids": list(op.inputs["Ids"]),
                "GRAD:Out": [grad_map[out_name]]},
        outputs={"GRAD:W": [gname]},
        attrs={"padding_idx": op.attrs.get("padding_idx", -1),
               "op_role": "Backward"},
        infer_shape=False)
    grad_map[w_name] = gname
    return [gname]


from .registry import set_grad_maker as _set_gm  # noqa: E402
_set_gm("lookup_table", _lookup_table_grad_maker)
