"""Vision op lowerings beyond the conv/pool core.

Reference analogues: grid_sampler_op.cc, affine_grid_op.cc,
affine_channel_op.cc, pool_op.cc (pool3d), conv_transpose_op.cc
(conv3d_transpose), unpool_op.cc, spp_op.cc, shuffle_channel (reshape
trick), psroi_pool_op.cc, crop_op.cc, random_crop_op.cc, im2sequence_op.cc,
activation_op.cc (selu) — SURVEY.md §2.2 dense-math / tensor-manip rows.

TPU notes: samplers are expressed as gathers + bilinear weights (XLA fuses
the four corner gathers); pooling variants ride lax.reduce_window which XLA
lowers to the TPU's windowed reductions.
"""

import numpy as np

from .registry import register_op
from .nn_ops import _pair, ceil_extra_pad


def _jnp():
    import jax.numpy as jnp
    return jnp


def _triple(v):
    return _pair(v, 3)


def _bilinear_nchw(feat, ys, xs, align=True):
    """feat [C,H,W]; ys/xs [...] pixel coords -> [C, ...] bilinear samples,
    zero outside."""
    jnp = _jnp()
    H, W = feat.shape[1], feat.shape[2]
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0

    def at(yy, xx):
        yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        inb = (yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1)
        return feat[:, yi, xi] * inb.astype(feat.dtype)[None]

    return (at(y0, x0) * ((1 - wy1) * (1 - wx1)) +
            at(y0, x0 + 1) * ((1 - wy1) * wx1) +
            at(y0 + 1, x0) * (wy1 * (1 - wx1)) +
            at(y0 + 1, x0 + 1) * (wy1 * wx1))


@register_op("grid_sampler")
def _grid_sampler(ctx):
    """X [N,C,H,W], Grid [N,H',W',2] normalized to [-1,1] -> [N,C,H',W']
    (grid_sampler_op.cc: bilinear, zero padding, align_corners)."""
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    grid = ctx.input("Grid")
    H, W = x.shape[2], x.shape[3]

    def one(feat, g):
        xs = (g[..., 0] + 1.0) * (W - 1) / 2.0
        ys = (g[..., 1] + 1.0) * (H - 1) / 2.0
        return _bilinear_nchw(feat, ys, xs)

    return {"Output": jax.vmap(one)(x, grid)}


@register_op("affine_grid")
def _affine_grid(ctx):
    """Theta [N,2,3] -> Grid [N,H,W,2] of normalized sample coords
    (affine_grid_op.cc)."""
    jnp = _jnp()
    theta = ctx.input("Theta")
    if ctx.has_input("OutputShape"):
        # output H/W define array shapes, which XLA requires static; a
        # traced OutputShape tensor cannot be supported (the layer rejects
        # Variables up front with a clear error)
        shape = [int(d) for d in np.asarray(ctx.input("OutputShape"))]
    else:
        shape = [int(d) for d in ctx.attr("output_shape")]
    H, W = shape[2], shape[3]
    ys = jnp.linspace(-1.0, 1.0, H)
    xs = jnp.linspace(-1.0, 1.0, W)
    xg, yg = jnp.meshgrid(xs, ys)            # [H, W]
    ones = jnp.ones_like(xg)
    base = jnp.stack([xg, yg, ones], axis=-1)    # [H, W, 3]
    out = jnp.einsum("hwk,nck->nhwc", base.astype(theta.dtype), theta)
    return {"Output": out}


@register_op("affine_channel")
def _affine_channel(ctx):
    x = ctx.input("X")
    layout = ctx.attr("data_layout", "NCHW")
    cshape = (1, -1, 1, 1) if layout != "NHWC" else (1, 1, 1, -1)
    scale = ctx.input("Scale")
    bias = ctx.input("Bias")
    out = x
    if scale is not None:
        out = out * scale.reshape(cshape)
    if bias is not None:
        out = out + bias.reshape(cshape)
    return {"Out": out}


@register_op("pool3d")
def _pool3d(ctx):
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    ptype = ctx.attr("pooling_type", "max")
    ksize = _triple(ctx.attr("ksize", [2, 2, 2]))
    strides = _triple(ctx.attr("strides", [1, 1, 1]))
    pads = _triple(ctx.attr("paddings", [0, 0, 0]))
    ceil_mode = bool(ctx.attr("ceil_mode", False))
    if ctx.attr("global_pooling", False):
        ksize = (x.shape[2], x.shape[3], x.shape[4])
        strides, pads = ksize, (0, 0, 0)
        ceil_mode = False
    window = (1, 1) + ksize
    stride = (1, 1) + strides
    extras = [ceil_extra_pad(x.shape[2 + i], ksize[i], strides[i], pads[i])
              if ceil_mode else 0 for i in range(3)]
    padding = ((0, 0), (0, 0)) + tuple(
        (p, p + e) for p, e in zip(pads, extras))
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, np.asarray(init, x.dtype),
                                    jax.lax.max, window, stride, padding)
    else:
        summed = jax.lax.reduce_window(
            x, np.asarray(0, x.dtype), jax.lax.add, window, stride, padding)
        if ctx.attr("exclusive", True) and (any(pads) or any(extras)):
            ones = jnp.ones(x.shape, x.dtype)
            counts = jax.lax.reduce_window(
                ones, np.asarray(0, x.dtype), jax.lax.add, window, stride,
                padding)
            out = summed / counts
        else:
            out = summed / np.asarray(
                ksize[0] * ksize[1] * ksize[2], x.dtype)
    return {"Out": out}


# conv3d_transpose lives in nn_ops.py (grouped + torch-verified numerics)


@register_op("unpool")
def _unpool(ctx):
    """Max unpooling (unpool_op.cc): X [N,C,h,w] pooled values, Indices
    [N,C,h,w] flat positions within each HxW output plane."""
    jnp = _jnp()
    x = ctx.input("X")
    idx = ctx.input("Indices").astype(jnp.int32)
    ksize = ctx.attr("ksize", [2, 2])
    strides = ctx.attr("strides", [2, 2])
    pads = ctx.attr("paddings", [0, 0])
    N, C, h, w = x.shape
    H = (h - 1) * strides[0] - 2 * pads[0] + ksize[0]
    W = (w - 1) * strides[1] - 2 * pads[1] + ksize[1]
    flat = jnp.zeros((N, C, H * W), x.dtype)
    out = flat.at[
        jnp.arange(N)[:, None, None],
        jnp.arange(C)[None, :, None],
        idx.reshape(N, C, -1)].add(x.reshape(N, C, -1))
    return {"Out": out.reshape(N, C, H, W)}


def _adaptive_pool2d_masked(x, bins_h, bins_w, ptype):
    """Adaptive pooling via per-bin masks (integer boundaries matching the
    reference's ADAPT_START/END). x [N,C,H,W] -> [N,C,bins_h,bins_w]."""
    jnp = _jnp()
    N, C, H, W = x.shape
    hi = jnp.arange(H)
    wi = jnp.arange(W)
    ib = np.arange(bins_h)
    jb = np.arange(bins_w)
    hstart = np.floor(ib * H / bins_h).astype(np.int64)
    hend = np.ceil((ib + 1) * H / bins_h).astype(np.int64)
    wstart = np.floor(jb * W / bins_w).astype(np.int64)
    wend = np.ceil((jb + 1) * W / bins_w).astype(np.int64)
    hmask = (hi[None, :] >= hstart[:, None]) & (hi[None, :] < hend[:, None])
    wmask = (wi[None, :] >= wstart[:, None]) & (wi[None, :] < wend[:, None])
    m = (hmask[:, None, :, None] & wmask[None, :, None, :])  # [bh,bw,H,W]
    xb = x[:, :, None, None, :, :]                            # [N,C,1,1,H,W]
    if ptype == "max":
        big = jnp.where(m[None, None], xb,
                        jnp.asarray(-np.inf, x.dtype))
        return jnp.max(big, axis=(4, 5))
    big = jnp.where(m[None, None], xb, jnp.asarray(0, x.dtype))
    counts = m.sum(axis=(2, 3)).astype(x.dtype)               # [bh,bw]
    return jnp.sum(big, axis=(4, 5)) / counts[None, None]


def _spp_level_bounds(size, bins):
    """spp_op.h level geometry: kernel = ceil(size/bins), stride =
    kernel, symmetric padding (kernel*bins - size + 1)/2, windows
    clipped to the input (math/pooling.cc) — NOT adaptive integer
    bins; the partitions differ whenever size % bins != 0."""
    k = -(-size // bins)
    p = (k * bins - size + 1) // 2
    starts = [max(i * k - p, 0) for i in range(bins)]
    ends = [min(i * k - p + k, size) for i in range(bins)]
    return starts, ends


@register_op("spp")
def _spp(ctx):
    """Spatial pyramid pooling (spp_op.h): levels 0..pyramid_height-1,
    each pooled to 2^l x 2^l on the reference's ceil-kernel grid and
    flattened, concat over levels; avg is exclusive (clipped-window
    counts). Pinned by tests/test_spp_oracle.py. Documented deviation:
    the reference grid can produce EMPTY edge windows (pad >= remaining
    extent, e.g. H=5 at bins=4) which its kernel fills with accumulator
    initials (-FLT_MAX / 0-divided-by-0); this lowering emits -inf/NaN
    sentinels there instead."""
    jnp = _jnp()
    x = ctx.input("X")
    height = int(ctx.attr("pyramid_height", 1))
    ptype = ctx.attr("pooling_type", "max")
    N, _, H, W = x.shape
    hi = jnp.arange(H)
    wi = jnp.arange(W)
    outs = []
    for l in range(height):
        bins = 2 ** l
        hs, he = _spp_level_bounds(H, bins)
        ws, we = _spp_level_bounds(W, bins)
        hmask = (hi[None, :] >= np.asarray(hs)[:, None]) & \
                (hi[None, :] < np.asarray(he)[:, None])      # [bins, H]
        wmask = (wi[None, :] >= np.asarray(ws)[:, None]) & \
                (wi[None, :] < np.asarray(we)[:, None])      # [bins, W]
        m = hmask[:, None, :, None] & wmask[None, :, None, :]
        xb = x[:, :, None, None, :, :]                       # [N,C,1,1,H,W]
        if ptype == "max":
            big = jnp.where(m[None, None], xb,
                            jnp.asarray(-np.inf, x.dtype))
            p = jnp.max(big, axis=(4, 5))
        else:
            big = jnp.where(m[None, None], xb, jnp.asarray(0, x.dtype))
            counts = m.sum(axis=(2, 3)).astype(x.dtype)
            p = jnp.sum(big, axis=(4, 5)) / counts[None, None]
        outs.append(p.reshape(N, -1))
    return {"Out": jnp.concatenate(outs, axis=1)}


@register_op("shuffle_channel")
def _shuffle_channel(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    group = int(ctx.attr("group", 1))
    N, C, H, W = x.shape
    out = x.reshape(N, group, C // group, H, W).transpose(0, 2, 1, 3, 4)
    return {"Out": out.reshape(N, C, H, W)}


@register_op("psroi_pool")
def _psroi_pool(ctx):
    """Position-sensitive RoI pooling (psroi_pool_op.cc): input channels
    C = output_channels * ph * pw; bin (i, j) of output channel c averages
    input channel c*ph*pw + i*pw + j over the bin region."""
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    rois = ctx.input("ROIs")
    lens = ctx.lod_len("ROIs")
    oc = int(ctx.attr("output_channels"))
    ph = int(ctx.attr("pooled_height", 1))
    pw = int(ctx.attr("pooled_width", 1))
    scale = float(ctx.attr("spatial_scale", 1.0))
    B, C, H, W = x.shape
    squeeze = rois.ndim == 2
    if squeeze:
        rois = rois[None]
    R = rois.shape[1]
    hi = jnp.arange(H)
    wi = jnp.arange(W)

    def one_roi(feat, roi):
        # reference rounds the RAW roi coords, adds 1 to the end, THEN
        # scales (psroi_pool_op.h roi_start_w = round(rois[0]) * scale,
        # roi_end_w = (round(rois[2]) + 1) * scale)
        x1 = jnp.round(roi[0]) * scale
        y1 = jnp.round(roi[1]) * scale
        x2 = (jnp.round(roi[2]) + 1.0) * scale
        y2 = (jnp.round(roi[3]) + 1.0) * scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h = rh / ph
        bin_w = rw / pw
        ib = jnp.arange(ph, dtype=feat.dtype)
        jb = jnp.arange(pw, dtype=feat.dtype)
        hstart = jnp.clip(jnp.floor(ib * bin_h + y1), 0, H)
        hend = jnp.clip(jnp.ceil((ib + 1) * bin_h + y1), 0, H)
        wstart = jnp.clip(jnp.floor(jb * bin_w + x1), 0, W)
        wend = jnp.clip(jnp.ceil((jb + 1) * bin_w + x1), 0, W)
        hmask = (hi[None, :] >= hstart[:, None]) & \
                (hi[None, :] < hend[:, None])                 # [ph, H]
        wmask = (wi[None, :] >= wstart[:, None]) & \
                (wi[None, :] < wend[:, None])                 # [pw, W]
        m = hmask[:, None, :, None] & wmask[None, :, None, :]  # [ph,pw,H,W]
        fgrp = feat.reshape(oc, ph, pw, H, W)                  # c,i,j,H,W
        masked = jnp.where(m[None], fgrp, jnp.asarray(0, feat.dtype))
        s = jnp.sum(masked, axis=(3, 4))                        # [oc, ph, pw]
        cnt = jnp.maximum(m.sum(axis=(2, 3)).astype(feat.dtype), 1.0)
        return s / cnt[None]

    out = jax.vmap(lambda feat, rs: jax.vmap(
        lambda r: one_roi(feat, r))(rs))(x, rois)
    if lens is not None:
        valid = jnp.arange(R)[None, :] < lens[:, None]
        out = jnp.where(valid[:, :, None, None, None], out, 0.0)
    if squeeze:
        out = out[0]
    return {"Out": out}


@register_op("crop")
def _crop(ctx):
    """crop_op.cc: slice X at offsets to shape (or Y's shape). The slice
    extent must be static (XLA), but the offsets may be a traced tensor —
    lax.dynamic_slice takes traced start indices."""
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    if ctx.has_input("Y") and ctx.input("Y") is not None:
        shape = ctx.input("Y").shape
    else:
        shape = [int(d) for d in ctx.attr("shape")]
    off_in = ctx.input("Offsets") if ctx.has_input("Offsets") else None
    if off_in is not None:
        offsets = [off_in[i] for i in range(x.ndim)]
    else:
        offsets = [int(d) for d in
                   ctx.attr("offsets", [0] * x.ndim) or [0] * x.ndim]
    # a non-positive extent keeps the input's remaining extent past the
    # offset (so -1 in the batch slot crops every row); with runtime
    # Offsets that extent is data-dependent, so it needs a concrete
    # (eager) offset — under jit dynamic_slice would silently clamp the
    # start to 0 and return the uncropped axis
    if any(d <= 0 for d in shape) and off_in is not None and \
            isinstance(off_in, jax.core.Tracer):
        raise NotImplementedError(
            "crop with runtime Offsets and a non-positive shape entry "
            "has a data-dependent output extent — pass static offsets "
            "via the attr, give every shape entry a positive size, or "
            "run eagerly")
    shape = [int(x.shape[i]) - int(offsets[i]) if d <= 0 else d
             for i, d in enumerate(shape)]
    return {"Out": jax.lax.dynamic_slice(x, offsets, shape)}


@register_op("scale_sub_region")
def _scale_sub_region(ctx):
    """Scale a per-sample [c1,c2,h1,h2,w1,w2] sub-box of an NCHW tensor
    by `value` (reference legacy ScaleSubRegionLayer; indices 1-based
    inclusive). Built from broadcasted range masks so offsets may be
    traced tensors."""
    jnp = _jnp()
    x = ctx.input("X")          # [B, C, H, W]
    idx = ctx.input("Indices").astype(jnp.int32)   # [B, 6]
    value = ctx.attr("value", 1.0)
    B, C, H, W = x.shape

    def axis_mask(lo, hi, n):
        r = jnp.arange(n)[None, :]
        return ((r >= (lo - 1)[:, None]) & (r <= (hi - 1)[:, None]))

    mc = axis_mask(idx[:, 0], idx[:, 1], C)[:, :, None, None]
    mh = axis_mask(idx[:, 2], idx[:, 3], H)[:, None, :, None]
    mw = axis_mask(idx[:, 4], idx[:, 5], W)[:, None, None, :]
    m = (mc & mh & mw)
    return {"Out": jnp.where(m, x * value, x)}


@register_op("random_crop")
def _random_crop(ctx):
    """random_crop_op.cc: crop the trailing dims to `shape` at a random
    offset (per-op seed via the functional rng)."""
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    shape = [int(d) for d in ctx.attr("shape")]
    k = len(shape)
    lead = x.shape[:x.ndim - k]
    key = ctx.rng_key()
    starts = []
    for i, (extent, want) in enumerate(zip(x.shape[x.ndim - k:], shape)):
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, extent - want + 1))
    offsets = [0] * len(lead) + [s for s in starts]
    return {"Out": jax.lax.dynamic_slice(
        x, offsets, list(lead) + shape), "SeedOut": None}


@register_op("im2sequence")
def _im2sequence(ctx):
    """im2sequence_op.cc: [N,C,H,W] -> rows of flattened kh*kw*C patches;
    ragged output [N, oh*ow, C*kh*kw] with oh*ow rows per image."""
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    kernels = ctx.attr("kernels", [1, 1])
    strides = ctx.attr("strides", [1, 1])
    pads = ctx.attr("paddings", [0, 0, 0, 0])
    N, C, H, W = x.shape
    kh, kw = int(kernels[0]), int(kernels[1])
    sh, sw = int(strides[0]), int(strides[1])
    pu, pl, pd, pr = (int(p) for p in pads)
    oh = (H + pu + pd - kh) // sh + 1
    ow = (W + pl + pr - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), [(pu, pd), (pl, pr)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))   # [N, C*kh*kw, oh, ow]
    out = patches.reshape(N, C * kh * kw, oh * ow).transpose(0, 2, 1)
    lens = jnp.full((N,), oh * ow, jnp.int32)
    return {"Out": out, "Out@LOD_LEN": lens}


@register_op("selu")
def _selu(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    scale = ctx.attr("scale", 1.0507009873554805)
    alpha = ctx.attr("alpha", 1.6732632423543772)
    return {"Out": scale * jnp.where(
        x > 0, x, alpha * (jnp.exp(x) - 1.0))}
