"""TPU op registry: every op type is a pure JAX lowering (see registry.py).

Importing this package registers the full op set — the analogue of the static
registrar objects REGISTER_OPERATOR produces in the reference
(op_registry.h:185)."""

from . import registry
from . import math_ops       # noqa: F401
from . import nn_ops         # noqa: F401
from . import tensor_ops     # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import sequence_ops   # noqa: F401
from . import control_flow_ops  # noqa: F401
from . import distributed_ops   # noqa: F401
from . import loss_ops          # noqa: F401
from . import beam_ops          # noqa: F401
from . import detection_ops     # noqa: F401
from . import vision_ops        # noqa: F401
from . import misc_ops          # noqa: F401
from . import io_ops            # noqa: F401
from . import compat_ops        # noqa: F401
from . import csp_ops           # noqa: F401
from . import pallas_kernels    # noqa: F401
from . import quant_ops         # noqa: F401

from .registry import (  # noqa: F401
    register_op, get_op_def, has_op, registered_ops, infer_shape, ExecContext,
    call_lower, set_amp, amp_enabled,
)
