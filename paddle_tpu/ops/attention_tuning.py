"""Shape -> block-config autotuner cache for the Pallas flash-attention
kernels.

Reference analogue: none — the reference's CUDA kernels hard-code launch
geometry per architecture. On TPU the MXU-aligned (block_q, block_kv)
tiling choice decides whether the kernel lands near its roofline (the
Tensor Processing Primitives observation, PAPERS.md), so the choice is
data: ``tools/bench_attention.py --tune`` sweeps configs on the real chip
and persists the winners here; ``flash_attention`` consults the cache at
TRACE time, so every later jit/export of the same shape rides the tuned
geometry with zero runtime cost.

Precedence (deterministic, trace-time):
  1. explicit block args at the call site (expert override)
  2. nonzero ``FLAGS.flash_block_*`` (process-wide override, per field)
  3. the tune-cache entry for (seq_len, head_dim, causal, dtype)
  4. the MXU-aligned heuristic default

Storage rides the repo-wide kernel-tuning registry
(paddle_tpu/compile_cache.py, namespace ``flash_attention`` under
``FLAGS.compile_cache_dir``/tuning/) — the same atomic
write-temp→fsync→rename commit discipline and FLAGS-configurable store
as the AOT compile cache, invalidated by file mtime so a fresh
``--tune`` run takes effect without a process restart.  A nonzero
``FLAGS.attention_tune_cache`` (or an explicit ``record(path=...)``)
keeps the legacy single-JSON behavior for that path — the expert/test
override; otherwise the legacy default JSON
(<repo>/tools/attention_tune_cache.json) remains a READ-ONLY fallback
so pre-registry tune files keep working.  Entries are keyed by
``S{seq}_D{head_dim}_c{0|1}_{dtype}``.
"""

import json
import os
import threading

TUNING_NAMESPACE = "flash_attention"
DEQUANT_NAMESPACE = "dequant_matmul"

__all__ = ["AttentionConfig", "get_config", "default_config", "lookup",
           "record", "cache_path", "config_key", "attention_vmem_bytes",
           "decode_config_key", "get_decode_config", "record_decode",
           "dequant_config_key", "get_dequant_config", "record_dequant",
           "MIN_LANES"]

MIN_LANES = 128     # TPU lane width: the last-dim alignment quantum
_SUBLANES = 8       # f32 sublane quantum; bf16 wants 16

# candidate block edges, largest first; all MXU/VPU aligned down to the
# interpret-mode floor (tiny CPU-suite shapes legitimately use 4/2/1)
_CANDIDATES = (512, 256, 128, 64, 32, 16, 8, 4, 2, 1)


class AttentionConfig(object):
    """Immutable block geometry for one attention shape."""

    __slots__ = ("block_q", "block_kv", "block_q_bwd", "block_kv_bwd")

    def __init__(self, block_q, block_kv, block_q_bwd=None,
                 block_kv_bwd=None):
        object.__setattr__(self, "block_q", int(block_q))
        object.__setattr__(self, "block_kv", int(block_kv))
        object.__setattr__(self, "block_q_bwd",
                           int(block_q_bwd or block_q))
        object.__setattr__(self, "block_kv_bwd",
                           int(block_kv_bwd or block_kv))

    def __setattr__(self, *a):
        raise AttributeError("AttentionConfig is immutable")

    def asdict(self):
        return {"block_q": self.block_q, "block_kv": self.block_kv,
                "block_q_bwd": self.block_q_bwd,
                "block_kv_bwd": self.block_kv_bwd}

    def __repr__(self):
        return ("AttentionConfig(bq=%d, bkv=%d, bq_bwd=%d, bkv_bwd=%d)"
                % (self.block_q, self.block_kv, self.block_q_bwd,
                   self.block_kv_bwd))

    def __eq__(self, other):
        return (isinstance(other, AttentionConfig)
                and self.asdict() == other.asdict())

    def __ne__(self, other):
        return not self.__eq__(other)


def config_key(seq_len, head_dim, causal, dtype):
    return "S%d_D%d_c%d_%s" % (int(seq_len), int(head_dim),
                               1 if causal else 0, str(dtype))


def cache_path():
    from ..flags import FLAGS
    p = FLAGS.attention_tune_cache
    if p:
        return os.path.expanduser(p)
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "tools",
        "attention_tune_cache.json")


# path -> (mtime, entries); a --tune run in another process shows up via
# the mtime check, a record() in this one invalidates explicitly
_memo = {}
_memo_lock = threading.Lock()


def _load(path):
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return {}
    with _memo_lock:
        hit = _memo.get(path)
        if hit is not None and hit[0] == mtime:
            return hit[1]
    try:
        with open(path) as f:
            raw = json.load(f)
        entries = raw.get("configs", raw) if isinstance(raw, dict) else {}
    except (OSError, ValueError):
        entries = {}
    with _memo_lock:
        _memo[path] = (mtime, entries)
    return entries


def _legacy_override():
    """Nonzero FLAGS.attention_tune_cache pins the legacy single-JSON
    path exclusively (expert/test override)."""
    from ..flags import FLAGS
    return bool(FLAGS.attention_tune_cache)


def _to_config(rec):
    if not isinstance(rec, dict):
        return None
    try:
        return AttentionConfig(rec["block_q"], rec["block_kv"],
                               rec.get("block_q_bwd"),
                               rec.get("block_kv_bwd"))
    except (KeyError, TypeError, ValueError):
        return None


def lookup(seq_len, head_dim, causal, dtype):
    """Tune-cache entry for the shape, or None on a miss.  Resolution:
    the legacy path exclusively when FLAGS.attention_tune_cache is set;
    otherwise the kernel-tuning registry first, then the legacy default
    JSON as a read-only fallback."""
    key = config_key(seq_len, head_dim, causal, dtype)
    if _legacy_override():
        return _to_config(_load(cache_path()).get(key))
    from .. import compile_cache as cc
    cfg = _to_config(cc.tuning_lookup(TUNING_NAMESPACE, key))
    if cfg is not None:
        return cfg
    return _to_config(_load(cache_path()).get(key))


def record(seq_len, head_dim, causal, dtype, config, extra=None,
           path=None):
    """Persist a tuned config (read-modify-write; bench_attention --tune).

    Default: one record committed to the repo-wide kernel-tuning
    registry (namespace ``flash_attention``).  With an explicit `path`
    or FLAGS.attention_tune_cache set, the legacy single-JSON file is
    written instead — atomically, via the shared write-temp→fsync→rename
    helper: a tuner killed mid-record leaves the previous file intact
    plus a stale tmp, never a truncated JSON that poisons later traces."""
    rec = config.asdict()
    if extra:
        rec.update(extra)
    key = config_key(seq_len, head_dim, causal, dtype)
    if path is None and not _legacy_override():
        from .. import compile_cache as cc
        return cc.tuning_record(TUNING_NAMESPACE, key, rec)
    path = path or cache_path()
    entries = dict(_load(path))
    entries[key] = rec
    d = os.path.dirname(path)
    if d and not os.path.isdir(d):
        os.makedirs(d)
    from ..fluid import checkpoint
    checkpoint.atomic_write(
        path, json.dumps(entries, indent=2, sort_keys=True).encode(),
        chaos_point="tuning_tmp_written")
    with _memo_lock:
        _memo.pop(path, None)
    return path


def _pick_block(seq_len, cap):
    for b in _CANDIDATES:
        if b <= cap and seq_len % b == 0:
            # a 1-row block only ever makes sense for a 1-row sequence;
            # a prime length degrades to the XLA path instead
            if b == 1 and seq_len > 1:
                return None
            return b
    return None


def default_config(seq_len, head_dim, dtype="bfloat16"):
    """MXU-aligned heuristic: the largest candidate edge <= 128 that
    divides the sequence (128 = one MXU pass per tile edge; larger tiles
    only win when --tune proves it on the target shape). Returns None
    when no candidate divides seq_len (caller falls back to plain
    attention)."""
    b = _pick_block(seq_len, MIN_LANES)
    if b is None:
        return None
    return AttentionConfig(b, b, b, b)


def get_config(seq_len, head_dim, causal, dtype):
    """Trace-time config resolution: FLAGS override > cache > heuristic.
    Fields are resolved independently so a single-flag override rides the
    cache for the rest. Returns None when no geometry divides seq_len."""
    from ..flags import FLAGS
    base = lookup(seq_len, head_dim, causal, dtype) \
        or default_config(seq_len, head_dim, dtype)
    if base is None:
        return None
    picked = {}
    for field in ("block_q", "block_kv", "block_q_bwd", "block_kv_bwd"):
        v = int(getattr(FLAGS, "flash_" + field))
        picked[field] = v if v > 0 else getattr(base, field)
    return AttentionConfig(**picked)


def decode_config_key(seq_len, head_dim, dtype):
    """Tuning key of the decode-attention kernel's kv-block edge for one
    (slot-cache length, head_dim, dtype) shape — same registry namespace
    as the training kernels, distinct key family."""
    return "DEC_S%d_D%d_%s" % (int(seq_len), int(head_dim), str(dtype))


def _decode_block(rec):
    if isinstance(rec, dict):
        try:
            return int(rec["block_kv"]) or None
        except (KeyError, TypeError, ValueError):
            return None
    return None


def get_decode_config(seq_len, head_dim, dtype):
    """kv-block edge for the decode-attention kernel (the serving decode
    step gathers K/V from the slot cache in blocks of this many cached
    positions).  Resolution mirrors get_config: nonzero
    ``FLAGS.flash_block_kv`` > tune-registry entry > the MXU-aligned
    heuristic.  None when no candidate divides the cache length (the
    caller falls back to the plain-XLA gather)."""
    from ..flags import FLAGS
    v = int(FLAGS.flash_block_kv)
    if v > 0:
        return v if seq_len % v == 0 else None
    key = decode_config_key(seq_len, head_dim, dtype)
    if _legacy_override():
        b = _decode_block(_load(cache_path()).get(key))
    else:
        from .. import compile_cache as cc
        b = _decode_block(cc.tuning_lookup(TUNING_NAMESPACE, key))
        if b is None:
            b = _decode_block(_load(cache_path()).get(key))
    if b is not None and seq_len % b == 0:
        return b
    return _pick_block(seq_len, MIN_LANES)


def record_decode(seq_len, head_dim, dtype, block_kv, extra=None,
                  path=None):
    """Persist a tuned decode kv-block edge (bench_serving --decode
    --tune writes these) through the same store/legacy resolution as
    record()."""
    rec = {"block_kv": int(block_kv)}
    if extra:
        rec.update(extra)
    key = decode_config_key(seq_len, head_dim, dtype)
    if path is None and not _legacy_override():
        from .. import compile_cache as cc
        return cc.tuning_record(TUNING_NAMESPACE, key, rec)
    path = path or cache_path()
    entries = dict(_load(path))
    entries[key] = rec
    d = os.path.dirname(path)
    if d and not os.path.isdir(d):
        os.makedirs(d)
    from ..fluid import checkpoint
    checkpoint.atomic_write(
        path, json.dumps(entries, indent=2, sort_keys=True).encode(),
        chaos_point="tuning_tmp_written")
    with _memo_lock:
        _memo.pop(path, None)
    return path


def dequant_config_key(m, k, n, dtype):
    """Tuning key of the fused dequant-matmul kernel's block geometry for
    one (rows, reduce, channels, activation-dtype) shape — its own
    registry namespace (``dequant_matmul``), one JSON under
    <store>/tuning/ like every other kernel family."""
    return "M%d_K%d_N%d_%s" % (int(m), int(k), int(n), str(dtype))


def _dequant_blocks(rec):
    if not isinstance(rec, dict):
        return None
    try:
        bm, bk, bn = (int(rec["block_m"]), int(rec["block_k"]),
                      int(rec["block_n"]))
    except (KeyError, TypeError, ValueError):
        return None
    return (bm, bk, bn) if bm > 0 and bk > 0 and bn > 0 else None


def get_dequant_config(m, k, n, dtype):
    """(block_m, block_k, block_n) for the fused dequant-matmul kernel,
    or None when no candidate geometry tiles the shape (the caller falls
    back to the plain-XLA dequant composition).  Resolution mirrors the
    attention kernels: tuned registry entry first, then the MXU-aligned
    heuristic — block edges <= 128 that divide each dim, with the row
    block allowed down to 1 (serving buckets legitimately run batch 1,
    and one padded bucket row tile is still a full-lane MXU pass)."""
    from .. import compile_cache as cc
    key = dequant_config_key(m, k, n, dtype)
    tuned = _dequant_blocks(cc.tuning_lookup(DEQUANT_NAMESPACE, key))
    if tuned is not None:
        bm, bk, bn = tuned
        if m % bm == 0 and k % bk == 0 and n % bn == 0:
            return tuned
    bm = next((b for b in _CANDIDATES if b <= MIN_LANES and m % b == 0),
              None)
    bk = _pick_block(k, MIN_LANES * 4)
    bn = _pick_block(n, MIN_LANES * 2)
    if bm is None or bk is None or bn is None:
        return None
    return (bm, bk, bn)


def record_dequant(m, k, n, dtype, block_m, block_k, block_n,
                   extra=None):
    """Persist a tuned dequant-matmul geometry to the kernel-tuning
    registry (namespace ``dequant_matmul``) with the shared atomic
    commit discipline; a killed tuner leaves the previous registry
    intact."""
    rec = {"block_m": int(block_m), "block_k": int(block_k),
           "block_n": int(block_n)}
    if extra:
        rec.update(extra)
    from .. import compile_cache as cc
    return cc.tuning_record(DEQUANT_NAMESPACE,
                            dequant_config_key(m, k, n, dtype), rec)


def attention_vmem_bytes(head_dim, block_q, block_kv, itemsize=2):
    """Rough single-program VMEM footprint of the forward kernel: the
    q/k/v tiles, the fp32 scores tile, and the fp32 accumulator + m/l
    state (lane-replicated). The tuner skips configs past the budget
    instead of discovering Mosaic allocation failures on chip."""
    return (block_q * head_dim * itemsize          # q tile
            + 2 * block_kv * head_dim * itemsize   # k + v tiles
            + block_q * block_kv * 4               # scores/p (fp32)
            + block_q * head_dim * 4               # acc (fp32)
            + 2 * block_q * MIN_LANES * 4)         # m + l (fp32)
