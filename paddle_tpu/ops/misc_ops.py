"""Misc tensor / sequence op lowerings completing the §2.2 inventory.

Reference analogues: norm_op.cc, squared_l2_distance_op.cc,
pad_constant_like_op.cc, label_smooth_op.cc, bilinear_tensor_product_op.cc,
scatter_nd_add_op (gather_scatter family), sequence_scatter_op.cc,
sequence_expand_as_op.cc, gather_tree (beam ancestry), row_conv_op.cc,
fsp_op (distillation), fake_quantize_op.cc / fake_dequantize_op.cc.
"""

import numpy as np

from .registry import register_op


def _jnp():
    import jax.numpy as jnp
    return jnp


@register_op("norm")
def _norm(ctx):
    """l2-normalize along axis; emits Out and the Norm denominator."""
    jnp = _jnp()
    x = ctx.input("X")
    axis = int(ctx.attr("axis", 1))
    eps = float(ctx.attr("epsilon", 1e-10))
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    return {"Out": x / norm, "Norm": norm}


@register_op("squared_l2_distance")
def _squared_l2_distance(ctx):
    jnp = _jnp()
    x, y = ctx.input("X"), ctx.input("Y")
    sub = x - y                      # y may broadcast [1, D] -> [N, D]
    sub = jnp.broadcast_to(sub, (x.shape[0],) + sub.shape[1:])
    out = jnp.sum(sub * sub, axis=tuple(range(1, sub.ndim)),
                  keepdims=False)[:, None]
    return {"Out": out, "sub_result": sub}


@register_op("pad_constant_like")
def _pad_constant_like(ctx):
    jnp = _jnp()
    x, y = ctx.input("X"), ctx.input("Y")
    val = ctx.attr("pad_value", 0.0)
    pads = [(0, int(xd) - int(yd)) for xd, yd in zip(x.shape, y.shape)]
    return {"Out": jnp.pad(y, pads, constant_values=val)}


@register_op("label_smooth")
def _label_smooth(ctx):
    jnp = _jnp()
    x = ctx.input("X")       # one-hot-ish distribution [..., K]
    eps = float(ctx.attr("epsilon", 0.0))
    dist = ctx.input("PriorDist")
    K = x.shape[-1]
    if dist is not None:
        prior = dist.reshape((1,) * (x.ndim - 1) + (K,))
        return {"Out": (1.0 - eps) * x + eps * prior}
    return {"Out": (1.0 - eps) * x + eps / K}


@register_op("bilinear_tensor_product")
def _bilinear_tensor_product(ctx):
    jnp = _jnp()
    x = ctx.input("X")       # [N, M]
    y = ctx.input("Y")       # [N, P]
    w = ctx.input("Weight")  # [K, M, P]
    out = jnp.einsum("nm,kmp,np->nk", x, w, y)
    b = ctx.input("Bias")
    if b is not None:
        out = out + b.reshape(1, -1)
    return {"Out": out}


@register_op("scatter_nd_add")
def _scatter_nd_add(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    index = ctx.input("Index").astype(jnp.int32)
    updates = ctx.input("Updates")
    idx = tuple(index[..., i] for i in range(index.shape[-1]))
    return {"Out": x.at[idx].add(updates)}


@register_op("scatter_nd")
def _scatter_nd(ctx):
    jnp = _jnp()
    index = ctx.input("Index").astype(jnp.int32)
    updates = ctx.input("Updates")
    shape = [int(d) for d in ctx.attr("shape")]
    zeros = jnp.zeros(shape, updates.dtype)
    idx = tuple(index[..., i] for i in range(index.shape[-1]))
    return {"Out": zeros.at[idx].add(updates)}


@register_op("sequence_scatter")
def _sequence_scatter(ctx):
    """X [B, D]; Ids ragged [B, T] + lens; Updates ragged [B, T]:
    out[b, ids[b,t]] += updates[b,t] for valid t (sequence_scatter_op.cc)."""
    jnp = _jnp()
    x = ctx.input("X")
    ids = ctx.input("Ids")
    upd = ctx.input("Updates")
    lens = ctx.lod_len("Ids")
    if ids.ndim == 3:
        ids = ids[..., 0]
    if upd.ndim == 3:
        upd = upd[..., 0]
    B, T = ids.shape
    if lens is None:
        valid = jnp.ones((B, T), bool)
    else:
        valid = jnp.arange(T)[None, :] < lens[:, None]
    upd = jnp.where(valid, upd, 0)
    rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    out = x.at[rows.reshape(-1), ids.reshape(-1).astype(jnp.int32)].add(
        upd.reshape(-1))
    return {"Out": out}


@register_op("sequence_expand_as")
def _sequence_expand_as(ctx):
    """X [B, D] one row per sequence -> ragged [B, T, D] repeating each row
    len(Y_b) times (sequence_expand_as_op.cc)."""
    jnp = _jnp()
    x = ctx.input("X")
    y = ctx.input("Y")
    ylens = ctx.lod_len("Y")
    T = y.shape[1] if y.ndim > 1 else y.shape[0]
    B = x.shape[0]
    if ylens is None:
        ylens = jnp.full((B,), T, jnp.int32)
    out = jnp.broadcast_to(x[:, None], (B, T) + x.shape[1:])
    mask = (jnp.arange(T)[None, :] < ylens[:, None])
    out = out * mask.reshape((B, T) + (1,) * (x.ndim - 1)).astype(x.dtype)
    return {"Out": out, "Out@LOD_LEN": ylens}


@register_op("gather_tree")
def _gather_tree(ctx):
    """Beam ancestry walk (gather_tree): Ids/Parents [T, B, W] ->
    full sequences [T, B, W] by backtracking parents from the last step."""
    import jax
    jnp = _jnp()
    ids = ctx.input("Ids")
    parents = ctx.input("Parents").astype(jnp.int32)
    T, B, W = ids.shape

    def step(carry, t):
        beam = carry                      # [B, W] beam index at step t+1
        idx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, W))
        out_t = ids[t, idx, beam]
        parent = parents[t, idx, beam]
        return parent, out_t

    beam0 = jnp.broadcast_to(jnp.arange(W)[None, :], (B, W))
    _, outs = jax.lax.scan(step, beam0, jnp.arange(T - 1, -1, -1))
    return {"Out": outs[::-1]}


@register_op("row_conv")
def _row_conv(ctx):
    """Lookahead row convolution (row_conv_op.cc): ragged X [B, T, D],
    Filter [k, D]: out[t] = sum_j filter[j] * x[t + j], zero past the
    sequence end."""
    jnp = _jnp()
    x = ctx.input("X")
    w = ctx.input("Filter")
    lens = ctx.lod_len("X")
    B, T, D = x.shape
    k = w.shape[0]
    if lens is not None:
        mask = (jnp.arange(T)[None, :] < lens[:, None]).astype(x.dtype)
        x = x * mask[:, :, None]
    out = jnp.zeros_like(x)
    padded = jnp.pad(x, ((0, 0), (0, k), (0, 0)))
    for j in range(k):
        out = out + padded[:, j:j + T, :] * w[j][None, None, :]
    return {"Out": out}


@register_op("fsp")
def _fsp(ctx):
    """FSP matrix for distillation (fsp_op): X [N,C1,H,W], Y [N,C2,H,W] ->
    [N, C1, C2] mean over H*W of channel outer products."""
    jnp = _jnp()
    x, y = ctx.input("X"), ctx.input("Y")
    hw = x.shape[2] * x.shape[3]
    return {"Out": jnp.einsum("nchw,ndhw->ncd", x, y) / hw}


# ---------------------------------------------------------------------------
# quantization (fake_quantize_op.cc, fake_dequantize_op.cc)
# ---------------------------------------------------------------------------

def _quant(x, scale, bit_length):
    jnp = _jnp()
    bnt = (1 << (bit_length - 1)) - 1
    return jnp.round(jnp.clip(x / scale, -1.0, 1.0) * bnt)


@register_op("fake_quantize_abs_max")
def _fake_quantize_abs_max(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    bits = int(ctx.attr("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    scale = jnp.maximum(scale, 1e-12)
    return {"Out": _quant(x, scale, bits), "OutScale": scale.reshape(1)}


@register_op("fake_quantize_range_abs_max", stateful=True)
def _fake_quantize_range_abs_max(ctx):
    """Sliding-window running max (fake_quantize_op.cc FindRangeAbsMax):
    each training step records the current batch's abs-max into
    InScales[Iter % window_size] and the effective scale is the max over
    the window, so one outlier batch ages out after window_size steps.

    Wiring: thread a [window_size] InScales buffer and an Iter counter
    through the op (outputs OutScales / IterOut name the same vars).
    Without them the op degrades to max(cur, InScale) — a monotone running
    max that never forgets an outlier; acceptable only for short runs.
    """
    jnp = _jnp()
    x = ctx.input("X")
    bits = int(ctx.attr("bit_length", 8))
    in_scale = ctx.input("InScale")
    if ctx.attr("is_test", False) and in_scale is not None:
        scale = jnp.maximum(in_scale.reshape(())[None][0], 1e-12)
        return {"Out": _quant(x, scale, bits), "OutScale": scale.reshape(1)}
    cur = jnp.max(jnp.abs(x))
    scales = ctx.input("InScales")
    it = ctx.input("Iter")
    if scales is not None and it is not None:
        it = it.reshape(()).astype(jnp.int32)
        window = scales.shape[0]
        scales = scales.at[it % window].set(cur)
        # entries beyond the first Iter+1 steps are still zero and never
        # win the max, matching the reference's min(iter+1, window) span
        scale = jnp.maximum(jnp.max(scales), 1e-12)
        return {"Out": _quant(x, scale, bits),
                "OutScale": scale.reshape(1),
                "OutScales": scales,
                "IterOut": (it + 1).reshape(1)}
    if in_scale is not None:
        scale = jnp.maximum(cur, in_scale.reshape(())[None][0])
    else:
        scale = cur
    scale = jnp.maximum(scale, 1e-12)
    return {"Out": _quant(x, scale, bits), "OutScale": scale.reshape(1)}


@register_op("fake_dequantize_max_abs")
def _fake_dequantize_max_abs(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    scale = ctx.input("Scale").reshape(())
    max_range = float(ctx.attr("max_range", 127.0))
    return {"Out": x * scale / max_range}


@register_op("fake_quantize_dequantize_abs_max")
def _fake_quantize_dequantize_abs_max(ctx):
    """Quantize-dequantize with a straight-through estimator so QAT
    gradients flow as identity through the rounding (the reference's grad
    kernel passes dOut through unchanged)."""
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    bits = int(ctx.attr("bit_length", 8))
    bnt = (1 << (bits - 1)) - 1
    scale = jnp.maximum(jnp.max(jnp.abs(jax.lax.stop_gradient(x))), 1e-12)
    q = jnp.round(jnp.clip(x / scale, -1.0, 1.0) * bnt)
    qdq = q * scale / bnt
    out = x + jax.lax.stop_gradient(qdq - x)    # STE
    return {"Out": out, "OutScale": scale.reshape(1)}


# ---------------------------------------------------------------------------
# fused ops produced by the ir passes (fused_elemwise_activation_op.cc;
# the fc op the reference registers natively, operators/fc_op in later eras)
# ---------------------------------------------------------------------------

@register_op("fused_elemwise_activation")
def _fused_elemwise_activation(ctx):
    import jax
    jnp = _jnp()
    x, y = ctx.input("X"), ctx.input("Y")
    functors = ctx.attr("functor_list", ["elementwise_add", "relu"])
    act = functors[1] if len(functors) > 1 else "relu"
    s = x + y
    if act == "relu":
        out = jnp.maximum(s, 0)
    elif act == "sigmoid":
        out = jax.nn.sigmoid(s)
    elif act == "tanh":
        out = jnp.tanh(s)
    elif act == "gelu":
        out = jax.nn.gelu(
            s, approximate=bool(ctx.attr("approximate", False)))
    else:
        raise NotImplementedError(act)
    return {"Out": out}


@register_op("fc")
def _fc_fused(ctx):
    jnp = _jnp()
    x, w = ctx.input("Input"), ctx.input("W")
    b = ctx.input("Bias")
    ncol = int(ctx.attr("in_num_col_dims", 1))
    lead = x.shape[:ncol]
    x2 = x.reshape((-1, int(np.prod(x.shape[ncol:]))))
    out = x2 @ w
    if b is not None:
        out = out + b.reshape(1, -1)
    return {"Out": out.reshape(tuple(lead) + (w.shape[-1],))}


# ---------------------------------------------------------------------------
# hash (hash_op.cc/h): per input row, num_hash hashed bucket ids — the
# reference computes XXH64(row_bytes, seed=ihash) % mod_by. The TPU
# lowering uses a vectorized FNV-1a-style integer mix (same contract:
# deterministic per-row bucketing, one id per seed) — the exact hash
# function differs from xxhash, which only changes WHICH bucket a row
# lands in, not the op's semantics.
# ---------------------------------------------------------------------------

@register_op("hash")
def _hash(ctx):
    jnp = _jnp()
    x = ctx.input("X")                 # [N, last_dim] integer ids
    num_hash = int(ctx.attr("num_hash", 1))
    mod_by = int(ctx.attr("mod_by", 1))
    xi = x.astype(jnp.uint32)
    outs = []
    for seed in range(num_hash):
        h = jnp.full(x.shape[:-1],
                     np.uint32((2166136261 ^ (seed * 0x9E3779B9))
                               & 0xFFFFFFFF),
                     jnp.uint32)
        for k in range(x.shape[-1]):   # static, small last dim
            h = (h ^ xi[..., k]) * jnp.uint32(16777619)
        # final avalanche
        h = h ^ (h >> 15)
        h = h * jnp.uint32(0x2C1B3C6D)
        h = h ^ (h >> 12)
        outs.append((h % jnp.uint32(mod_by)).astype(x.dtype))
    out = jnp.stack(outs, axis=-1)[..., None]   # [N, num_hash, 1]
    return {"Out": out}


# ---------------------------------------------------------------------------
# unique_with_counts (unique_with_counts_op.cc): data-dependent output
# size — legal on concrete values (eager/host path); under jit it is an
# XLA-static-shape limit.
# ---------------------------------------------------------------------------

@register_op("unique_with_counts")
def _unique_with_counts(ctx):
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    if isinstance(x, jax.core.Tracer):
        raise NotImplementedError(
            "unique_with_counts has a data-dependent output shape and "
            "cannot be traced under jit — run the program eagerly")
    arr = np.asarray(x).reshape(-1)
    uniq, index, counts = np.unique(arr, return_inverse=True,
                                    return_counts=True)
    from ..fluid import core as fcore
    idx_dtype = fcore.convert_dtype_to_np(
        ctx.attr("dtype", fcore.VarDesc.VarType.INT32))
    return {"Out": jnp.asarray(uniq),
            "Index": jnp.asarray(index.astype(idx_dtype)),
            "Count": jnp.asarray(counts.astype(idx_dtype))}
