"""Tensor creation / manipulation / RNG op lowerings.

Reference analogues: fill_constant_op, uniform_random_op, gaussian_random_op,
truncated_gaussian_random_op, reshape_op, transpose_op, concat_op, split_op,
squeeze/unsqueeze, flatten, stack/unstack, gather/scatter, slice, expand,
reverse, shape, assign, cast (in math_ops), pad (nn_ops), range, linspace.
"""

import numpy as np

from .registry import register_op


def _jnp():
    import jax.numpy as jnp
    return jnp


def _np_dtype(attr_dtype, default=np.float32):
    from ..fluid import core as fcore
    if attr_dtype is None:
        return np.dtype(default)
    return fcore.convert_dtype_to_np(attr_dtype)


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

@register_op("fill_constant")
def _fill_constant(ctx):
    jnp = _jnp()
    shape = ctx.attr("shape", [1])
    dtype = _np_dtype(ctx.attr("dtype"))
    return {"Out": jnp.full(tuple(int(d) for d in shape),
                            ctx.attr("value", 0.0), dtype=dtype)}


@register_op("fill_constant_batch_size_like")
def _fill_constant_bsl(ctx):
    jnp = _jnp()
    ref = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    in_idx = ctx.attr("input_dim_idx", 0)
    out_idx = ctx.attr("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    dtype = _np_dtype(ctx.attr("dtype"))
    return {"Out": jnp.full(tuple(shape), ctx.attr("value", 0.0),
                            dtype=dtype)}


@register_op("fill_zeros_like")
def _fill_zeros_like(ctx):
    jnp = _jnp()
    return {"Out": jnp.zeros_like(ctx.input("X"))}


@register_op("assign")
def _assign(ctx):
    return {"Out": ctx.input("X")}


@register_op("remat_tag")
def _remat_tag(ctx):
    """Identity carrying a jax.ad_checkpoint name tag (attr 'tag').
    Under whole-graph AD a save_only_these_names(tag) policy keeps the
    tagged value and rematerializes everything between tags in the
    backward (the block-granularity remat lever in ROOFLINE.md);
    in normal execution XLA elides it entirely."""
    from jax.ad_checkpoint import checkpoint_name
    return {"Out": checkpoint_name(ctx.input("X"),
                                   ctx.attr("tag", "block_out"))}


@register_op("assign_value")
def _assign_value(ctx):
    jnp = _jnp()
    dtype = _np_dtype(ctx.attr("dtype"))
    if ctx.attr("fp32_values"):
        vals = np.array(ctx.attr("fp32_values"), dtype=np.float32)
    elif ctx.attr("int64_values"):
        vals = np.array(ctx.attr("int64_values"), dtype=np.int64)
    else:
        vals = np.array(ctx.attr("int32_values"), dtype=np.int32)
    return {"Out": jnp.asarray(vals.reshape(ctx.attr("shape")), dtype=dtype)}


@register_op("shape")
def _shape(ctx):
    jnp = _jnp()
    return {"Out": jnp.asarray(np.array(ctx.input("Input").shape,
                                        dtype=np.int32))}


@register_op("range")
def _range(ctx):
    jnp = _jnp()
    start, end, step = ctx.input("Start"), ctx.input("End"), ctx.input("Step")
    # dynamic arange is not XLA-friendly; require concrete python scalars
    return {"Out": jnp.arange(float(start), float(end), float(step))}


@register_op("linspace")
def _linspace(ctx):
    jnp = _jnp()
    return {"Out": jnp.linspace(float(ctx.input("Start")),
                                float(ctx.input("Stop")),
                                int(ctx.input("Num")))}


# ---------------------------------------------------------------------------
# RNG (uniform_random_op.cc etc.) — deterministic threefry keyed by (seed, op
# uid, step), the functional replacement for the reference's per-op curand.
# ---------------------------------------------------------------------------

@register_op("uniform_random")
def _uniform_random(ctx):
    import jax
    shape = tuple(int(d) for d in ctx.attr("shape"))
    dtype = _np_dtype(ctx.attr("dtype"))
    return {"Out": jax.random.uniform(
        ctx.rng_key(), shape, minval=ctx.attr("min", -1.0),
        maxval=ctx.attr("max", 1.0), dtype=dtype)}


@register_op("uniform_random_batch_size_like")
def _uniform_random_bsl(ctx):
    import jax
    ref = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    shape[ctx.attr("output_dim_idx", 0)] = ref.shape[ctx.attr(
        "input_dim_idx", 0)]
    dtype = _np_dtype(ctx.attr("dtype"))
    return {"Out": jax.random.uniform(
        ctx.rng_key(), tuple(shape), minval=ctx.attr("min", -1.0),
        maxval=ctx.attr("max", 1.0), dtype=dtype)}


@register_op("gaussian_random")
def _gaussian_random(ctx):
    import jax
    shape = tuple(int(d) for d in ctx.attr("shape"))
    dtype = _np_dtype(ctx.attr("dtype"))
    out = jax.random.normal(ctx.rng_key(), shape, dtype=dtype)
    return {"Out": out * ctx.attr("std", 1.0) + ctx.attr("mean", 0.0)}


@register_op("truncated_gaussian_random")
def _truncated_gaussian_random(ctx):
    import jax
    shape = tuple(int(d) for d in ctx.attr("shape"))
    dtype = _np_dtype(ctx.attr("dtype"))
    out = jax.random.truncated_normal(ctx.rng_key(), -2.0, 2.0, shape,
                                      dtype=dtype)
    return {"Out": out * ctx.attr("std", 1.0) + ctx.attr("mean", 0.0)}


@register_op("randint")
def _randint(ctx):
    import jax
    import jax.numpy as jnp
    shape = tuple(int(d) for d in ctx.attr("shape"))
    return {"Out": jax.random.randint(
        ctx.rng_key(), shape, ctx.attr("low", 0), ctx.attr("high"),
        dtype=jnp.int64)}


@register_op("shuffle_batch")
def _shuffle_batch(ctx):
    import jax
    x = ctx.input("X")
    perm = jax.random.permutation(ctx.rng_key(), x.shape[0])
    return {"Out": x[perm], "ShuffleIdx": perm}


# ---------------------------------------------------------------------------
# reshape family — reshape2/transpose2 also emit XShape (a shape-only var the
# reference uses to reconstruct shapes in grad; we keep the contract).
# ---------------------------------------------------------------------------

def _target_shape(x, shape):
    shape = list(shape)
    neg = [i for i, d in enumerate(shape) if d == -1]
    for i, d in enumerate(shape):
        if d == 0:
            shape[i] = x.shape[i]
    if neg:
        known = int(np.prod([d for d in shape if d > 0])) or 1
        shape[neg[0]] = int(np.prod(x.shape)) // known
    return tuple(shape)


def _ragged_target(ctx, x, shape):
    """Build-time shapes for ragged vars use the packed [rows, ...] layout;
    at runtime they are padded [B, T, ...]. A reshape whose leading dim is
    the ragged -1 therefore maps to [B, T] + rest."""
    if ctx.lod_len("X") is not None and shape and shape[0] == -1:
        return tuple(x.shape[:2]) + tuple(int(d) for d in shape[1:])
    return _target_shape(x, shape)


@register_op("reshape")
def _reshape(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    if ctx.has_input("Shape"):
        shape = [int(d) for d in np.asarray(ctx.input("Shape"))]
    else:
        shape = ctx.attr("shape")
    return {"Out": jnp.reshape(x, _ragged_target(ctx, x, shape))}


@register_op("reshape2")
def _reshape2(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    shape = ctx.attr("shape")
    out = jnp.reshape(x, _ragged_target(ctx, x, shape))
    return {"Out": out, "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("transpose")
def _transpose(ctx):
    jnp = _jnp()
    return {"Out": jnp.transpose(ctx.input("X"), ctx.attr("axis"))}


@register_op("transpose2")
def _transpose2(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    return {"Out": jnp.transpose(x, ctx.attr("axis")),
            "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("squeeze")
def _squeeze(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    axes = ctx.attr("axes", [])
    if not axes:
        return {"Out": jnp.squeeze(x)}
    axes = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
    return {"Out": jnp.squeeze(x, axis=axes)}


@register_op("squeeze2")
def _squeeze2(ctx):
    x = ctx.input("X")
    jnp = _jnp()
    out = _squeeze(ctx)["Out"]
    return {"Out": out, "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("unsqueeze")
def _unsqueeze(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    for a in sorted(ctx.attr("axes")):
        x = jnp.expand_dims(x, a)
    return {"Out": x}


@register_op("unsqueeze2")
def _unsqueeze2(ctx):
    jnp = _jnp()
    x0 = ctx.input("X")
    out = _unsqueeze(ctx)["Out"]
    return {"Out": out, "XShape": jnp.zeros((0,) + x0.shape, x0.dtype)}


@register_op("flatten")
def _flatten(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    axis = ctx.attr("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return {"Out": jnp.reshape(x, (lead, -1))}


@register_op("flatten2")
def _flatten2(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    out = _flatten(ctx)["Out"]
    return {"Out": out, "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


# ---------------------------------------------------------------------------
# concat/split/stack/gather/scatter/slice/expand/reverse
# ---------------------------------------------------------------------------

@register_op("concat")
def _concat(ctx):
    jnp = _jnp()
    axis = ctx.attr("axis", 0)
    if any(l is not None for l in ctx.lod_lens("X")) and axis >= 1:
        axis += 1  # padded ragged layout inserts the time dim at 1
    return {"Out": jnp.concatenate(ctx.inputs("X"), axis=axis)}


@register_op("split")
def _split(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    axis = ctx.attr("axis", 0)
    num = ctx.attr("num", 0)
    sections = ctx.attr("sections", [])
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register_op("stack")
def _stack(ctx):
    jnp = _jnp()
    return {"Y": jnp.stack(ctx.inputs("X"), axis=ctx.attr("axis", 0))}


@register_op("unstack")
def _unstack(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    axis = ctx.attr("axis", 0)
    n = x.shape[axis]
    return {"Y": [jnp.squeeze(s, axis) for s in jnp.split(x, n, axis=axis)]}


@register_op("gather")
def _gather(ctx):
    jnp = _jnp()
    x, idx = ctx.input("X"), ctx.input("Index")
    if idx.ndim == 2 and idx.shape[1] == 1:
        idx = idx.reshape(-1)
    return {"Out": jnp.take(x, idx.astype(jnp.int32), axis=0)}


@register_op("gather_nd")
def _gather_nd(ctx):
    jnp = _jnp()
    x, idx = ctx.input("X"), ctx.input("Index")
    idx = idx.astype(jnp.int32)
    return {"Out": x[tuple(jnp.moveaxis(idx, -1, 0))]}


@register_op("scatter")
def _scatter(ctx):
    jnp = _jnp()
    x, ids, upd = ctx.input("X"), ctx.input("Ids"), ctx.input("Updates")
    if ids.ndim == 2 and ids.shape[1] == 1:
        ids = ids.reshape(-1)
    ids = ids.astype(jnp.int32)
    if ctx.attr("overwrite", True):
        out = x.at[ids].set(upd)
    else:
        out = x.at[ids].add(upd)
    return {"Out": out}


@register_op("slice")
def _slice(ctx):
    x = ctx.input("Input")
    axes = ctx.attr("axes")
    starts = ctx.attr("starts")
    ends = ctx.attr("ends")
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    return {"Out": x[tuple(idx)]}


@register_op("strided_slice")
def _strided_slice(ctx):
    x = ctx.input("Input")
    axes = ctx.attr("axes")
    starts, ends = ctx.attr("starts"), ctx.attr("ends")
    strides = ctx.attr("strides", [1] * len(axes))
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(s, e, st)
    return {"Out": x[tuple(idx)]}


@register_op("expand")
def _expand(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    times = ctx.attr("expand_times")
    return {"Out": jnp.tile(x, tuple(times))}


@register_op("expand_as")
def _expand_as(ctx):
    jnp = _jnp()
    x, y = ctx.input("X"), ctx.input("target_tensor")
    times = tuple(t // s for t, s in zip(y.shape, x.shape))
    return {"Out": jnp.tile(x, times)}


@register_op("reverse")
def _reverse(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    out = x
    for a in ctx.attr("axis"):
        out = jnp.flip(out, a)
    return {"Out": out}


@register_op("tile")
def _tile(ctx):
    jnp = _jnp()
    return {"Out": jnp.tile(ctx.input("X"), tuple(ctx.attr("repeat_times")))}


@register_op("where")
def _where(ctx):
    jnp = _jnp()
    return {"Out": jnp.where(ctx.input("Condition"), ctx.input("X"),
                             ctx.input("Y"))}


@register_op("space_to_depth")
def _space_to_depth(ctx):
    jnp = _jnp()
    x = ctx.input("X")  # NCHW
    b = ctx.attr("blocksize")
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return {"Out": x.reshape(n, c * b * b, h // b, w // b)}


@register_op("pixel_shuffle")
def _pixel_shuffle(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    r = ctx.attr("upscale_factor")
    n, c, h, w = x.shape
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return {"Out": x.reshape(n, c // (r * r), h * r, w * r)}


@register_op("lod_reset")
def _lod_reset(ctx):
    """lod_reset_op.cc: re-segment X's rows by Y's LoD (or target_lod).
    Dense encoding: compact X's valid rows (static-shape scatter via a
    cumsum of the mask), then regroup them into Y's padded layout."""
    jnp = _jnp()
    x = ctx.input("X")
    xlens = ctx.lod_len("X")
    ylens = ctx.lod_len("Y")
    if ylens is None:
        target = ctx.attr("target_lod", [])
        if not target:
            return {"Out": x}
        offsets = np.asarray(target, np.int64)
        ylens = jnp.asarray(offsets[1:] - offsets[:-1], jnp.int32)
    # 1) compact X's valid rows into flat [N, ...] (row-major order)
    if x.ndim >= 3 or xlens is not None:
        B_x, T_x = x.shape[0], x.shape[1]
        if xlens is None:
            xlens = jnp.full((B_x,), T_x, jnp.int32)
        mask = (jnp.arange(T_x)[None, :] < xlens[:, None]).reshape(-1)
        rows = x.reshape((B_x * T_x,) + tuple(x.shape[2:]))
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        dest = jnp.where(mask, pos, B_x * T_x)   # out-of-range -> dropped
        flat = jnp.zeros_like(rows).at[dest].set(rows, mode="drop")
    else:
        flat = x                                # [N, ...] already flat
    # 2) regroup into Y's padded [B_y, T_y, ...] layout (T_y static: Y's
    # padded time axis, else the flat row count)
    B_y = ylens.shape[0]
    y = ctx.input("Y")
    T_y = y.shape[1] if (y is not None and y.ndim >= 3) \
        else int(flat.shape[0])
    off = jnp.cumsum(ylens) - ylens             # exclusive offsets
    t = jnp.arange(T_y)[None, :]
    idx = (off[:, None] + t).clip(0, flat.shape[0] - 1)
    out = jnp.take(flat, idx.reshape(-1), axis=0).reshape(
        (B_y, T_y) + tuple(flat.shape[1:]))
    m = (t < ylens[:, None]).reshape(
        (B_y, T_y) + (1,) * (flat.ndim - 1)).astype(out.dtype)
    return {"Out": out * m, "Out@LOD_LEN": ylens}


@register_op("is_empty")
def _is_empty(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    return {"Out": jnp.asarray([x.size == 0])}


def _print_msg(raw):
    # escape braces: the user message must not be treated as a format
    # template (message="loss {step}" would raise during tracing)
    return (raw or "").replace("{", "{{").replace("}", "}}")


@register_op("print")
def _print(ctx):
    import jax
    x = ctx.input("In")
    if ctx.attr("print_phase", "both") in ("forward", "both"):
        jax.debug.print(_print_msg(ctx.attr("message", "")) + " {}", x)
    return {"Out": x}


@register_op("print_grad")
def _print_grad(ctx):
    """Backward phase of print_op.cc: print_phase backward/both dumps the
    incoming cotangent, then passes it through unchanged."""
    import jax
    d = ctx.input("GRAD:Out")
    attrs = ctx.attr("fwd_attrs", None) or {}
    if d is not None and \
            attrs.get("print_phase", "both") in ("backward", "both"):
        jax.debug.print(_print_msg(attrs.get("message", ""))
                        + " @GRAD {}", d)
    return {"GRAD:In": d}
