"""Control-flow op lowerings.

Reference analogues: operators/controlflow/while_op.cc:36 (runs a sub-block
via a nested Executor per iteration, grad at :119), conditional_block_op.cc,
recurrent_op (block-based StaticRNN runtime), tensor_array_read_write.

TPU redesign: sub-blocks are interpreted at trace time by the same
functionalizer (fluid/functionalizer.run_block), so
- `while`       -> lax.while_loop whose carry is the sub-block's write-set
- `conditional_block` -> lax.cond over the sub-block
- `recurrent` (DynamicRNN) -> lax.scan over the padded time axis with masks
- StaticRNN has NO op at all: the layer unrolls its step ops straight into
  the parent block at build time (trace-time unrolling is free under XLA and
  keeps the whole net differentiable by the generic vjp machinery).

Gradient support: a `while` built with max_iters lowers to a bounded masked
lax.scan and is differentiable through the generic vjp machinery (reference
while_grad, while_op.cc:119); without a bound it lowers to lax.while_loop
(forward) and differentiates via the explicit `while_grad_dynamic` op — a
host-path replay of the loop (initial carries snapshotted before the
forward op) followed by a per-iteration vjp sweep, the direct analogue of
the reference's per-iteration-scope WhileGradOp. Training-time recurrences
can also go through recurrent/scan or the unrolled StaticRNN.
"""

import numpy as np

from .registry import register_op


def _jnp():
    import jax.numpy as jnp
    return jnp


def _subblock_io(block, env):
    """(reads, writes): external var names the sub-block reads / vars it
    writes, in deterministic order."""
    produced = set()
    reads, writes = [], []
    for op in block.ops:
        for n in op.input_arg_names:
            if n and n not in produced and n in env and n not in reads:
                reads.append(n)
        for n in op.output_arg_names:
            if n:
                produced.add(n)
                if n not in writes:
                    writes.append(n)
    return reads, writes


@register_op("while")
def _while(ctx):
    """Pure while op over its declared X (reads + carry inits) / Out
    (writes) slots. Two lowerings:

    - max_iters attr set and not is_test: bounded masked lax.scan — the body
      runs exactly max_iters times and a jnp.where on the condition freezes
      the carry once it turns false. This form is DIFFERENTIABLE (the where
      gates cotangents), restoring the reference's while_grad capability
      (while_op.cc:119,:181) through the generic vjp machinery.
    - otherwise: lax.while_loop — dynamic trip count, forward-only
      (inference/decoding loops).

    Programs built before X/Out declaration fall back to env introspection.
    """
    import jax
    jnp = _jnp()
    from ..fluid import functionalizer
    block = ctx.attr("sub_block")
    cond_name = ctx.op.inputs["Condition"][0]

    x_names = list(ctx.op.inputs.get("X", []))
    out_names = list(ctx.op.outputs.get("Out", []))
    if x_names:
        vals = dict(zip(x_names, ctx.inputs("X")))
        vals.setdefault(cond_name, ctx.input("Condition"))
        carry_names = [n for n in out_names if vals.get(n) is not None]
        closure = {n: v for n, v in vals.items()
                   if n not in carry_names and v is not None}
        env = None
    else:        # legacy env-introspection path
        env = ctx.env
        reads, writes = _subblock_io(block, env)
        carry_names = [n for n in writes if n in env]
        closure = {n: env[n] for n in reads if n not in carry_names}
        vals = env

    if cond_name not in carry_names and cond_name not in closure:
        closure[cond_name] = vals[cond_name]
    init = tuple(vals[n] for n in carry_names)

    def overlay(carry):
        e = dict(closure)
        e.update(zip(carry_names, carry))
        return e

    def run_body(e):
        functionalizer.run_block(block, e, step=ctx.step, seed=ctx.seed,
                                 mesh=ctx.mesh)
        return tuple(e[n] for n in carry_names)

    max_iters = ctx.attr("max_iters")
    record_cap = ctx.attr("grad_max_iters") \
        if ctx.attr("record_for_grad", False) else None
    if functionalizer.block_tree_has_host_ops(block) or \
            ctx.attr("force_host", False):
        # host ops (save/send/...) need concrete values each iteration:
        # interpret the body per iteration on the host, like the
        # reference's nested-Executor WhileOp (while_op.cc:50). Only
        # possible when the surrounding program runs eagerly.
        probe = vals.get(cond_name, closure.get(cond_name))
        if isinstance(probe, jax.core.Tracer) or \
                any(isinstance(v, jax.core.Tracer) for v in init):
            raise RuntimeError(
                "while body contains host ops (possibly nested) and "
                "cannot be traced under jit — run the program through "
                "the Executor's eager path")
        import numpy as _np
        carry = init
        while bool(_np.asarray(overlay(carry)[cond_name]).reshape(())):
            carry = run_body(overlay(carry))
        final = carry
    elif max_iters and not ctx.attr("is_test", False):
        run_pinned = _pin_carry_dtypes(run_body, init, jnp)

        def scan_body(carry, _):
            e = overlay(carry)
            pred = e[cond_name].reshape(())
            new = run_pinned(e)
            kept = tuple(jnp.where(pred, nv, cv)
                         for nv, cv in zip(new, carry))
            return kept, None
        final, _ = jax.lax.scan(scan_body, init, None,
                                length=int(max_iters))
    elif record_cap and not ctx.attr("is_test", False):
        final = _recorded_while(ctx, block, carry_names, closure, init,
                                cond_name,
                                _pin_carry_dtypes(run_body, init, jnp),
                                int(record_cap))
    else:
        run_pinned = _pin_carry_dtypes(run_body, init, jnp)

        def cond_fun(carry):
            return overlay(carry)[cond_name].reshape(())

        def body_fun(carry):
            return run_pinned(overlay(carry))

        final = jax.lax.while_loop(cond_fun, body_fun, init)

    if env is not None:        # legacy: write straight into the parent env
        for n, v in zip(carry_names, final):
            env[n] = v
        return {}
    by_name = dict(zip(carry_names, final))
    return {"Out": [by_name.get(n) for n in out_names]}


def _pin_carry_dtypes(run_body, init, jnp):
    """Wrap a while/scan body so its carry outputs keep the INIT dtypes:
    under AMP a body op can promote a bf16-initialized carry to fp32
    (bf16 activation meeting an fp32 master weight), tripping the
    carry-type check at lowering — the same class fixed in the
    lstm/gru/recurrent scans."""
    dtypes = tuple(jnp.asarray(v).dtype for v in init)

    def pinned(e):
        return tuple(jnp.asarray(nv).astype(dt)
                     for nv, dt in zip(run_body(e), dtypes))
    return pinned


def _recorded_while(ctx, block, carry_names, closure, init, cond_name,
                    run_body, cap):
    """Jit-native gradient for a dynamic-trip-count while (VERDICT r3 #3;
    reference WhileGradOp, while_op.cc:119 — but in-graph instead of a
    nested-executor replay).

    Forward: `lax.while_loop` that records each iteration's pre-body
    carries into a static [cap, ...] buffer (the in-graph analogue of the
    reference's per-iteration scopes), truncating at `cap` iterations
    (FLAGS.while_grad_max_iters bucketing — XLA needs a static bound for
    the residual buffer).
    Backward: a reverse `lax.while_loop` from the recorded trip count
    down to 0, running the body's vjp at each recorded carry — cost
    O(actual trip count), not O(cap). The whole construct is a
    `jax.custom_vjp`, so the generic per-op vjp machinery differentiates
    through it and the training program stays inside ONE jitted XLA
    computation (no SegmentedProgramRunner).

    Overflow is LOUD: if the loop still wants to run at `cap` iterations,
    every float carry is poisoned with NaN — a silently-truncated forward
    would train on wrong values (and diverge from the is_test lowering,
    which stays unbounded). Raise FLAGS.while_grad_max_iters when this
    trips."""
    import jax
    jnp = _jnp()

    def is_floatv(v):
        return jnp.issubdtype(jnp.result_type(v), jnp.floating)

    d_names = [n for n, v in zip(carry_names, init) if is_floatv(v)]
    n_names = [n for n in carry_names if n not in d_names]
    vals_by_name = dict(zip(carry_names, init))
    dcl_names = [n for n, v in closure.items() if is_floatv(v)]
    ndcl = {n: v for n, v in closure.items() if n not in dcl_names}

    def run_env(dc, ndc, dcl):
        e = dict(ndcl)
        e.update(zip(dcl_names, dcl))
        e.update(zip(n_names, ndc))
        e.update(zip(d_names, dc))
        return e

    def step_all(dc, ndc, dcl):
        e = run_env(dc, ndc, dcl)
        new = dict(zip(carry_names, run_body(e)))
        return (tuple(new[n] for n in d_names),
                tuple(new[n] for n in n_names))

    def fwd_impl(dc0, dcl):
        ndc0 = tuple(vals_by_name[n] for n in n_names)
        bd = tuple(jnp.zeros((cap,) + tuple(v.shape), jnp.result_type(v))
                   for v in dc0)
        bn = tuple(jnp.zeros((cap,) + tuple(v.shape), jnp.result_type(v))
                   for v in ndc0)

        def cond_fn(c):
            i, dc, ndc = c[0], c[1], c[2]
            e = run_env(dc, ndc, dcl)
            return jnp.logical_and(
                e[cond_name].reshape(()).astype(bool), i < cap)

        def body_fn(c):
            i, dc, ndc, bd, bn = c
            bd = tuple(b.at[i].set(v) for b, v in zip(bd, dc))
            bn = tuple(b.at[i].set(v) for b, v in zip(bn, ndc))
            dc2, ndc2 = step_all(dc, ndc, dcl)
            return (i + 1, dc2, ndc2, bd, bn)

        return jax.lax.while_loop(
            cond_fn, body_fn, (jnp.asarray(0, jnp.int32), dc0, ndc0,
                               bd, bn))

    def finals(t, dc, ndc, dcl):
        # cap reached with the condition still true = truncated loop:
        # poison the float finals so training fails loudly instead of
        # silently optimizing a different (shorter) program
        e = run_env(dc, ndc, dcl)
        overflow = jnp.logical_and(
            t >= cap, e[cond_name].reshape(()).astype(bool))
        return tuple(jnp.where(overflow, jnp.nan, v).astype(v.dtype)
                     for v in dc), ndc

    @jax.custom_vjp
    def run(dc0, dcl):
        t, dc, ndc, _, _ = fwd_impl(dc0, dcl)
        return finals(t, dc, ndc, dcl)

    def run_fwd(dc0, dcl):
        t, dc, ndc, bd, bn = fwd_impl(dc0, dcl)
        return finals(t, dc, ndc, dcl), (t, bd, bn, dcl)

    def run_bwd(res, g):
        t, bd, bn, dcl = res
        g_dc = tuple(g[0])  # cotangents for the nondiff finals are float0
        g_dcl = tuple(jnp.zeros(v.shape, jnp.result_type(v)) for v in dcl)

        def cond_fn(c):
            return c[0] >= 0

        def body_fn(c):
            k, gdc, gdcl = c
            dck = tuple(b[k] for b in bd)
            ndck = tuple(b[k] for b in bn)
            _, vjp_fn = jax.vjp(
                lambda d, cl: step_all(d, ndck, cl)[0], dck, dcl)
            gd, gcl = vjp_fn(gdc)
            return (k - 1, tuple(gd),
                    tuple(a + b for a, b in zip(gdcl, gcl)))

        _, g_dc, g_dcl = jax.lax.while_loop(
            cond_fn, body_fn, (t - 1, g_dc, g_dcl))
        return g_dc, g_dcl

    run.defvjp(run_fwd, run_bwd)

    dc_f, ndc_f = run(tuple(vals_by_name[n] for n in d_names),
                      tuple(closure[n] for n in dcl_names))
    by = dict(zip(d_names, dc_f))
    by.update(zip(n_names, ndc_f))
    return tuple(by[n] for n in carry_names)


def _is_float_var(block, name):
    from ..fluid import core as fcore
    v = block._find_var_recursive(name)
    if v is None or v.dtype is None:
        return False
    try:
        return np.issubdtype(fcore.convert_dtype_to_np(v.dtype),
                             np.floating)
    except Exception:
        return False


def _while_grad_maker(op, block, grad_map, no_grad_set, bw_ctx=None):
    """Differentiating a while: with max_iters the bounded-scan lowering
    is jax-differentiable — decline (None) to the generic vjp path.
    WITHOUT a bound, emit an explicit `while_grad` op (reference
    while_op.cc:119 WhileGradOp): a host-path op that replays the loop
    recording per-iteration carries, then runs the body's vjp backward
    over the recorded trajectory — dynamic trip counts fully supported
    on the eager/host execution path."""
    from ..fluid.framework import grad_var_name
    pending = (bw_ctx or {}).get("pending", {})
    partials = (bw_ctx or {}).get("partials", {})
    x_names = list(op.inputs.get("X", []))
    out_names = list(op.outputs.get("Out", []))

    # Force-finalize each carry's POST-loop contributions — for BOTH the
    # bounded and dynamic paths: with a pre-loop consumer in the graph,
    # pending has not drained and the partials contributed so far
    # (exactly the post-loop consumers — they precede this op in the
    # reverse walk) are the loop's out-grad. The canonical grad name is
    # reused later by the producer's own finalize; sequential execution
    # makes the in-place rebinding safe (this op's grad consumes the
    # value before the overwrite).
    for n in out_names:
        if n in grad_map:
            continue
        parts = partials.pop(n, [])
        if not parts:
            continue
        gname = grad_var_name(n)
        v = block._find_var_recursive(n)
        if not block.has_var(gname) and v is not None:
            from ..fluid.backward import _create_grad_var
            _create_grad_var(block, v, gname)
        if len(parts) == 1:
            block.append_op(type="assign", inputs={"X": [parts[0]]},
                            outputs={"Out": [gname]}, infer_shape=False)
        else:
            block.append_op(type="sum", inputs={"X": parts},
                            outputs={"Out": [gname]}, infer_shape=False)
        grad_map[n] = gname

    if op.attrs.get("max_iters"):
        return None      # bounded scan: generic vjp path (grads seeded
                         # from the force-finalized map above)

    from ..flags import FLAGS
    from ..fluid import functionalizer as _fz
    if not FLAGS.dynamic_while_host_grad and \
            not _fz.block_tree_has_host_ops(op.attrs.get("sub_block")):
        # jit-native dynamic-while gradient (VERDICT r3 #3): mark the
        # forward op to lower to the recording custom_vjp form
        # (_recorded_while) and decline to the generic vjp path — the
        # training program stays fully jitted. Host-op bodies (save/
        # send/print...) still need the replay below.
        op.attrs["record_for_grad"] = True
        op.attrs["grad_max_iters"] = int(FLAGS.while_grad_max_iters)
        return None

    out_grads = [grad_map.get(n, "") for n in out_names]
    if not any(out_grads):
        return []        # loop contributes no gradient — handled, empty

    # carries are clobbered IN PLACE by the forward loop (Out name ==
    # X name), so the replay needs snapshots of the INITIAL values:
    # insert assigns right before the forward while op (the analogue of
    # the reference's per-iteration scope capture)
    while_idx = next(i for i, o in enumerate(block.ops) if o is op)
    feed_names = []
    n_inserted = 0
    for n in x_names:
        if n in out_names:
            init_name = n + "@WHILE_INIT"
            v = block._find_var_recursive(n)
            block.create_var(name=init_name, dtype=v.dtype,
                             shape=v.shape, stop_gradient=True)
            block._insert_op(
                while_idx + n_inserted,
                type="assign", inputs={"X": [n]},
                outputs={"Out": [init_name]}, attrs={})
            n_inserted += 1
            feed_names.append(init_name)
        else:
            feed_names.append(n)

    made = []
    x_grad_names = []
    for n in x_names:
        if n in no_grad_set or not _is_float_var(block, n):
            x_grad_names.append("")
            continue
        gname = grad_var_name(n) + "@WHILE"
        v = block._find_var_recursive(n)
        block.create_var(name=gname, dtype=v.dtype, shape=v.shape,
                         stop_gradient=True)
        x_grad_names.append(gname)
    block.append_op(
        type="while_grad_dynamic",
        inputs={"X": feed_names, "GRAD:Out": out_grads},
        outputs={"GRAD:X": x_grad_names},
        attrs={"sub_block": op.attrs.get("sub_block"),
               "out_names": out_names, "x_names": x_names,
               "cond_name": list(op.inputs.get("Condition", ["?"]))[0],
               "op_role": "Backward"},
        infer_shape=False)
    # integrate with the backward pass's fan-in protocol (bw_ctx carries
    # its pending/partials state):
    # - a CARRY's out-grad (grad_map[n]) was CONSUMED by the replay; the
    #   computed initial-state grad REPLACES it — summing would
    #   double-count the upstream gradient through an identity loop
    # - a CLOSURE input behaves like any other consumer: contribute a
    #   partial and let finalize_grad sum across all consumers
    for n, gname in zip(x_names, x_grad_names):
        if not gname:
            continue
        made.append(gname)
        if n in out_names:
            if pending.get(n, 0) > 0:
                # other consumers still owed: join their fan-in; the
                # stale out-grad in grad_map is overwritten at finalize
                partials.setdefault(n, []).append(gname)
            else:
                grad_map[n] = gname
        else:
            partials.setdefault(n, []).append(gname)
            # the handled-branch decrement in backward.py finalizes this
            # var once every consumer (including this loop) contributed
    return made


@register_op("while_grad_dynamic")
def _while_grad(ctx):
    """Reference WhileGradOp (while_op.cc:119): replay the forward loop
    from its recorded inputs (per-iteration carries = the reference's
    per-iteration scopes), then apply the body's vjp backward over the
    trajectory. Host path only — trip count is data-dependent."""
    import jax
    jnp = _jnp()
    from ..fluid import functionalizer

    block = ctx.attr("sub_block")
    out_names = list(ctx.attr("out_names", []))
    # X holds @WHILE_INIT snapshots for clobbered carries; x_names maps
    # each position back to the loop's own variable names
    x_names = list(ctx.attr("x_names", [])) or \
        list(ctx.op.inputs.get("X", []))
    cond_name = ctx.attr("cond_name")
    vals = dict(zip(x_names, ctx.inputs("X")))
    grad_out_vals = dict(zip(out_names, ctx.inputs("GRAD:Out")))
    if any(isinstance(v, jax.core.Tracer) for v in vals.values()
           if v is not None):
        raise NotImplementedError(
            "while_grad replays a data-dependent trip count and runs on "
            "the host execution path only (programs containing it are "
            "segmented automatically by the executor)")

    carry_names = [n for n in out_names if vals.get(n) is not None]
    closure = {n: v for n, v in vals.items()
               if n not in carry_names and v is not None}

    def is_float(v):
        return np.issubdtype(np.asarray(v).dtype, np.floating)

    diff_carries = [n for n in carry_names if is_float(vals[n])]
    nondiff_carries = [n for n in carry_names if n not in diff_carries]
    diff_closure = [n for n in closure if is_float(closure[n])]

    # ---- forward replay: ONE body execution per iteration, capturing
    # each iteration's vjp closure as we go (the residuals play the role
    # of the reference's per-iteration scopes) ----
    vjp_fns = []
    cur = {n: vals[n] for n in carry_names}

    def cond_of(env):
        src = env.get(cond_name, closure.get(cond_name))
        return bool(np.asarray(src).reshape(()))

    cl_vals_now = tuple(closure[n] for n in diff_closure)

    def make_step(nondiff_env):
        def step_fn(dc_vals, cl_vals):
            e = dict(closure)
            e.update(nondiff_env)
            e.update(dict(zip(diff_closure, cl_vals)))
            e.update(dict(zip(diff_carries, dc_vals)))
            functionalizer.run_block(block, e, step=ctx.step,
                                     seed=ctx.seed, mesh=ctx.mesh)
            diff_out = tuple(e[n] for n in diff_carries)
            aux = {n: e[n] for n in nondiff_carries}
            return diff_out, aux
        return step_fn

    probe = dict(closure)
    probe.update(cur)
    while cond_of(probe):
        nondiff_env = {n: cur[n] for n in nondiff_carries}
        step_fn = make_step(nondiff_env)
        diff_out, vjp_fn, aux = jax.vjp(
            step_fn, tuple(cur[n] for n in diff_carries), cl_vals_now,
            has_aux=True)
        vjp_fns.append(vjp_fn)
        cur = dict(zip(diff_carries, diff_out))
        cur.update(aux)
        probe = dict(closure)
        probe.update(cur)

    # ---- backward sweep over the captured closures ----
    g_carry = {n: (grad_out_vals.get(n)
                   if grad_out_vals.get(n) is not None
                   else jnp.zeros_like(vals[n]))
               for n in diff_carries}
    g_closure = {n: jnp.zeros_like(closure[n]) for n in diff_closure}

    for vjp_fn in reversed(vjp_fns):
        gc, gcl = vjp_fn(tuple(g_carry[n] for n in diff_carries))
        g_carry = dict(zip(diff_carries, gc))
        for n, g in zip(diff_closure, gcl):
            g_closure[n] = g_closure[n] + g

    grads = []
    for n in x_names:
        if n in g_carry:
            grads.append(g_carry[n])
        elif n in g_closure:
            grads.append(g_closure[n])
        else:
            grads.append(None)
    return {"GRAD:X": grads}


from .registry import set_grad_maker as _set_gm_cf  # noqa: E402
_set_gm_cf("while", _while_grad_maker)


@register_op("conditional_block")
def _conditional_block(ctx):
    import jax
    from ..fluid import functionalizer
    block = ctx.attr("sub_block")
    env = ctx.env
    cond = ctx.input("Cond")
    reads, writes = _subblock_io(block, env)
    carry_names = [n for n in writes if n in env]
    closure = {n: env[n] for n in reads}

    def true_fn(carry):
        e = dict(closure)
        e.update(zip(carry_names, carry))
        functionalizer.run_block(block, e, step=ctx.step, seed=ctx.seed,
                                 mesh=ctx.mesh)
        return tuple(e[n] for n in carry_names)

    def false_fn(carry):
        return carry

    init = tuple(env[n] for n in carry_names)
    # TensorArray carries are Python lists at trace time — lax.cond can't
    # carry them; interpret on the host (valid: values are concrete there)
    has_list_carry = any(isinstance(v, list)
                         for v in init + tuple(closure.values()))
    if functionalizer.block_tree_has_host_ops(block) or has_list_carry:
        # host ops need concrete values: interpret the branch on the host
        # (reference ConditionalBlockOp ran the sub-block via a nested
        # Executor; only possible when the program runs eagerly)
        if isinstance(cond, jax.core.Tracer) or \
                any(isinstance(v, jax.core.Tracer) for v in init):
            raise RuntimeError(
                "conditional_block body contains host ops (possibly "
                "nested) and cannot be traced under jit — run the "
                "program through the Executor's eager path")
        import numpy as _np
        out = true_fn(init) if bool(
            _np.asarray(cond).reshape(()).astype(bool)) else false_fn(init)
    else:
        out = jax.lax.cond(cond.reshape(()).astype(bool), true_fn, false_fn,
                           init)
    for n, v in zip(carry_names, out):
        env[n] = v
    return {}


# ---------------------------------------------------------------------------
# tensor array ops (tensor_array_read_write.cc; LoDTensorArray lod_tensor_
# array.h). Arrays with static length are python lists at trace time — the
# functionalizer stores them directly in env.
# ---------------------------------------------------------------------------

@register_op("write_to_array")
def _write_to_array(ctx):
    env = ctx.env
    out_name = ctx.op.outputs["Out"][0]
    arr = env.get(out_name)
    if not isinstance(arr, list):
        arr = []
    i = int(ctx.input("I").reshape(())) if not hasattr(
        ctx.input("I"), "aval") else None
    x = ctx.input("X")
    if i is None:
        # traced index: only append-at-end pattern supported under jit
        arr = arr + [x]
    else:
        arr = list(arr)
        while len(arr) <= i:
            arr.append(None)
        arr[i] = x
    env[out_name] = arr
    return {}


@register_op("read_from_array")
def _read_from_array(ctx):
    arr = ctx.input("X")
    i = int(np.asarray(ctx.input("I")).reshape(()))
    return {"Out": arr[i]}


@register_op("array_length")
def _array_length(ctx):
    jnp = _jnp()
    return {"Out": jnp.asarray([len(ctx.input("X"))], jnp.int64)}


@register_op("array_to_lod_tensor")
def _array_to_lod_tensor(ctx):
    jnp = _jnp()
    arr = ctx.input("X")
    return {"Out": jnp.stack(arr, axis=1)}  # [B, T, ...]


@register_op("lod_tensor_to_array")
def _lod_tensor_to_array(ctx):
    jnp = _jnp()
    x = ctx.input("X")
    return {"Out": [x[:, t] for t in range(x.shape[1])]}


@register_op("max_sequence_len")
def _max_sequence_len(ctx):
    jnp = _jnp()
    lens = ctx.lod_len("RankTable")
    return {"Out": jnp.max(lens).reshape((1,)).astype(jnp.int64)}


# ---------------------------------------------------------------------------
# recurrent op — DynamicRNN over the padded encoding via lax.scan
# (reference recurrent_op.cc + layers/control_flow.py:1542 DynamicRNN)
# ---------------------------------------------------------------------------

@register_op("recurrent")
def _recurrent(ctx):
    """Inputs: sequence inputs [B, T, D...] (slot X, ragged), initial states
    (slot InitStates), external params (slot Params). Sub-block computes one
    step from per-step slices + state vars; attrs name the mapping."""
    import jax
    jnp = _jnp()
    from ..fluid import functionalizer

    block = ctx.attr("sub_block")
    seq_names = ctx.attr("seq_input_names")      # sub-block step-slice names
    state_names = ctx.attr("state_names")        # memory var names
    state_prev_names = ctx.attr("state_prev_names")
    out_names = ctx.attr("output_names")
    xs_list = ctx.inputs("X")
    lens = ctx.lod_len("X")
    init_states = ctx.inputs("InitStates")
    param_names = ctx.attr("param_names", [])
    params = dict(zip(param_names, ctx.inputs("Params")))
    # ragged external reads (DynamicRNN static_input): carry their length
    # companions into the step env so sequence ops inside the block mask
    # correctly (attention over the padded encoder output)
    for name, ln in zip(param_names, ctx.lod_lens("Params")):
        if ln is not None:
            params[name + functionalizer.LOD_LEN_SUFFIX] = ln

    B, T = xs_list[0].shape[0], xs_list[0].shape[1]
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    mask = (jnp.arange(T)[None, :] < lens[:, None]).astype(
        xs_list[0].dtype)  # [B, T]

    xs_t = [jnp.swapaxes(x, 0, 1) for x in xs_list]     # [T, B, ...]
    mask_t = jnp.swapaxes(mask, 0, 1)[..., None]        # [T, B, 1]

    def step(carry, inp):
        mt = inp[-1]
        slices = inp[:-1]
        e = dict(params)
        e.update(zip(seq_names, slices))
        e.update(zip(state_prev_names, carry))
        functionalizer.run_block(block, e, step=ctx.step, seed=ctx.seed,
                                 mesh=ctx.mesh)
        new_states = []
        for prev, name in zip(carry, state_names):
            new = e[name]
            # carry dtype stays the init's: under AMP the block's fc
            # outputs promote to fp32 against bf16 boot states, which
            # would otherwise trip scan's carry-type check — states are
            # activations, so the bf16 round matches AMP semantics
            new_states.append((mt * new + (1 - mt) * prev)
                              .astype(prev.dtype))
        outs = tuple(e[n] * mt for n in out_names)
        return tuple(new_states), outs

    init = tuple(init_states)
    (final_states, outs) = jax.lax.scan(step, init,
                                        tuple(xs_t) + (mask_t,))
    result = {}
    out_vals = [jnp.swapaxes(o, 0, 1) for o in outs]
    result["Out"] = out_vals
    result["Out@LOD_LEN"] = [lens] * len(out_vals)
    result["FinalStates"] = list(final_states)
    return result
