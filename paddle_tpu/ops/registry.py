"""Op registry — the TPU-native "kernel layer".

Reference analogue: paddle/fluid/framework/op_registry.h:185 (REGISTER_OPERATOR,
REGISTER_OP_CPU/CUDA_KERNEL) + operator.cc:700 (RunImpl kernel dispatch) +
grad_op_desc_maker.h:34 (GradOpDescMakerBase).

TPU-first redesign: instead of a per-device kernel map keyed by
OpKernelType(place, dtype, layout, library), each op registers ONE pure JAX
lowering `lower(ctx) -> {out_slot: value}`. The Executor interprets a Block by
calling lowerings inside a single jax trace, so the whole block becomes one
fused XLA computation — kernel selection, layout transforms and fusion all
belong to the XLA compiler (SURVEY.md §7 design stance). Placement is chosen
once per jit, not per op, so the reference's data-transform-between-kernels
machinery (operator.cc:804) has no equivalent and none is needed.

Autodiff: the reference generates grad OpDescs via per-op C++ GradOpDescMakers.
Here every op gets a *generic* grad op `<type>_grad` whose lowering is
`jax.vjp` of the forward lowering. Because forward and backward ops execute in
the same trace, the executor stashes the vjp closure produced at the forward
op and the grad op consumes it — zero recompute, numerically exact, and no
per-op gradient code. Ops may still register a custom grad maker when the
generic io signature is not right (e.g. ops with integer inputs only).

Shape inference: `infer_shape(op, block)` runs the lowering under
jax.eval_shape on ShapeDtypeStructs, substituting a dummy extent for the
batch-dim placeholder -1 and restoring it on outputs. This replaces ~300
hand-written C++ InferShape functions (op_desc.cc:660).
"""

import functools

import numpy as np

_REGISTRY = {}

# dummy extents substituted for -1 during eval_shape; we recognise the value
# in output shapes and map it back to -1, so it must not collide with any
# real static dim of the op — pick per-op from unlikely primes.
_DUMMY_CANDIDATES = (97, 811, 1327, 2957)


class OpDef:
    def __init__(self, type, lower, infer_shape=None, grad_maker=None,
                 no_eval_shape_cache=False, stateful=False):
        self.type = type
        self.lower = lower
        self.custom_infer_shape = infer_shape
        self.grad_maker = grad_maker
        self.stateful = stateful


class ExecContext:
    """What a lowering sees: attrs + resolved input values (+ rng/step).
    `env` is set only for env-mutating control-flow ops (while/cond/arrays),
    which write their results into the interpreter environment directly."""

    __slots__ = ("op", "attrs", "_inputs", "step", "seed", "mesh", "env")

    def __init__(self, op, inputs, step=0, seed=0, mesh=None, env=None):
        self.op = op
        self.attrs = op.attrs
        self._inputs = inputs  # slot -> [values]
        self.step = step
        self.seed = seed
        self.mesh = mesh
        self.env = env

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def input(self, slot):
        """Single input value for slot (None if absent)."""
        vs = self._inputs.get(slot)
        if not vs:
            return None
        return vs[0]

    def inputs(self, slot):
        """List of input values for slot."""
        return self._inputs.get(slot, [])

    def has_input(self, slot):
        return bool(self._inputs.get(slot))

    def lod_len(self, slot):
        """Per-sequence length vector [B] for a ragged (LoD) input, or None.
        See functionalizer.LOD_LEN_SUFFIX."""
        vs = self._inputs.get(slot + "@LOD_LEN")
        return vs[0] if vs else None

    def lod_lens(self, slot):
        """Length companions for EVERY input in a multi-input slot (list
        aligned with inputs(slot); entries are None for dense inputs)."""
        vs = self._inputs.get(slot + "@LOD_LEN")
        return vs if vs else [None] * len(self._inputs.get(slot, []))

    def lod_seg(self, slot):
        """Per-outer-group inner-sequence COUNTS [B_outer] for a NESTED
        (lod_level-2) input, or None (functionalizer.LOD_SEG_SUFFIX)."""
        vs = self._inputs.get(slot + "@LOD_SEG")
        return vs[0] if vs else None

    def rng_key(self):
        """Deterministic per-op, per-step PRNG key. Reproduces the reference's
        per-op `seed` attr semantics (e.g. dropout_op) while staying functional:
        the executor threads a step counter through the trace."""
        import jax
        base = jax.random.key(np.uint32(self.seed or 0))
        return jax.random.fold_in(jax.random.fold_in(base, self.op.uid),
                                  self.step)


def register_op(type, lower=None, infer_shape=None, grad_maker=None,
                stateful=False):
    """Register an op. Usable as decorator: @register_op("relu")."""
    def deco(fn):
        _REGISTRY[type] = OpDef(type, fn, infer_shape=infer_shape,
                                grad_maker=grad_maker, stateful=stateful)
        return fn
    if lower is not None:
        return deco(lower)
    return deco


def set_grad_maker(type, maker):
    _REGISTRY[type].grad_maker = maker


def get_op_def(type):
    od = _REGISTRY.get(type)
    if od is None:
        raise NotImplementedError(
            "op '%s' is not registered in the TPU op registry" % type)
    return od


def has_op(type):
    return type in _REGISTRY


def registered_ops():
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# shape inference via jax.eval_shape
# ---------------------------------------------------------------------------

def _pick_dummy(op, block):
    """A dummy batch extent that appears in no input's static dims."""
    static = set()
    for names in op.inputs.values():
        for n in names:
            v = block._find_var_recursive(n)
            if v is not None and v.shape is not None:
                static.update(int(d) for d in v.shape
                              if d is not None and d >= 0)
    for c in _DUMMY_CANDIDATES:
        if c not in static:
            return c
    c = max(static) + 101
    return c


def _subst_dummy(shape, dummy):
    return tuple(dummy if d is None or d < 0 else int(d) for d in shape)


def _restore_dummy(shape, had_dynamic, dummy):
    if not had_dynamic:
        return tuple(int(d) for d in shape)
    return tuple(-1 if d == dummy else int(d) for d in shape)


def infer_shape(op, block):
    """Fill in shape/dtype of op's output Variables by abstractly evaluating
    the lowering. Best-effort: ops whose outputs are already shaped, or whose
    lowering cannot run abstractly, are skipped silently (the executor will
    still produce correct runtime shapes)."""
    od = _REGISTRY.get(op.type)
    if od is None:
        return
    if od.custom_infer_shape is not None:
        od.custom_infer_shape(op, block)
        return
    import jax
    from ..fluid import core as fcore

    dummy = _pick_dummy(op, block)
    in_structs = {}
    had_dynamic = False
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            v = block._find_var_recursive(n)
            if v is None or v.shape is None:
                return
            if any(d is None or d < 0 for d in v.shape):
                had_dynamic = True
            vals.append(jax.ShapeDtypeStruct(_subst_dummy(v.shape, dummy),
                                             fcore.convert_dtype_to_np(v.dtype)))
        in_structs[slot] = vals

    try:
        out = jax.eval_shape(
            lambda ins: od.lower(ExecContext(op, ins, step=0, seed=0)),
            in_structs)
    except Exception:
        return
    if out is None:
        return
    for slot, vals in out.items():
        names = op.outputs.get(slot, [])
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for n, s in zip(names, vals):
            v = block._find_var_recursive(n)
            if v is None or s is None:
                continue
            v.shape = _restore_dummy(s.shape, had_dynamic, dummy)
            v.dtype = fcore.convert_np_dtype_to_dtype_(s.dtype)


# ---------------------------------------------------------------------------
# generic vjp-based gradients
# ---------------------------------------------------------------------------

def _is_float(x):
    import jax.numpy as jnp
    if x is None:
        return False
    return jnp.issubdtype(jnp.result_type(x), jnp.floating)


def make_forward_and_vjp(op, od, ctx):
    """Run forward; also build the vjp closure over float inputs.

    Returns (outputs_dict, vjp_fn, layout) where vjp_fn maps output cotangent
    pytree -> grads for the float inputs (same dict-of-lists layout, None for
    non-float entries)."""
    import jax

    in_layout = [(slot, len(vals)) for slot, vals in ctx._inputs.items()]
    flat_in = [v for _, vals in ctx._inputs.items() for v in vals]
    diff_idx = [i for i, v in enumerate(flat_in) if _is_float(v)]

    def rebuild(flat):
        d, i = {}, 0
        for slot, n in in_layout:
            d[slot] = list(flat[i:i + n])
            i += n
        return d

    def f(*diff_vals):
        flat = list(flat_in)
        for i, v in zip(diff_idx, diff_vals):
            flat[i] = v
        c2 = ExecContext(op, rebuild(flat), step=ctx.step, seed=ctx.seed,
                         mesh=ctx.mesh)
        outs = call_lower(od, c2)
        # normalized {slot: [vals]} so cotangent trees are predictable
        return {s: list(v) if isinstance(v, (list, tuple)) else [v]
                for s, v in outs.items()}

    primals = [flat_in[i] for i in diff_idx]
    outs, vjp = jax.vjp(f, *primals)

    def vjp_to_slots(cotangents):
        diff_grads = vjp(cotangents)
        flat_grads = [None] * len(flat_in)
        for i, g in zip(diff_idx, diff_grads):
            flat_grads[i] = g
        d, i = {}, 0
        for slot, n in in_layout:
            d[slot] = flat_grads[i:i + n]
            i += n
        return d

    return outs, vjp_to_slots


# ---------------------------------------------------------------------------
# automatic mixed precision (bf16 compute, fp32 master weights)
# ---------------------------------------------------------------------------
# The reference's fp16 story is data_type_transform + a float16 type
# (platform/float16.h) with per-kernel fp16 registrations. TPU-first
# equivalent: matmul-class ops compute in bfloat16 — the MXU natively
# accumulates bf16 inputs in fp32, so no explicit preferred_element_type is
# needed (and setting one breaks jax's conv transpose under AMP; the Pallas
# flash kernel sets it internally). Numerically sensitive ops are forced
# back to fp32, and parameters/optimizer state stay fp32. The casts live
# INSIDE the differentiated lowering call, so gradients flow to the fp32
# primals automatically.

_AMP = {"enabled": False}

# compute-bound ops that should feed the MXU in bf16
AMP_WHITE = frozenset([
    "conv2d", "depthwise_conv2d", "conv3d", "conv2d_transpose",
    "conv3d_transpose", "mul", "matmul", "flash_attention",
])

# numerically sensitive ops: force fp32 inputs. batch_norm is NOT here:
# its lowering computes statistics in fp32 internally and normalizes in
# the input dtype, so forcing fp32 inputs would only double the HBM
# traffic of every activation (bf16 in/out + f32 stats is the
# TPU-idiomatic BN precision split).
AMP_BLACK = frozenset([
    "softmax", "softmax_with_cross_entropy", "cross_entropy",
    "sigmoid_cross_entropy_with_logits", "layer_norm",
    "group_norm", "mean", "reduce_mean", "reduce_sum", "sum", "exp", "log",
    "sequence_softmax", "log_softmax", "linear_chain_crf", "warpctc",
    # optimizer updates accumulate in fp32 master weights
    "sgd", "momentum", "lars_momentum", "adam", "adamax", "adagrad",
    "decayed_adagrad", "adadelta", "rmsprop", "ftrl", "proximal_gd",
])


def set_amp(enabled):
    _AMP["enabled"] = bool(enabled)


def amp_enabled():
    return _AMP["enabled"]


def _amp_cast(vals, to_dtype):
    import jax.numpy as jnp
    out = []
    for v in vals:
        if v is not None and hasattr(v, "dtype") and \
                v.dtype in (jnp.float32, jnp.bfloat16) and \
                v.dtype != to_dtype:
            v = v.astype(to_dtype)
        out.append(v)
    return out


class OpError(RuntimeError):
    """An op lowering failed; the message carries the op's identity and
    its inputs' shapes/dtypes (reference platform/enforce.h: every kernel
    error surfaces with operator context instead of a bare backend
    trace)."""


def _describe_inputs(op, inputs):
    lines = []
    for slot, names in op.inputs.items():
        vals = inputs.get(slot, [])
        for i, n in enumerate(names):
            v = vals[i] if i < len(vals) else None
            if v is None:
                desc = "<missing>"
            elif hasattr(v, "shape"):
                desc = "shape=%s dtype=%s" % (tuple(v.shape),
                                              getattr(v, "dtype", "?"))
            else:
                desc = type(v).__name__
            lines.append("    %s[%d] '%s': %s" % (slot, i, n, desc))
    return lines


def call_lower(od, ctx):
    """All lowering invocations go through here so (a) AMP casts sit
    inside the traced computation and (b) failures re-raise with op
    context — type, input names/shapes/dtypes (enforce.h analogue)."""
    try:
        return _call_lower_inner(od, ctx)
    except (OpError, NotImplementedError):
        raise                     # already actionable / intentional
    except Exception as e:
        lines = ["%s: %s" % (type(e).__name__, e),
                 "  [operator context] op '%s' failed during lowering"
                 % od.type]
        lines += _describe_inputs(ctx.op, ctx._inputs)
        attrs = {}
        for k, v in ctx.attrs.items():
            if k == "sub_block" or k.startswith("fwd_"):
                continue
            r = repr(v)
            # cap each attr: a custom_dist_probs list can hold the whole
            # vocab — the context must stay readable
            attrs[k] = r if len(r) <= 200 else r[:200] + "...<truncated>"
        if attrs:
            lines.append("    attrs: %s" % attrs)
        raise OpError("\n".join(lines)) from e


def _call_lower_inner(od, ctx):
    if not _AMP["enabled"]:
        return od.lower(ctx)
    import jax.numpy as jnp
    if od.type in AMP_WHITE:
        to = jnp.bfloat16
    elif od.type in AMP_BLACK:
        to = jnp.float32
    else:
        return od.lower(ctx)
    new_inputs = {}
    for slot, vals in ctx._inputs.items():
        if slot.endswith("@LOD_LEN"):
            new_inputs[slot] = vals     # integer length companions
        else:
            new_inputs[slot] = _amp_cast(vals, to)
    c2 = ExecContext(ctx.op, new_inputs, step=ctx.step, seed=ctx.seed,
                     mesh=ctx.mesh, env=ctx.env)
    return od.lower(c2)
