"""In-graph checkpoint IO ops — host-side kernels.

Reference analogues: operators/save_op.cc, load_op.cc,
save_combine_op.cc, load_combine_op.cc — tensor serialization with a
version header, executed as ordinary ops inside a program (driven by
fluid.io.save/load_vars, io.py:89-:505).

TPU redesign: these are HOST_OPS (functionalizer.HOST_OPS) — the
segmented program runner executes them eagerly between jitted compute
segments, so a training program containing a `save` op still runs its
compute from the XLA jit cache. Serialization is numpy .npy/.npz (the
same on-disk format as fluid/io.py, so in-graph saves and host-API saves
are interchangeable).
"""

import os

import numpy as np

from .registry import register_op


def _require_concrete(v, op):
    import jax
    if isinstance(v, jax.core.Tracer):
        raise RuntimeError(
            "op '%s' is a host IO op and cannot run under jit — it must "
            "be executed by the segmented host path (this indicates a "
            "mis-partitioned program)" % op)
    return np.asarray(v)


@register_op("save")
def _save(ctx):
    x = _require_concrete(ctx.input("X"), "save")
    path = ctx.attr("file_path")
    if not ctx.attr("overwrite", True) and os.path.exists(path):
        raise RuntimeError("save: %s exists and overwrite=False" % path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        np.save(f, x)
    return {}


@register_op("load")
def _load(ctx):
    import jax.numpy as jnp
    path = ctx.attr("file_path")
    with open(path, "rb") as f:
        arr = np.load(f)
    if ctx.attr("load_as_fp16", False):
        arr = arr.astype(np.float16)
    return {"Out": jnp.asarray(arr)}


@register_op("save_combine")
def _save_combine(ctx):
    xs = ctx.inputs("X")
    names = ctx.op.inputs.get("X", [])
    path = ctx.attr("file_path")
    if not ctx.attr("overwrite", True) and os.path.exists(path):
        raise RuntimeError("save_combine: %s exists and overwrite=False"
                           % path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    arrays = {n.replace("/", "__"): _require_concrete(v, "save_combine")
              for n, v in zip(names, xs)}
    with open(path, "wb") as f:
        np.savez(f, **arrays)
    return {}


@register_op("load_combine")
def _load_combine(ctx):
    import jax.numpy as jnp
    names = ctx.op.outputs.get("Out", [])
    path = ctx.attr("file_path")
    with np.load(path) as z:
        return {"Out": [jnp.asarray(z[n.replace("/", "__")])
                        for n in names]}
