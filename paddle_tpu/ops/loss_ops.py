"""Structured-prediction losses and streaming metrics.

Reference analogues: paddle/fluid/operators/linear_chain_crf_op.h (forward
algorithm at :140 ForwardOneSequence, LogLikelihood = -(score - logZ), i.e.
a cost), crf_decoding_op.h (:98 Viterbi backtrack; with Label given, output
is 1 at correctly decoded positions, :62), warpctc_op.cc (CTC loss via
dynloaded libwarpctc), ctc_align_op.cc (merge repeats, drop blank),
edit_distance_op.h (Levenshtein DP), metrics/auc_op.h (threshold-bucketed
streaming AUC), metrics/precision_recall_op.h, mean_iou_op.h,
rank_loss_op.h, nce_op.h, hierarchical_sigmoid_op.h (MatrixBitCodeFunctor
"SimpleCode": node id = label + num_classes, path = bits of the id),
multiplex_op.cc, sampling_id_op.cc, chunk_eval_op.h.

TPU-first notes: the reference dispatches CTC to a hand-written CUDA library
(warpctc) and runs CRF/chunk_eval on CPU only; here every loss is a pure
jnp/lax program — `lax.scan` over the padded time axis with per-sequence
masks — so forward AND backward fuse into the surrounding XLA computation
and gradients come from the registry's generic vjp, replacing warpctc's
hand-written gradient kernel. Ragged inputs use the padded [B, T, ...] +
lengths encoding from ops/sequence_ops.py.
"""

import numpy as np

from .registry import register_op


def _jnp():
    import jax.numpy as jnp
    return jnp


def _left_pack(x, keep):
    """Pack each row's kept entries to the left (zero fill); dropped entries
    are routed to a discarded extra slot. Returns (packed, new_lens)."""
    jnp = _jnp()
    B, T = x.shape
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    pos = jnp.where(keep, pos, T)
    out = jnp.zeros((B, T + 1), x.dtype)
    out = out.at[jnp.arange(B)[:, None], pos].set(x)[:, :T]
    return out, jnp.sum(keep.astype(jnp.int32), axis=1)


def _op_key(ctx):
    """Per-(op, step) PRNG key, additionally folding in the op's `seed` attr
    so distinct seeds give distinct draws (reference per-op seed semantics)."""
    key = ctx.rng_key()
    seed = ctx.attr("seed", 0) or 0
    if seed:
        import jax
        key = jax.random.fold_in(key, seed)
    return key


def _lens_or_full(ctx, slot, B, T):
    jnp = _jnp()
    lens = ctx.lod_len(slot)
    if lens is None:
        return jnp.full((B,), T, jnp.int32)
    return lens.astype(jnp.int32)


# ---------------------------------------------------------------------------
# linear-chain CRF (linear_chain_crf_op.h)
# ---------------------------------------------------------------------------

@register_op("linear_chain_crf")
def _linear_chain_crf(ctx):
    """Emission [B,T,D]+lens, Transition [D+2,D] (row0=start, row1=end,
    rows2..=pairwise), Label [B,T,1] int. LogLikelihood output is the
    *cost* logZ - score, matching linear_chain_crf_op.h:193 `return -ll`."""
    import jax
    jnp = _jnp()
    emission = ctx.input("Emission")
    trans = ctx.input("Transition")
    label = ctx.input("Label")
    if label.ndim == 3:
        label = label[..., 0]
    label = label.astype(jnp.int32)
    B, T, D = emission.shape
    lens = _lens_or_full(ctx, "Emission", B, T)
    e = emission.astype(jnp.float32)
    w = trans.astype(jnp.float32)
    start, end, pair = w[0], w[1], w[2:]

    # forward algorithm in log domain, masked beyond each sequence's length
    a0 = start[None, :] + e[:, 0]                       # [B, D]

    def step(a_prev, inp):
        e_t, active = inp                               # [B,D], [B]
        sc = a_prev[:, :, None] + pair[None, :, :]      # [B, D, D]
        a_new = e_t + jax.nn.logsumexp(sc, axis=1)
        a = jnp.where(active[:, None], a_new, a_prev)
        return a, a

    ts = jnp.arange(1, T)
    active = ts[None, :] < lens[:, None]                # [B, T-1]
    a_last, alphas = jax.lax.scan(
        step, a0, (jnp.moveaxis(e[:, 1:], 1, 0), jnp.moveaxis(active, 1, 0)))
    log_z = jax.nn.logsumexp(a_last + end[None, :], axis=-1)  # [B]

    # gold path score
    t_idx = jnp.arange(T)[None, :]
    tok_mask = (t_idx < lens[:, None]).astype(jnp.float32)
    emit_score = jnp.sum(
        jnp.take_along_axis(e, label[..., None], axis=2)[..., 0] * tok_mask,
        axis=1)
    pair_scores = pair[label[:, :-1], label[:, 1:]]     # [B, T-1]
    pair_mask = (jnp.arange(1, T)[None, :] < lens[:, None]).astype(jnp.float32)
    trans_score = jnp.sum(pair_scores * pair_mask, axis=1)
    last = jnp.maximum(lens - 1, 0)
    y_last = jnp.take_along_axis(label, last[:, None], axis=1)[:, 0]
    score = emit_score + trans_score + start[label[:, 0]] + end[y_last]

    nll = (log_z - score)[:, None]                      # [B, 1] cost
    # parity buffers (grad flows through nll via vjp, these are diagnostics)
    alpha_full = jnp.concatenate([a0[:, None], jnp.moveaxis(alphas, 0, 1)],
                                 axis=1)
    row_max = jnp.max(e, axis=-1, keepdims=True)
    return {"LogLikelihood": nll.astype(emission.dtype),
            "Alpha": jax.nn.softmax(alpha_full, axis=-1),
            "EmissionExps": jnp.exp(e - row_max),
            "TransitionExps": jnp.exp(w)}


@register_op("crf_decoding")
def _crf_decoding(ctx):
    """Viterbi decode (crf_decoding_op.h:70 Decode). Padded positions emit 0.
    With Label given: 1 at positions decoded correctly (:62)."""
    import jax
    jnp = _jnp()
    emission = ctx.input("Emission")
    trans = ctx.input("Transition")
    B, T, D = emission.shape
    lens = _lens_or_full(ctx, "Emission", B, T)
    e = emission.astype(jnp.float32)
    w = trans.astype(jnp.float32)
    start, end, pair = w[0], w[1], w[2:]

    a0 = start[None, :] + e[:, 0]

    def fwd(a_prev, inp):
        e_t, active = inp
        sc = a_prev[:, :, None] + pair[None, :, :]      # [B, from, to]
        best = jnp.max(sc, axis=1)
        track = jnp.argmax(sc, axis=1).astype(jnp.int32)
        a_new = e_t + best
        a = jnp.where(active[:, None], a_new, a_prev)
        return a, track

    ts = jnp.arange(1, T)
    active = ts[None, :] < lens[:, None]
    a_last, tracks = jax.lax.scan(
        fwd, a0, (jnp.moveaxis(e[:, 1:], 1, 0), jnp.moveaxis(active, 1, 0)))
    final_tag = jnp.argmax(a_last + end[None, :], axis=-1).astype(jnp.int32)

    # backtrack from each sequence's last valid step; while t >= len the
    # carried tag stays final_tag, so at t == len-1 it is the true last tag
    def back(cur, inp):
        track_t, t = inp                                # [B, D], scalar
        prev = jnp.take_along_axis(track_t, cur[:, None], axis=1)[:, 0]
        cur_new = jnp.where(t <= lens - 1, prev, cur)
        return cur_new, cur

    if T > 1:
        carry0, path_rev = jax.lax.scan(
            back, final_tag, (tracks[::-1], jnp.arange(T - 1, 0, -1)))
        # emitted values are tags at positions T-1..1; carry0 is position 0
        path = jnp.concatenate([carry0[:, None], jnp.flip(path_rev, 0).T],
                               axis=1)
    else:
        path = final_tag[:, None]
    tok_mask = jnp.arange(T)[None, :] < lens[:, None]
    path = jnp.where(tok_mask, path, 0)
    if ctx.has_input("Label"):
        label = ctx.input("Label")
        if label.ndim == 3:
            label = label[..., 0]
        out = jnp.where(tok_mask & (label.astype(jnp.int32) == path), 1, 0)
        return {"ViterbiPath": out[..., None].astype(jnp.int64)}
    return {"ViterbiPath": path[..., None].astype(jnp.int64)}


# ---------------------------------------------------------------------------
# CTC (warpctc_op.cc — here a pure lax.scan log-domain forward pass)
# ---------------------------------------------------------------------------

@register_op("warpctc")
def _warpctc(ctx):
    """Logits [B,T,C]+lens (unnormalised), Label [B,L]+label lens.
    Loss [B,1] = -log p(label | logits) via the CTC forward algorithm.
    The reference calls libwarpctc (warpctc_op.cc); gradient here is the
    registry's generic vjp of this forward — exact, no custom kernel."""
    import jax
    jnp = _jnp()
    logits = ctx.input("Logits")
    label = ctx.input("Label")
    if label.ndim == 3:
        label = label[..., 0]
    label = label.astype(jnp.int32)
    blank = ctx.attr("blank", 0)
    B, T, C = logits.shape
    L = label.shape[1]
    in_lens = _lens_or_full(ctx, "Logits", B, T)
    lab_lens = _lens_or_full(ctx, "Label", B, L)

    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    # extended label sequence with interleaved blanks: length S = 2L+1
    S = 2 * L + 1
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(label)                    # [B, S]
    ext_valid = jnp.arange(S)[None, :] < (2 * lab_lens + 1)[:, None]
    neg_inf = jnp.float32(-1e30)

    # can we skip from s-2 to s? only onto a non-blank differing from s-2
    ext_prev2 = jnp.concatenate(
        [jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_prev2)      # [B, S]

    def emit(t):
        return jnp.take_along_axis(logp[:, t], ext, axis=1)  # [B, S]

    a = jnp.where((jnp.arange(S)[None, :] < 2), emit(0), neg_inf)
    a = jnp.where(ext_valid, a, neg_inf)

    def step(a_prev, t):
        shift1 = jnp.concatenate(
            [jnp.full((B, 1), neg_inf), a_prev[:, :-1]], axis=1)
        shift2 = jnp.concatenate(
            [jnp.full((B, 2), neg_inf), a_prev[:, :-2]], axis=1)
        shift2 = jnp.where(can_skip, shift2, neg_inf)
        merged = jnp.logaddexp(jnp.logaddexp(a_prev, shift1), shift2)
        a_new = merged + emit(t)
        a_new = jnp.where(ext_valid, a_new, neg_inf)
        a = jnp.where((t < in_lens)[:, None], a_new, a_prev)
        return a, None

    a_last, _ = jax.lax.scan(step, a, jnp.arange(1, T))
    # p(label) = alpha[S_eff-1] + alpha[S_eff-2], S_eff = 2*lab_len+1;
    # an empty label has S_eff=1 — only the single blank state counts
    idx_last = 2 * lab_lens                              # blank after last lab
    idx_prev = jnp.maximum(2 * lab_lens - 1, 0)
    at_last = jnp.take_along_axis(a_last, idx_last[:, None], axis=1)[:, 0]
    at_prev = jnp.take_along_axis(a_last, idx_prev[:, None], axis=1)[:, 0]
    ll = jnp.where(lab_lens > 0, jnp.logaddexp(at_last, at_prev), at_last)
    loss = -ll[:, None]
    if ctx.attr("norm_by_times", False):
        # the reference scales only the GRADIENT by 1/T (warpctc_op.h
        # grad kernel UnpaddingLoDTensorFunctor norm_by_times); the Loss
        # output stays raw. value = raw, d/dlogits = raw_grad / T:
        import jax
        t = jnp.maximum(in_lens, 1).astype(jnp.float32)[:, None]
        normed = loss / t
        loss = jax.lax.stop_gradient(loss - normed) + normed
    return {"Loss": loss.astype(logits.dtype)}


@register_op("ctc_align")
def _ctc_align(ctx):
    """Greedy CTC decode post-step: merge repeats, drop blanks
    (ctc_align_op.cc). Input [B,T]+lens int; output [B,T] left-packed,
    zero-padded, with new lengths."""
    jnp = _jnp()
    x = ctx.input("Input")
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    if squeeze:
        x = x[..., 0]
    x = x.astype(jnp.int32)
    B, T = x.shape
    lens = _lens_or_full(ctx, "Input", B, T)
    blank = ctx.attr("blank", 0)
    merge = ctx.attr("merge_repeated", True)

    prev = jnp.concatenate([jnp.full((B, 1), -1, jnp.int32), x[:, :-1]],
                           axis=1)
    keep = (x != blank) & (jnp.arange(T)[None, :] < lens[:, None])
    if merge:
        keep = keep & (x != prev)
    out, new_lens = _left_pack(x, keep)
    out = out.astype(jnp.int64)
    if squeeze:
        out = out[..., None]
    return {"Output": out, "Output@LOD_LEN": new_lens}


@register_op("edit_distance")
def _edit_distance(ctx):
    """Levenshtein distance between ragged Hyps and Refs (edit_distance_op.h).
    Out [B,1] float (normalized by ref length if `normalized`),
    SequenceNum [1]."""
    import jax
    jnp = _jnp()
    hyp = ctx.input("Hyps")
    ref = ctx.input("Refs")
    if hyp.ndim == 3:
        hyp = hyp[..., 0]
    if ref.ndim == 3:
        ref = ref[..., 0]
    hyp = hyp.astype(jnp.int32)
    ref = ref.astype(jnp.int32)
    B, Th = hyp.shape
    Tr = ref.shape[1]
    hlens = _lens_or_full(ctx, "Hyps", B, Th)
    rlens = _lens_or_full(ctx, "Refs", B, Tr)

    ignored = ctx.attr("ignored_tokens", []) or []
    if ignored:
        def erase(x, lens):
            keep = jnp.arange(x.shape[1])[None, :] < lens[:, None]
            for tok in ignored:
                keep = keep & (x != tok)
            return _left_pack(x, keep)

        hyp, hlens = erase(hyp, hlens)
        ref, rlens = erase(ref, rlens)

    def one(h, r, hl, rl):
        row0 = jnp.arange(Tr + 1, dtype=jnp.float32)

        def outer(row, i):
            def inner(carry, j):
                # carry = new[j-1]; row[j] is d[i-1][j]
                sub = row[j - 1] + (h[i - 1] != r[j - 1])
                val = jnp.minimum(jnp.minimum(row[j] + 1, carry + 1), sub)
                return val, val

            first = jnp.float32(i)
            _, rest = jax.lax.scan(inner, first, jnp.arange(1, Tr + 1))
            new_row = jnp.concatenate([first[None], rest])
            return jnp.where(i <= hl, new_row, row), None

        final, _ = jax.lax.scan(outer, row0, jnp.arange(1, Th + 1))
        d = final[rl]
        # empty-ref convention (edit_distance_op.h): dist = hyp len
        d = jnp.where(rl == 0, hl.astype(jnp.float32), d)
        return d

    dist = jax.vmap(one)(hyp, ref, hlens, rlens)
    if ctx.attr("normalized", False):
        dist = dist / jnp.maximum(rlens, 1).astype(jnp.float32)
    return {"Out": dist[:, None],
            "SequenceNum": jnp.asarray([B], jnp.int64)}


# ---------------------------------------------------------------------------
# streaming metrics (metrics/auc_op.h, precision_recall_op.h, mean_iou_op.h)
# ---------------------------------------------------------------------------

@register_op("auc", stateful=True)
def _auc(ctx):
    """Threshold-bucketed streaming AUC (metrics/auc_op.h).

    StatPos/StatNeg are persistable state threaded through like
    batch_norm's mean/var, shaped [S, num_thresholds+1]: S=1 rows
    accumulated forever for slide_steps=0 (the reference's "global"
    op instance), S=slide_steps rows used as a ring of per-batch
    histograms otherwise (statAuc:88-127 — each batch shifts the
    window and the AUC integrates the window SUM). The integration
    matches calcAuc:130-157 exactly, including the top trapezoid from
    (0,0) to the bucket-n point (r5 audit: the earlier version dropped
    it, biasing AUC when predictions hit 1.0)."""
    jnp = _jnp()
    pred = ctx.input("Predict")
    label = ctx.input("Label")
    stat_pos = ctx.input("StatPos")
    stat_neg = ctx.input("StatNeg")
    n = ctx.attr("num_thresholds", 200)
    slide = int(ctx.attr("slide_steps", 0) or 0)
    if stat_pos.ndim == 1:          # legacy flat state
        stat_pos = stat_pos[None, :]
        stat_neg = stat_neg[None, :]
    if label.ndim == 2:
        label = label[:, 0]
    p1 = pred[:, -1] if pred.ndim == 2 else pred
    bucket = jnp.clip((p1 * n).astype(jnp.int32), 0, n)
    is_pos = (label > 0).astype(stat_pos.dtype)
    hist_pos = jnp.zeros((n + 1,), stat_pos.dtype).at[bucket].add(is_pos)
    hist_neg = jnp.zeros((n + 1,), stat_neg.dtype).at[bucket].add(
        1 - is_pos)
    if slide <= 0:
        # "global" mode: accumulate forever in the single row
        stat_pos = stat_pos.at[0].add(hist_pos)
        stat_neg = stat_neg.at[0].add(hist_neg)
    else:
        # ring of per-batch histograms; slide==1 replaces the window
        stat_pos = jnp.concatenate([stat_pos[1:], hist_pos[None]], axis=0)
        stat_neg = jnp.concatenate([stat_neg[1:], hist_neg[None]], axis=0)
    win_pos = jnp.sum(stat_pos, axis=0)
    win_neg = jnp.sum(stat_neg, axis=0)
    # for threshold i, TP = sum_{b>=i} pos, FP = sum_{b>=i} neg; pad a
    # trailing 0 so the trapezoid from (0,0) to the bucket-n point is
    # included (calcAuc walks idx = n..0 starting from zero totals)
    tp = jnp.concatenate([jnp.cumsum(win_pos[::-1])[::-1],
                          jnp.zeros((1,), win_pos.dtype)]) \
        .astype(jnp.float32)
    fp = jnp.concatenate([jnp.cumsum(win_neg[::-1])[::-1],
                          jnp.zeros((1,), win_neg.dtype)]) \
        .astype(jnp.float32)
    if ctx.attr("curve", "ROC") == "PR":
        # trapezoid over (recall, precision) points — a superset: the
        # reference kernel ignores `curve` and always integrates ROC
        rec = tp / jnp.maximum(tp[0], 1.0)
        prec = jnp.where(tp + fp > 0, tp / jnp.maximum(tp + fp, 1.0), 1.0)
        auc_val = jnp.sum((rec[:-1] - rec[1:]) * (prec[:-1] + prec[1:]) / 2.0)
    else:
        area = jnp.sum((fp[:-1] - fp[1:]) * (tp[:-1] + tp[1:]) / 2.0)
        denom = tp[0] * fp[0]
        auc_val = jnp.where(denom > 0, area / jnp.maximum(denom, 1.0), 0.0)
    return {"AUC": auc_val.astype(jnp.float32).reshape((1,)),
            "StatPosOut": stat_pos, "StatNegOut": stat_neg}


@register_op("precision_recall", stateful=True)
def _precision_recall(ctx):
    """Multi-class streaming precision/recall/F1 (macro + micro).
    StatesInfo [C,4] = per-class TP, FP, TN, FN (precision_recall_op.h)."""
    jnp = _jnp()
    idx = ctx.input("Indices")
    labels = ctx.input("Labels")
    states = ctx.input("StatesInfo")
    C = states.shape[0]
    if idx.ndim == 2:
        idx = idx[:, 0]
    if labels.ndim == 2:
        labels = labels[:, 0]
    idx = idx.astype(jnp.int32)
    labels = labels.astype(jnp.int32)
    w = ctx.input("Weights")
    wv = w[:, 0] if (w is not None and w.ndim == 2) else \
        (w if w is not None else jnp.ones(idx.shape, jnp.float32))
    pred_oh = (idx[:, None] == jnp.arange(C)[None, :]).astype(jnp.float32)
    lab_oh = (labels[:, None] == jnp.arange(C)[None, :]).astype(jnp.float32)
    wv = wv[:, None]
    tp = jnp.sum(pred_oh * lab_oh * wv, axis=0)
    fp = jnp.sum(pred_oh * (1 - lab_oh) * wv, axis=0)
    fn = jnp.sum((1 - pred_oh) * lab_oh * wv, axis=0)
    tn = jnp.sum((1 - pred_oh) * (1 - lab_oh) * wv, axis=0)
    batch = jnp.stack([tp, fp, tn, fn], axis=1)

    def metrics(st):
        tp_, fp_, tn_, fn_ = st[:, 0], st[:, 1], st[:, 2], st[:, 3]
        prec = jnp.where(tp_ + fp_ > 0, tp_ / jnp.maximum(tp_ + fp_, 1e-12),
                         1.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / jnp.maximum(tp_ + fn_, 1e-12),
                        1.0)
        # macro F1 is the F1 OF the macro-averaged P/R
        # (precision_recall_op.h:144 CalcF1Score(macro_p, macro_r)),
        # NOT the mean of per-class F1s (r5 audit)
        mp, mr = jnp.mean(prec), jnp.mean(rec)
        mf = jnp.where(mp + mr > 0,
                       2 * mp * mr / jnp.maximum(mp + mr, 1e-12), 0.0)
        macro = jnp.stack([mp, mr, mf])
        stp, sfp, sfn = jnp.sum(tp_), jnp.sum(fp_), jnp.sum(fn_)
        mprec = jnp.where(stp + sfp > 0, stp / jnp.maximum(stp + sfp, 1e-12),
                          1.0)
        mrec = jnp.where(stp + sfn > 0, stp / jnp.maximum(stp + sfn, 1e-12),
                         1.0)
        mf1 = jnp.where(mprec + mrec > 0,
                        2 * mprec * mrec / jnp.maximum(mprec + mrec, 1e-12),
                        0.0)
        return jnp.concatenate([macro, jnp.stack([mprec, mrec, mf1])])

    accum = states.astype(jnp.float32) + batch
    return {"BatchMetrics": metrics(batch).astype(jnp.float32),
            "AccumMetrics": metrics(accum).astype(jnp.float32),
            "AccumStatesInfo": accum}


@register_op("mean_iou")
def _mean_iou(ctx):
    """Mean intersection-over-union over classes (mean_iou_op.h)."""
    jnp = _jnp()
    pred = ctx.input("Predictions").astype(jnp.int32).reshape(-1)
    label = ctx.input("Labels").astype(jnp.int32).reshape(-1)
    C = ctx.attr("num_classes")
    cls = jnp.arange(C)[None, :]
    p_oh = (pred[:, None] == cls)
    l_oh = (label[:, None] == cls)
    inter = jnp.sum(p_oh & l_oh, axis=0).astype(jnp.float32)
    union = jnp.sum(p_oh | l_oh, axis=0).astype(jnp.float32)
    # fold streaming accumulators in FIRST so the reported metric covers
    # history too (reference mean_iou_op.h accumulates before dividing)
    wrong = (union - inter).astype(jnp.int32)
    correct = inter.astype(jnp.int32)
    for extra_w in ctx.inputs("InWrongs"):
        wrong = wrong + extra_w
    for extra_c in ctx.inputs("InCorrects"):
        correct = correct + extra_c
    inter_t = correct.astype(jnp.float32)
    union_t = inter_t + wrong.astype(jnp.float32)
    valid = union_t > 0
    iou = jnp.where(valid, inter_t / jnp.maximum(union_t, 1.0), 0.0)
    mean_iou = jnp.sum(iou) / jnp.maximum(
        jnp.sum(valid.astype(jnp.float32)), 1.0)
    # streaming mean accumulators ADD into the output
    # (mean_iou_op.h:77-80,:112 — out_mean_iou starts at sum(InMeanIou)
    # and the batch mean is added on top)
    for extra_m in ctx.inputs("InMeanIou"):
        mean_iou = mean_iou + extra_m.reshape(-1)[0]
    return {"OutMeanIou": mean_iou.reshape((1,)),
            "OutWrong": wrong, "OutCorrect": correct}


# ---------------------------------------------------------------------------
# pairwise / sampled losses (rank_loss_op.h, nce_op.h,
# hierarchical_sigmoid_op.h)
# ---------------------------------------------------------------------------

@register_op("rank_loss")
def _rank_loss(ctx):
    jnp = _jnp()
    label = ctx.input("Label")
    left, right = ctx.input("Left"), ctx.input("Right")
    o = left - right
    return {"Out": jnp.logaddexp(0.0, o) - label * o}


@register_op("lambda_rank")
def _lambda_rank(ctx):
    """LambdaRank cost over per-query (per-sequence) score lists
    (reference legacy LambdaCost): for every in-query pair with
    rel_i > rel_j, |deltaNDCG(i,j)| * log(1 + exp(-(s_i - s_j))),
    where deltaNDCG swaps the two items' positions in the
    score-descending ranking, truncated at NDCG_num. Padded [B, T]
    encoding; O(T^2) pairwise terms batch onto the VPU."""
    jnp = _jnp()
    score = ctx.input("Score")      # model scores [B, T(, 1)]
    rel = ctx.input("Label")        # relevance   [B, T(, 1)]
    lens = ctx.lod_len("Score")
    if lens is None:
        lens = ctx.lod_len("Label")
    if score.ndim == 3:             # padded ragged rows carry a width-1
        score = score[..., 0]       # feature dim
    B, T = score.shape
    rel = rel.reshape(B, T)
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    ndcg_num = int(ctx.attr("NDCG_num", 5))
    valid = jnp.arange(T)[None, :] < lens[:, None]          # [B, T]

    # rank position of each item under score-descending order
    order = jnp.argsort(jnp.where(valid, -score, jnp.inf), axis=1)
    pos = jnp.argsort(order, axis=1)                        # [B, T] 0-based
    gain = jnp.exp2(rel) - 1.0
    disc = jnp.where(pos < ndcg_num,
                     1.0 / jnp.log2(pos.astype(score.dtype) + 2.0), 0.0)
    # ideal DCG truncated at NDCG_num, from relevance-descending order
    ideal_gain = -jnp.sort(jnp.where(valid, -gain, 0.0), axis=1)
    k = min(ndcg_num, T)
    max_dcg = jnp.sum(
        ideal_gain[:, :k] / jnp.log2(jnp.arange(k, dtype=score.dtype)
                                     + 2.0), axis=1)
    safe_max = jnp.where(max_dcg > 0, max_dcg, 1.0)

    dgain = gain[:, :, None] - gain[:, None, :]             # [B, T, T]
    ddisc = disc[:, :, None] - disc[:, None, :]
    dndcg = jnp.abs(dgain * ddisc) / safe_max[:, None, None]
    ds = score[:, :, None] - score[:, None, :]
    pair = (rel[:, :, None] > rel[:, None, :]) & \
        valid[:, :, None] & valid[:, None, :]
    loss = jnp.sum(jnp.where(pair, dndcg * jnp.logaddexp(0.0, -ds), 0.0),
                   axis=(1, 2))
    return {"Out": jnp.where(max_dcg > 0, loss, 0.0)[:, None]}


@register_op("nce")
def _nce(ctx):
    """Noise-contrastive estimation with a uniform sampler (nce_op.h).
    Negatives drawn per step from ctx.rng_key() — deterministic per
    (op, step) like the reference's per-op seed attr."""
    import jax
    jnp = _jnp()
    x = ctx.input("Input")                              # [B, D]
    label = ctx.input("Label")                          # [B, num_true]
    w = ctx.input("Weight")                             # [C, D]
    bias = ctx.input("Bias")
    num_neg = ctx.attr("num_neg_samples", 10)
    C = ctx.attr("num_total_classes", w.shape[0])
    B = x.shape[0]
    num_true = label.shape[1] if label.ndim == 2 else 1
    label = label.reshape(B, num_true).astype(jnp.int32)

    sampler = ctx.attr("sampler", 0)
    if sampler == 1:
        # log-uniform (Zipfian): P(k) = (log(k+2)-log(k+1)) / log(C+1);
        # inverse-transform sample: k = floor(exp(u * log(C+1))) - 1
        u = jax.random.uniform(_op_key(ctx), (B, num_neg))
        neg = jnp.clip((jnp.exp(u * np.log(C + 1.0)) - 1.0)
                       .astype(jnp.int32), 0, C - 1)

        def log_q_of(cls):
            k = cls.astype(jnp.float32)
            q = (jnp.log(k + 2.0) - jnp.log(k + 1.0)) / np.log(C + 1.0)
            return jnp.log(num_neg * q)
    elif sampler == 2:
        # custom distribution (reference nce_op.h CustomSampler via alias
        # tables): sample with jax.random.categorical over log-probs —
        # mathematically the same distribution, alias method not needed
        probs = np.asarray(ctx.attr("custom_dist_probs"), np.float32)
        probs = probs / probs.sum()
        logp_table = jnp.log(jnp.maximum(jnp.asarray(probs), 1e-30))
        neg = jax.random.categorical(
            _op_key(ctx), logp_table[None, :], axis=-1,
            shape=(B, num_neg)).astype(jnp.int32)

        def log_q_of(cls):
            return jnp.log(num_neg) + jnp.take(logp_table, cls)
    else:
        neg = jax.random.randint(_op_key(ctx), (B, num_neg), 0, C)

        def log_q_of(cls):
            return jnp.full(cls.shape, np.log(num_neg / float(C)),
                            jnp.float32)

    samples = jnp.concatenate([label, neg], axis=1)     # [B, true+neg]
    sw = jnp.take(w, samples, axis=0)                   # [B, S, D]
    logits = jnp.einsum("bd,bsd->bs", x, sw)
    if bias is not None:
        logits = logits + jnp.take(bias.reshape(-1), samples)
    # reference formula (nce_op.h:140-151): o = sigmoid(logit) is the
    # model probability, b = num_neg * q(y); cost = -log(o/(o+b)) for
    # true classes, -log(b/(o+b)) for sampled negatives. (NOT the
    # logit-minus-log-q form: o/(o+b) = 1/(1 + b + b*e^-s) differs from
    # sigmoid(s - log b) = 1/(1 + b*e^-s).)
    log_b = log_q_of(samples)                           # log(num_neg*q)
    log_o = jax.nn.log_sigmoid(logits)                  # log sigmoid, stable
    log_ob = jnp.logaddexp(log_o, log_b)                # log(o + b)
    pos = (log_ob - log_o)[:, :num_true].sum(axis=1)
    negl = (log_ob - log_b)[:, num_true:].sum(axis=1)
    cost = (pos + negl)[:, None]
    sw = ctx.input("SampleWeight")
    if sw is not None:
        cost = cost * sw.reshape(-1, 1)
    # reference SampleLogits holds the post-sigmoid sample outputs
    # (nce_op.h:141 overwrites in place)
    return {"Cost": cost, "SampleLogits": jax.nn.sigmoid(logits),
            "SampleLabels": samples.astype(jnp.int64)}


@register_op("hierarchical_sigmoid")
def _hsigmoid(ctx):
    """Default complete-binary-tree code (MatrixBitCodeFunctor SimpleCode,
    hierarchical_sigmoid_op.h): node id c = label + num_classes; the path is
    the bit prefix of c, internal node index at depth j is (c >> (len-1-j))-1
    and the target bit is bit (len-1-j-1)... realised here as: walking c's
    bits from below the MSB."""
    import jax
    jnp = _jnp()
    x = ctx.input("X")                                  # [B, D]
    w = ctx.input("W")                                  # [C-1, D]
    bias = ctx.input("Bias")                            # [C-1] or [C-1,1]
    label = ctx.input("Label").reshape(-1).astype(jnp.int32)
    C = ctx.attr("num_classes")
    max_len = int(np.floor(np.log2(max(C, 2)))) + 1     # max code length

    c = label + C                                       # node ids, >= C
    # code length = index of highest set bit
    code_len = (jnp.floor(jnp.log2(c.astype(jnp.float32)) + 1e-6)
                .astype(jnp.int32))                     # path edges count
    loss = jnp.zeros(x.shape[0], jnp.float32)
    pre_cols = []
    for j in range(max_len):
        # depth-j edge: parent node is c's bit-prefix above position `shift`,
        # the branch taken is bit `shift` itself (SimpleCode calc_index(b) =
        # (c >> (b+1)) - 1, calc_bit(b) = c & (1 << b))
        shift = code_len - 1 - j
        node = jnp.where(shift >= 0,
                         (c >> (jnp.maximum(shift, 0) + 1)) - 1, 0)
        bit = jnp.where(shift >= 0, (c >> jnp.maximum(shift, 0)) & 1, 0)
        valid = (j < code_len)
        wn = jnp.take(w, jnp.clip(node, 0, w.shape[0] - 1), axis=0)
        pre = jnp.einsum("bd,bd->b", x, wn)
        if bias is not None:
            pre = pre + jnp.take(bias.reshape(-1),
                                 jnp.clip(node, 0, w.shape[0] - 1))
        # reference clips pre to [-40, 40], then loss = softrelu(pre) -
        # bit*pre, and PreOut holds the in-place softrelu values
        # (hierarchical_sigmoid_op.h:66-75)
        pre = jnp.clip(pre, -40.0, 40.0)
        soft = jnp.logaddexp(0.0, pre)
        step_loss = soft - bit.astype(jnp.float32) * pre
        loss = loss + jnp.where(valid, step_loss, 0.0)
        pre_cols.append(jnp.where(valid, soft, 0.0))
    return {"Out": loss[:, None],
            "PreOut": jnp.stack(pre_cols, axis=1)}


# ---------------------------------------------------------------------------
# selection / sampling (multiplex_op.cc, sampling_id_op.cc)
# ---------------------------------------------------------------------------

@register_op("multiplex")
def _multiplex(ctx):
    jnp = _jnp()
    xs = ctx.inputs("X")
    ids = ctx.input("Ids").reshape(-1).astype(jnp.int32)
    stacked = jnp.stack(xs, axis=0)                     # [K, B, ...]
    out = stacked[ids, jnp.arange(stacked.shape[1])]
    return {"Out": out}


@register_op("sampling_id")
def _sampling_id(ctx):
    """sampling_id_op.h: draw one class id per row from the row's
    probability vector. Documented deviation: the reference walks the
    unnormalized CDF against u ~ U(min,max) (attrs, default 0..1), so
    rows not summing to 1 skew toward the last class; this lowering
    samples the NORMALIZED categorical (jax.random.categorical), which
    is the distribution the op documents. Draw-for-draw equality is
    impossible anyway (different generators)."""
    import jax
    jnp = _jnp()
    x = ctx.input("X")                                  # [B, C] probs
    logp = jnp.log(jnp.maximum(x, 1e-20))
    out = jax.random.categorical(_op_key(ctx), logp, axis=-1)
    return {"Out": out.astype(jnp.int64)}


# ---------------------------------------------------------------------------
# chunk_eval (chunk_eval_op.h) — chunk F1 for sequence labeling
# ---------------------------------------------------------------------------

# scheme -> (num_tag_types, tag_begin, tag_inside, tag_end, tag_single);
# -1 = the scheme has no such tag (chunk_eval_op.h Compute:110-141)
_CHUNK_SCHEMES = {
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_bounds(tags, mask, scheme, num_types, jnp):
    """Per-position chunk start/end flags + chunk type, vectorised.
    Tag encoding (chunk_eval_op.h): tag = type * num_tag + tag_pos.

    The flags implement the reference's GENERIC ChunkBegin/ChunkEnd
    transition rules (chunk_eval_op.h:83-106) parameterized by the
    scheme's tag constants — not per-scheme shortcuts. The r5 oracle
    audit (test_chunk_eval_matches_reference_oracle) caught two
    divergences in the shortcut version: a bare E/I tag entered from
    'other' or a different type still BEGINS a chunk, and 'plain'
    chunks are runs of equal types, not single tokens."""
    ntag, tb, ti, te, ts = _CHUNK_SCHEMES[scheme]
    typ = jnp.where(tags >= 0, tags // ntag, -1)
    pos = jnp.where(tags >= 0, tags % ntag, -1)
    inside = mask & (typ >= 0) & (typ < num_types)
    typ = jnp.where(inside, typ, -1)
    pos = jnp.where(inside, pos, -1)

    prev_typ = jnp.concatenate([jnp.full_like(typ[:, :1], -1),
                                typ[:, :-1]], axis=1)
    prev_pos = jnp.concatenate([jnp.full_like(pos[:, :1], -1),
                                pos[:, :-1]], axis=1)
    next_typ = jnp.concatenate([typ[:, 1:],
                                jnp.full_like(typ[:, :1], -1)], axis=1)
    next_pos = jnp.concatenate([pos[:, 1:],
                                jnp.full_like(pos[:, :1], -1)], axis=1)

    # ChunkBegin at t: type transition (incl. from 'other'/padding,
    # where prev_typ is -1) always begins; within a same-type run,
    # B/S begin, and I/E begin only after E/S.
    same_prev = prev_typ == typ
    start = inside & (~same_prev | (pos == tb) | (pos == ts) |
                      (((pos == ti) | (pos == te)) &
                       ((prev_pos == te) | (prev_pos == ts))))
    # ChunkEnd at t (ChunkEnd(prev=t, cur=t+1)): type transition ends;
    # within a same-type run, E/S end, and B/I end before B/S.
    same_next = next_typ == typ
    end = inside & (~same_next | (pos == te) | (pos == ts) |
                    (((pos == tb) | (pos == ti)) &
                     ((next_pos == tb) | (next_pos == ts))))
    return start, end, typ


@register_op("chunk_eval")
def _chunk_eval(ctx):
    jnp = _jnp()
    inf = ctx.input("Inference")
    lab = ctx.input("Label")
    if inf.ndim == 3:
        inf = inf[..., 0]
    if lab.ndim == 3:
        lab = lab[..., 0]
    inf = inf.astype(jnp.int32)
    lab = lab.astype(jnp.int32)
    B, T = inf.shape
    lens = _lens_or_full(ctx, "Inference", B, T)
    mask = jnp.arange(T)[None, :] < lens[:, None]
    scheme = ctx.attr("chunk_scheme", "IOB")
    num_types = ctx.attr("num_chunk_types")
    excluded = ctx.attr("excluded_chunk_types", []) or []

    i_start, i_end, i_typ = _chunk_bounds(inf, mask, scheme, num_types, jnp)
    l_start, l_end, l_typ = _chunk_bounds(lab, mask, scheme, num_types, jnp)
    for ex in excluded:
        i_start = i_start & (i_typ != ex)
        l_start = l_start & (l_typ != ex)
        i_end = i_end & (i_typ != ex)
        l_end = l_end & (l_typ != ex)

    import jax

    # chunk end index for a chunk starting at s = first t >= s with end[t];
    # computed as a reverse running-min of flagged indices
    def next_end_idx(end_flags):
        idx = jnp.where(end_flags, jnp.arange(T)[None, :], T + 1)
        return jnp.flip(jax.lax.cummin(jnp.flip(idx, axis=1), axis=1), axis=1)

    i_ends = next_end_idx(i_end)
    l_ends = next_end_idx(l_end)
    correct = (i_start & l_start & (i_typ == l_typ) &
               (i_ends == l_ends))
    num_i = jnp.sum(i_start.astype(jnp.int64))
    num_l = jnp.sum(l_start.astype(jnp.int64))
    num_c = jnp.sum(correct.astype(jnp.int64))
    prec = jnp.where(num_i > 0, num_c / jnp.maximum(num_i, 1), 0.0)
    rec = jnp.where(num_l > 0, num_c / jnp.maximum(num_l, 1), 0.0)
    f1 = jnp.where(num_c > 0, 2 * prec * rec /
                   jnp.maximum(prec + rec, 1e-12), 0.0)
    return {"Precision": prec.astype(jnp.float32).reshape((1,)),
            "Recall": rec.astype(jnp.float32).reshape((1,)),
            "F1-Score": f1.astype(jnp.float32).reshape((1,)),
            "NumInferChunks": num_i.reshape((1,)),
            "NumLabelChunks": num_l.reshape((1,)),
            "NumCorrectChunks": num_c.reshape((1,))}


@register_op("positive_negative_pair", stateful=True)
def _positive_negative_pair(ctx):
    """Ranking pair statistics per query (reference
    metrics/positive_negative_pair_op.h:44-110): every same-query item
    pair with differing labels contributes w = (w_i + w_j)/2 — positive
    when the score ordering agrees with the label ordering, negative
    otherwise; equal scores ALSO add to neutral (the reference counts a
    tie as neutral AND negative). Accumulator inputs make it streaming."""
    jnp = _jnp()
    score = ctx.input("Score")
    label = ctx.input("Label")
    query = ctx.input("QueryID")
    weight = ctx.input("Weight")
    col = ctx.attr("column", 0)
    s = score[:, col] if score.ndim == 2 else score.reshape(-1)
    lab = label.reshape(-1).astype(s.dtype)
    q = query.reshape(-1)
    n = s.shape[0]
    w = (weight.reshape(-1).astype(s.dtype) if weight is not None
         else jnp.ones((n,), s.dtype))
    pair_w = (w[:, None] + w[None, :]) * 0.5
    valid = ((q[:, None] == q[None, :])
             & (lab[:, None] != lab[None, :])
             & jnp.triu(jnp.ones((n, n), bool), k=1))
    sd = s[:, None] - s[None, :]
    ld = lab[:, None] - lab[None, :]
    agree = sd * ld > 0
    pos = jnp.sum(jnp.where(valid & agree, pair_w, 0.0))
    neg = jnp.sum(jnp.where(valid & ~agree, pair_w, 0.0))
    neu = jnp.sum(jnp.where(valid & (sd == 0), pair_w, 0.0))
    acc_p = ctx.input("AccumulatePositivePair")
    acc_n = ctx.input("AccumulateNegativePair")
    acc_u = ctx.input("AccumulateNeutralPair")
    if acc_p is not None and acc_n is not None and acc_u is not None:
        pos = pos + acc_p.reshape(-1)[0]
        neg = neg + acc_n.reshape(-1)[0]
        neu = neu + acc_u.reshape(-1)[0]
    f32 = jnp.float32
    return {"PositivePair": pos.astype(f32).reshape(1),
            "NegativePair": neg.astype(f32).reshape(1),
            "NeutralPair": neu.astype(f32).reshape(1)}
