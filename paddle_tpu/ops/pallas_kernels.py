"""Pallas TPU kernels for the ops XLA's fusion won't schedule optimally.

No direct reference analogue — the reference's hand-written CUDA kernels
(paddle/legacy/cuda, operators/math/*.cu) fill this role; on TPU the op set
that merits hand kernels is much smaller because XLA fuses elementwise
chains into matmuls. Flash attention is the headline case: the [S, S] score
matrix never leaves VMEM, with online-softmax accumulation over K/V blocks
(see /opt/skills/guides/pallas_guide.md).

The kernel runs in interpret mode off-TPU so the same code path is unit
tested on the CPU mesh. Gradients via jax.custom_vjp: the backward pass is
a blockwise (flash-style) recomputation in plain XLA — O(S * block) memory.
"""

import functools

import numpy as np

__all__ = ["flash_attention"]

_NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_q,
            block_k):
    """One (batch*head, q-block) program: fori_loop over K/V blocks with
    the online-softmax state held in registers/VMEM values (no scratch
    round-trips)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    S = k_ref.shape[1]
    nk = S // block_k

    q = q_ref[0]                      # [BQ, D]
    qpos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def compute(ik, state):
        o, l, m = state
        k = k_ref[0, pl.ds(ik * block_k, block_k), :]
        v = v_ref[0, pl.ds(ik * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            kpos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos > qpos, _NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)                    # [BQ, BK]
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        o = o * alpha + pv
        return o, l, m_new

    if causal:
        # fixed trip count (keeps the loop pipelineable); blocks entirely
        # above the diagonal are skipped with a cheap predicate
        def body(ik, state):
            return jax.lax.cond(
                ik * block_k <= (iq + 1) * block_q - 1,
                lambda st: compute(ik, st), lambda st: st, state)
    else:
        body = compute

    o0 = jnp.zeros((block_q, q_ref.shape[2]), jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    o, l, _ = jax.lax.fori_loop(0, nk, body, (o0, l0, m0))
    o_ref[0] = (o / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)


def _flash_fwd_pallas(q, k, v, scale, causal, block_q, block_k,
                      interpret):
    """q,k,v [BH, S, D] -> o [BH, S, D]."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape
    nq = S // block_q
    grid = (BH, nq)
    kern = functools.partial(_kernel, scale=scale, causal=causal,
                             block_q=block_q, block_k=block_k)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)


def _softmax_stats(q, k, scale, causal, block_k):
    """Recompute per-row logsumexp L [BH, S] blockwise — only [S, block_k]
    score tiles live, matching the O(S*block) memory of the rest of the
    backward."""
    import jax
    import jax.numpy as jnp
    BH, S, D = q.shape
    nb = S // block_k
    qpos = jnp.arange(S)

    def block(carry, jb):
        m, l = carry
        ks = jax.lax.dynamic_slice_in_dim(k, jb * block_k, block_k, 1)
        s = jnp.einsum("bqd,bkd->bqk", q, ks) * scale
        if causal:
            kpos = jb * block_k + jnp.arange(block_k)
            s = jnp.where((kpos[None, :] > qpos[:, None])[None],
                          _NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(s - m_new[..., None]), axis=-1)
        return (m_new, l), None

    m0 = jnp.full((BH, S), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((BH, S), jnp.float32)
    (m, l), _ = jax.lax.scan(block, (m0, l0), jnp.arange(nb))
    return m + jnp.log(jnp.maximum(l, 1e-20))


def _flash_bwd(scale, causal, block_k, res, do):
    """Blockwise flash backward in plain XLA: scan over K/V blocks, keeping
    only [S, block] score tiles live."""
    import jax
    import jax.numpy as jnp
    q, k, v, o = res
    BH, S, D = q.shape
    L = _softmax_stats(q, k, scale, causal, block_k)   # [BH, S]
    Drow = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                   axis=-1)                        # [BH, S]
    nb = S // block_k
    qpos = jnp.arange(S)

    def block(carry, jb):
        dq = carry
        ks = jax.lax.dynamic_slice_in_dim(k, jb * block_k, block_k, 1)
        vs = jax.lax.dynamic_slice_in_dim(v, jb * block_k, block_k, 1)
        s = jnp.einsum("bqd,bkd->bqk", q, ks) * scale
        if causal:
            kpos = jb * block_k + jnp.arange(block_k)
            s = jnp.where((kpos[None, :] > qpos[:, None])[None],
                          _NEG_INF, s)
        p = jnp.exp(s - L[..., None])              # [BH, S, BK]
        dv = jnp.einsum("bqk,bqd->bkd", p, do.astype(p.dtype))
        dp = jnp.einsum("bqd,bkd->bqk", do.astype(p.dtype), vs)
        ds = p * (dp - Drow[..., None])
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, ks) * scale
        dk = jnp.einsum("bqk,bqd->bkd", ds, q) * scale
        return dq, (dk, dv)

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dq, (dks, dvs) = jax.lax.scan(block, dq0, jnp.arange(nb))
    dk = jnp.moveaxis(dks, 0, 1).reshape(BH, S, D)
    dv = jnp.moveaxis(dvs, 0, 1).reshape(BH, S, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=None):
    """Fused attention: q,k,v [B, S, H, D] -> [B, S, H, D].

    Pallas kernel on TPU (interpret-mode elsewhere); differentiable via a
    blockwise custom VJP. Falls back to plain attention when S is not
    divisible by the block size."""
    import jax
    import jax.numpy as jnp

    B, S, H, D = q.shape
    scale = float(scale if scale is not None else 1.0 / np.sqrt(D))
    bq = block_q or min(128, S)
    bk = block_k or min(128, S)
    if S % bq or S % bk:
        from ..parallel.ring_attention import local_attention
        return local_attention(q, k, v, causal=causal, scale=scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    def from_bh(x):
        return x.reshape(B, H, S, D).transpose(0, 2, 1, 3)

    @jax.custom_vjp
    def _fa(qb, kb, vb):
        return _flash_fwd_pallas(qb, kb, vb, scale, causal, bq, bk,
                                 interpret)

    def _fa_fwd(qb, kb, vb):
        o = _flash_fwd_pallas(qb, kb, vb, scale, causal, bq, bk, interpret)
        return o, (qb, kb, vb, o)

    _fa.defvjp(_fa_fwd, functools.partial(_flash_bwd, scale, causal, bk))

    return from_bh(_fa(to_bh(q), to_bh(k), to_bh(v)))


# ---------------------------------------------------------------------------
# framework op wrapper: fluid programs reach the kernel via this op type
# ---------------------------------------------------------------------------

from .registry import register_op  # noqa: E402


@register_op("flash_attention")
def _flash_attention_op(ctx):
    q = ctx.input("Q")
    k = ctx.input("K")
    v = ctx.input("V")
    reshaped = False
    if q.ndim == 3:           # [B, S, D] with num_heads attr
        H = int(ctx.attr("num_heads", 1))
        B, S, Dm = q.shape
        if Dm % H:
            raise ValueError(
                "flash_attention: hidden size %d not divisible by "
                "num_heads %d" % (Dm, H))
        q = q.reshape(B, S, H, Dm // H)
        k = k.reshape(B, S, H, Dm // H)
        v = v.reshape(B, S, H, Dm // H)
        reshaped = True
    out = flash_attention(q, k, v, causal=bool(ctx.attr("causal", False)))
    if reshaped:
        out = out.reshape(B, S, Dm)
    return {"Out": out}
