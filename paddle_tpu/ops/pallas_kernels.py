"""Pallas TPU kernels for the ops XLA's fusion won't schedule optimally.

No direct reference analogue — the reference's hand-written CUDA kernels
(paddle/legacy/cuda, operators/math/*.cu) fill this role; on TPU the op set
that merits hand kernels is much smaller because XLA fuses elementwise
chains into matmuls. Flash attention is the headline case: the [S, S] score
matrix never leaves VMEM, with online-softmax accumulation over K/V blocks
(see /opt/skills/guides/pallas_guide.md).

Every contraction family here — flash attention fwd/bwd, decode
attention, fused dequant-matmul — instantiates ONE tiled-contraction
driver (`tiled_contraction`, the Tensor Processing Primitives shape,
PAPERS.md): the driver owns the grid/BlockSpec plumbing, the streamed
operand staging, fp32 accumulator init on the first reduction tile and
finalize on the last, compiler dimension semantics, and the
interpret-vs-Mosaic dispatch; a family plugs in a small epilogue pair
(`tile`/`finalize`) — online softmax for flash fwd + decode, transposed-
stationarity gradient folds for flash bwd, in-register dequant with a
per-channel (or per-head, for the int8 KV cache) scale at finalize for
the quantized families.  Block geometry resolves per shape at trace time
through ops/attention_tuning.py (FLAGS override > tuning registry >
heuristic); `tools/tune_kernels.py` sweeps and writes every namespace.

The kernels run in interpret mode off-TPU so the same code paths are unit
tested on the CPU mesh; `interpret=None` defers the choice to lowering
time so cross-platform exports embed the real Mosaic modules for tpu.
"""

import contextlib
import functools
import threading

import numpy as np

from . import attention_tuning

__all__ = ["tiled_contraction", "flash_attention", "decode_attention",
           "decode_attention_reference", "decode_attention_head_slice",
           "fused_bottleneck",
           "bottleneck_reference", "dequant_matmul",
           "dequant_matmul_reference", "mosaic_lowering"]

# Finite mask value (not -inf): exp(_NEG_INF - finite) underflows to an
# exact 0, and the logsumexp of a fully-masked row stays finite, so the
# ring-hop merge (parallel/ring_attention.py) never sees inf - inf.
_NEG_INF = -1e30
_TINY = 1e-20
_MIN_LANES = attention_tuning.MIN_LANES


def _compiler_params(**kw):
    """jax renamed TPUCompilerParams -> CompilerParams across 0.4.x/0.5;
    resolve whichever this install ships."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)


_DISPATCH = threading.local()


@contextlib.contextmanager
def mosaic_lowering(enable=True):
    """Force the interpret-vs-Mosaic choice for ``interpret=None`` call
    sites in this thread. functionalizer.export_step_for_tpu enters this
    while tracing, so off-chip TPU exports from a CPU-only host embed the
    real Mosaic kernels."""
    prev = getattr(_DISPATCH, "force_kernel", None)
    _DISPATCH.force_kernel = bool(enable)
    try:
        yield
    finally:
        _DISPATCH.force_kernel = prev


def _interpret_dispatch(call, interpret, *ops):
    """Kernel-vs-interpret dispatch shared by every Pallas entry point:
    an explicit `interpret` wins; None resolves at TRACE time — the real
    kernel when the trace targets TPU (tpu backend, or inside a
    mosaic_lowering() export context), interpret emulation elsewhere.

    This jax's lax.platform_dependent cannot serve here: it stages the
    dead Mosaic branch into single-platform CPU jits, whose pallas
    lowering rejects interpret=False outright."""
    import jax
    if interpret is None:
        force = getattr(_DISPATCH, "force_kernel", None)
        interpret = (jax.default_backend() != "tpu") if force is None \
            else not force
    return call(interpret, *ops)


def _causal_tile_live(iq, ik, block_q, block_kv):
    """A (q-tile, kv-tile) pair intersects the causal lower triangle iff
    the tile's first k row is <= its last q row."""
    return ik * block_kv <= (iq + 1) * block_q - 1


def _causal_tile_mask(s, iq, ik, block_q, block_kv):
    import jax
    import jax.numpy as jnp
    qpos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 0)
    kpos = ik * block_kv + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 1)
    return jnp.where(kpos > qpos, _NEG_INF, s)


# ---------------------------------------------------------------------------
# tiled-contraction substrate (the TPP refactor, PAPERS.md / ROOFLINE.md
# "Kernel substrate"): one parameterized driver owns everything the
# kernel families used to hand-copy — grid/BlockSpec plumbing, streamed
# operand staging, accumulator init on the first reduction tile,
# finalize on the last, compiler dimension semantics, interpret
# dispatch.  A family is a `tile`/`finalize` epilogue pair plugged into
# the driver; the shared epilogue helpers below (online softmax,
# softmax finalize, in-register dequant staging) are the reusable
# pieces those pairs compose from.
# ---------------------------------------------------------------------------


class _TileCtx(object):
    """What one grid step of a tiled contraction sees: the staged
    operand refs, the output refs, the accumulator scratch refs, and
    the grid coordinates (`ids`; `reduce_id`/`n_reduce` index the
    streamed reduction axis)."""

    __slots__ = ("ins", "outs", "scratch", "ids", "reduce_id",
                 "n_reduce")

    def __init__(self, ins, outs, scratch, ids, reduce_id, n_reduce):
        self.ins = ins
        self.outs = outs
        self.scratch = scratch
        self.ids = ids
        self.reduce_id = reduce_id
        self.n_reduce = n_reduce


def tiled_contraction(operands, *, grid, reduce_axis, in_specs,
                      out_specs, out_shape, scratch=(), scratch_fill=(),
                      tile=None, finalize=None, tile_live=None,
                      interpret=None):
    """THE tiled-contraction core every kernel family instantiates.

    `grid` runs with "parallel" semantics on every axis except
    `reduce_axis` (the streamed axis, "arbitrary"): whatever operand
    re-stages along that axis streams through the pipeline while the
    rest stay resident — the staging IS the BlockSpec index map.  Each
    scratch buffer resets to its `scratch_fill` value on the first
    reduction tile and `finalize(ctx)` writes the outputs from the
    accumulators on the last (normalization, per-channel dequant
    scales, and dtype casts live there).  `tile(ctx)` folds one
    reduction tile into the accumulators; `tile_live(ids)` optionally
    gates dead tiles (the causal upper triangle) out of the MXU work —
    the tile's DMA is already in flight, the compute is what matters.
    `interpret=None` resolves interpret-vs-Mosaic at trace time
    (_interpret_dispatch), like every kernel here always has."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    n_in = len(operands)
    n_out = len(out_shape) if isinstance(out_shape, (list, tuple)) else 1
    fills = tuple(scratch_fill) + (0.0,) * (len(scratch)
                                            - len(scratch_fill))

    def kern(*refs):
        ids = tuple(pl.program_id(i) for i in range(len(grid)))
        ctx = _TileCtx(refs[:n_in], refs[n_in:n_in + n_out],
                       refs[n_in + n_out:], ids, ids[reduce_axis],
                       pl.num_programs(reduce_axis))

        if ctx.scratch:
            @pl.when(ctx.reduce_id == 0)
            def _init():
                for ref, fill in zip(ctx.scratch, fills):
                    ref[...] = jnp.full_like(ref, fill)

        if tile_live is not None:
            @pl.when(tile_live(ids))
            def _tile():
                tile(ctx)
        else:
            tile(ctx)

        @pl.when(ctx.reduce_id == ctx.n_reduce - 1)
        def _finalize():
            finalize(ctx)

    sem = tuple("arbitrary" if i == reduce_axis else "parallel"
                for i in range(len(grid)))

    def call(interp, *ops):
        return pl.pallas_call(
            kern, grid=grid, in_specs=list(in_specs),
            out_specs=out_specs, out_shape=out_shape,
            scratch_shapes=list(scratch),
            compiler_params=_compiler_params(dimension_semantics=sem),
            interpret=interp,
        )(*ops)

    return _interpret_dispatch(call, interpret, *operands)


def _online_softmax_tile(s, pv_of, acc_ref, m_ref, l_ref):
    """Online-softmax epilogue shared by flash forward and decode
    attention: fold one masked f32 score tile `s` [R, BKV] into the
    running row max / normalizer / accumulator, rescaling prior
    contributions by alpha.  `pv_of(p)` contracts the tile
    probabilities against the resident value tile — an MXU matmul for
    flash, a VPU lane reduction for decode."""
    import jax.numpy as jnp
    m_prev = m_ref[...]                            # [R, LANES]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1)[:, None])
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[:, :1])                  # [R, BKV] f32
    l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1)[:, None]
    acc_ref[...] = acc_ref[...] * alpha[:, :1] + pv_of(p)
    m_ref[...] = m_new


def _softmax_finalize(acc_ref, m_ref, l_ref):
    """Normalize a finished online-softmax accumulator; returns
    (o_f32, lse) for the caller to cast/write — any constant per-row
    scale (the int8 KV epilogue's per-head V scale) folds in after the
    divide, once per output element."""
    import jax.numpy as jnp
    l = jnp.maximum(l_ref[:, :1], _TINY)
    return acc_ref[...] / l, m_ref[:, :1] + jnp.log(l)


def _stage_dequant(w, dtype):
    """In-register dequant staging (QUANTIZE.md; TPP's fused
    dequant-contraction shape): an int8 tile streamed from HBM is cast
    to the compute dtype the moment it lands in VMEM — float weights /
    KV rows never exist in HBM.  Symmetric per-channel (or per-head)
    scales distribute over the reduction, so they apply ONCE at
    finalize, never per streamed element."""
    return w.astype(dtype)


def _flash_fwd_pallas(q, k, v, scale, causal, block_q, block_kv,
                      interpret):
    """q,k,v [BH, S, D] -> (o [BH, S, D], lse [BH, S] f32): the
    online-softmax instantiation — Q and the (acc, m, l) state resident
    per (bh, q-block) output tile, K/V tiles streamed on the reduction
    axis."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape

    def tile(ctx):
        q_ref, k_ref, v_ref = ctx.ins
        acc_ref, m_ref, l_ref = ctx.scratch
        qb = q_ref[0]                                  # [BQ, D]
        kb = k_ref[0]                                  # [BKV, D]
        vb = v_ref[0]
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_tile_mask(s, ctx.ids[1], ctx.ids[2], block_q,
                                  block_kv)
        _online_softmax_tile(
            s,
            lambda p: jax.lax.dot_general(
                p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32),
            acc_ref, m_ref, l_ref)

    def finalize(ctx):
        o_ref, lse_ref = ctx.outs
        acc_ref, m_ref, l_ref = ctx.scratch
        o, lse = _softmax_finalize(acc_ref, m_ref, l_ref)
        o_ref[0] = o.astype(o_ref.dtype)
        lse_ref[0] = lse

    live = None
    if causal:
        live = lambda ids: _causal_tile_live(  # noqa: E731
            ids[1], ids[2], block_q, block_kv)
    o, lse = tiled_contraction(
        (q, k, v),
        grid=(BH, S // block_q, S // block_kv),
        reduce_axis=2,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_kv, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_kv, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, 1), jnp.float32),
        ],
        scratch=[pltpu.VMEM((block_q, D), jnp.float32),
                 pltpu.VMEM((block_q, _MIN_LANES), jnp.float32),
                 pltpu.VMEM((block_q, _MIN_LANES), jnp.float32)],
        scratch_fill=(0.0, _NEG_INF, 0.0),
        tile=tile, finalize=finalize, tile_live=live,
        interpret=interpret)
    return o, lse[..., 0]


def _flash_bwd_pallas(q, k, v, do, lse, di, scale, causal, block_q,
                      block_kv, interpret):
    """Fused backward: two instantiations with transposed stationarity
    (the dq pass streams K/V under resident q/do rows; the dkv pass
    streams q/do rows under a resident K/V block, so neither gradient
    needs a cross-program reduction).  di = rowsum(do * o) - dlse (the
    dlse term folds the lse output's cotangent into the same ds
    formula: d lse_i / d s_ij = p_ij)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape
    nq, nk = S // block_q, S // block_kv
    lse = lse[..., None]
    di = di[..., None]

    def dq_tile(ctx):
        q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref = ctx.ins
        (acc_ref,) = ctx.scratch
        qb = q_ref[0]
        kb = k_ref[0]
        vb = v_ref[0]
        dob = do_ref[0]
        lseb = lse_ref[0]                              # [BQ, 1]
        dib = di_ref[0]
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_tile_mask(s, ctx.ids[1], ctx.ids[2], block_q,
                                  block_kv)
        p = jnp.exp(s - lseb)                          # [BQ, BKV] f32
        dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - dib) * scale).astype(kb.dtype)
        acc_ref[...] = acc_ref[...] + jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    def dq_finalize(ctx):
        dq_ref = ctx.outs[0]
        dq_ref[0] = ctx.scratch[0][...].astype(dq_ref.dtype)

    qspec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    rowspec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    kvspec = pl.BlockSpec((1, block_kv, D), lambda b, i, j: (b, j, 0))
    live = None
    if causal:
        live = lambda ids: _causal_tile_live(  # noqa: E731
            ids[1], ids[2], block_q, block_kv)
    dq = tiled_contraction(
        (q, k, v, do, lse, di),
        grid=(BH, nq, nk),
        reduce_axis=2,
        in_specs=[qspec, kvspec, kvspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch=[pltpu.VMEM((block_q, D), jnp.float32)],
        tile=dq_tile, finalize=dq_finalize, tile_live=live,
        interpret=interpret)

    # kv-stationary twin: grid axis 1 walks KV blocks, the reduction
    # axis streams Q/dO/lse/di row tiles under the resident K/V block
    def dkv_tile(ctx):
        q_ref, do_ref, lse_ref, di_ref, k_ref, v_ref = ctx.ins
        dk_acc, dv_acc = ctx.scratch
        qb = q_ref[0]
        dob = do_ref[0]
        kb = k_ref[0]
        vb = v_ref[0]
        lseb = lse_ref[0]
        dib = di_ref[0]
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_tile_mask(s, ctx.ids[2], ctx.ids[1], block_q,
                                  block_kv)
        p = jnp.exp(s - lseb)                          # [BQ, BKV] f32
        pv = p.astype(dob.dtype)
        dv_acc[...] = dv_acc[...] + jax.lax.dot_general(
            pv, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - dib) * scale).astype(qb.dtype)
        dk_acc[...] = dk_acc[...] + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    def dkv_finalize(ctx):
        dk_ref, dv_ref = ctx.outs
        dk_acc, dv_acc = ctx.scratch
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)

    qspec_t = pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0))
    rowspec_t = pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0))
    kvspec_t = pl.BlockSpec((1, block_kv, D), lambda b, j, i: (b, j, 0))
    live_t = None
    if causal:
        live_t = lambda ids: _causal_tile_live(  # noqa: E731
            ids[2], ids[1], block_q, block_kv)
    dk, dv = tiled_contraction(
        (q, do, lse, di, k, v),
        grid=(BH, nk, nq),
        reduce_axis=2,
        in_specs=[qspec_t, qspec_t, rowspec_t, rowspec_t, kvspec_t,
                  kvspec_t],
        out_specs=[kvspec_t, kvspec_t],
        out_shape=[jax.ShapeDtypeStruct((BH, S, D), k.dtype),
                   jax.ShapeDtypeStruct((BH, S, D), v.dtype)],
        scratch=[pltpu.VMEM((block_kv, D), jnp.float32),
                 pltpu.VMEM((block_kv, D), jnp.float32)],
        tile=dkv_tile, finalize=dkv_finalize, tile_live=live_t,
        interpret=interpret)
    return dq, dk, dv


def _reference_lse(q, k, scale, causal):
    """Plain-XLA row logsumexp for the non-tileable fallback path (same
    finite-mask convention as the kernels)."""
    import jax.numpy as jnp
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    s = jnp.einsum("bqhd,bkhd->bqhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.arange(Sk)[None, :] > jnp.arange(Sq)[:, None]
        s = jnp.where(mask[None, :, None, :], _NEG_INF, s)
    m = jnp.max(s, axis=-1)
    return m + jnp.log(jnp.maximum(
        jnp.sum(jnp.exp(s - m[..., None]), axis=-1), _TINY))


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_kv=None, block_q_bwd=None, block_kv_bwd=None,
                    interpret=None, return_lse=False, block_k=None):
    """Fused attention: q,k,v [B, S, H, D] -> [B, S, H, D]
    (or (out, lse [B, S, H] f32) with return_lse — the residual the
    ring-attention hop merge consumes).

    Pallas kernel pair on TPU (interpret-mode elsewhere): a tiled
    forward emitting the row logsumexp, and a fused backward (dq +
    dkv kernels) via custom VJP. Block geometry defaults per shape
    through ops/attention_tuning.py (FLAGS override > tune cache >
    MXU-aligned heuristic); explicit block args win over all of it.
    Falls back to plain attention when no geometry divides S.
    `block_k` is the pre-tuning alias of `block_kv`."""
    import jax
    import jax.numpy as jnp

    B, S, H, D = q.shape
    scale = float(scale if scale is not None else 1.0 / np.sqrt(D))
    block_kv = block_kv or block_k
    cfg = attention_tuning.get_config(S, D, causal,
                                      jnp.dtype(q.dtype).name)
    bq = int(block_q or (cfg.block_q if cfg else 0))
    bkv = int(block_kv or (cfg.block_kv if cfg else 0))
    bq_b = int(block_q_bwd or (cfg.block_q_bwd if cfg else 0)) or bq
    bkv_b = int(block_kv_bwd or (cfg.block_kv_bwd if cfg else 0)) or bkv
    if (not bq or not bkv or S % bq or S % bkv or S % bq_b or S % bkv_b):
        from ..parallel.ring_attention import local_attention
        out = local_attention(q, k, v, causal=causal, scale=scale)
        if return_lse:
            return out, _reference_lse(q, k, scale, causal)
        return out
    # interpret=None defers the interpret-vs-Mosaic choice to LOWERING
    # time (_interpret_dispatch platform_dependent), so cross-platform
    # exports embed the real kernels for tpu

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    def from_bh(x):
        return x.reshape(B, H, S, D).transpose(0, 2, 1, 3)

    @jax.custom_vjp
    def _fa(qb, kb, vb):
        return _flash_fwd_pallas(qb, kb, vb, scale, causal, bq, bkv,
                                 interpret)

    def _fa_fwd(qb, kb, vb):
        o, lse = _flash_fwd_pallas(qb, kb, vb, scale, causal, bq, bkv,
                                   interpret)
        return (o, lse), (qb, kb, vb, o, lse)

    def _fa_bwd(res, cts):
        qb, kb, vb, o, lse = res
        do, dlse = cts
        di = (jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                      axis=-1)
              - dlse.astype(jnp.float32))              # [BH, S]
        return _flash_bwd_pallas(qb, kb, vb, do.astype(qb.dtype), lse,
                                 di, scale, causal, bq_b, bkv_b,
                                 interpret)

    _fa.defvjp(_fa_fwd, _fa_bwd)

    o, lse = _fa(to_bh(q), to_bh(k), to_bh(v))
    if return_lse:
        return from_bh(o), lse.reshape(B, H, S).transpose(0, 2, 1)
    return from_bh(o)


# ---------------------------------------------------------------------------
# decode attention: the serving-side kernel (SERVING.md continuous
# batching). One new query token per KV-cache slot attends over that
# slot's cached prefix — the memory-roofline-bound shape ROOFLINE.md
# names for generation: ~zero FLOP reuse, the win is streaming the K/V
# slot cache through VMEM exactly once per step. The instantiation is
# q-stationary per slot (all heads resident) with kv-cache blocks
# streamed on the reduction axis under the shared online-softmax
# epilogue; positions at or past the slot's live length are masked with
# the same finite _NEG_INF convention as the training kernels.
#
# The int8 KV-cache variant (QUANTIZE.md "Quantized KV cache") streams
# the SAME tiles at one byte per element: `kv_scales` carries the
# per-head symmetric fp32 scales of the quantized cache, int8 tiles
# dequantize in-register via _stage_dequant, the K scale folds into the
# per-head score scale and the V scale applies once at finalize — a 4x
# cut of the byte stream that bounds decode (ROOFLINE.md), same kernel
# skeleton.  Block geometry resolves through the shared kernel-tuning
# registry keyed by the CACHE dtype (attention_tuning.get_decode_config
# — FLAGS override > tuned entry > MXU-aligned heuristic), so int8 and
# fp32 caches tune independently (DEC_*_int8 vs DEC_*_float32 keys).
# ---------------------------------------------------------------------------


def decode_attention_reference(q, k_cache, v_cache, lengths, scale=None,
                               kv_scales=None):
    """Plain-XLA oracle/fallback with identical masking semantics:
    q [N, H, D] one new token per slot, k/v caches [N, S, H, D],
    lengths [N] live cached positions per slot -> [N, H, D].
    `kv_scales` [2, H] f32 (required iff the caches are int8) applies
    the same per-head dequant algebra as the kernel: K scale on the
    scores, V scale after the normalizing divide."""
    import jax.numpy as jnp
    N, S = k_cache.shape[0], k_cache.shape[1]
    H, D = q.shape[1], q.shape[-1]
    scale = float(scale if scale is not None else 1.0 / np.sqrt(D))
    s = jnp.einsum("nhd,nshd->nhs", q.astype(jnp.float32),
                   k_cache.astype(jnp.float32)) * scale
    if kv_scales is not None:
        sc = jnp.asarray(kv_scales, jnp.float32).reshape(2, H)
        s = s * sc[0][None, :, None]
    mask = jnp.arange(S)[None, None, :] >= \
        jnp.asarray(lengths).astype(jnp.int32)[:, None, None]
    s = jnp.where(mask, _NEG_INF, s)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.maximum(jnp.sum(p, axis=-1), _TINY)
    o = jnp.einsum("nhs,nshd->nhd", p,
                   v_cache.astype(jnp.float32)) / l[..., None]
    if kv_scales is not None:
        o = o * sc[1][None, :, None]
    return o.astype(q.dtype)


def decode_attention(q, k_cache, v_cache, lengths, scale=None,
                     block_kv=None, interpret=None, kv_scales=None):
    """Slot-cache decode attention: q [N, H, D] (the one new token of
    each of N slots), k_cache/v_cache [N, S, H, D] (the slot table's
    cached keys/values, time-major; fp32 or int8), lengths [N] int32
    (live positions per slot — cached positions >= length are masked
    out) -> [N, H, D] in q's dtype.

    With int8 caches, `kv_scales` [2, H] f32 (k-scales row 0, v-scales
    row 1 — the per-(layer,head) scales of the quantized slot table,
    sliced per layer by the decode step) is required: tiles dequantize
    in-register, float KV never materializes in HBM.

    Pallas instantiation of the tiled-contraction core on TPU
    (interpret emulation elsewhere) streaming kv-cache blocks under
    resident per-slot queries; block geometry via
    attention_tuning.get_decode_config keyed by the CACHE dtype
    (FLAGS.flash_block_kv override > kernel-tuning registry >
    heuristic). Falls back to the plain-XLA composition when no block
    edge divides the cache length. A slot with length 0 produces
    well-defined garbage (every position masked) — the decode step
    gates dead slots out downstream."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, H, D = q.shape
    S = k_cache.shape[1]
    scale = float(scale if scale is not None else 1.0 / np.sqrt(D))
    kv_dtype = jnp.dtype(k_cache.dtype)
    quant = kv_dtype == jnp.dtype(jnp.int8)
    if quant and kv_scales is None:
        raise ValueError(
            "decode_attention: int8 KV caches need kv_scales [2, H] "
            "(per-head fp32 dequant scales)")
    bkv = int(block_kv or attention_tuning.get_decode_config(
        S, D, kv_dtype.name) or 0)
    if not bkv or S % bkv:
        return decode_attention_reference(q, k_cache, v_cache, lengths,
                                          scale=scale,
                                          kv_scales=kv_scales)
    lengths2d = jnp.asarray(lengths).astype(jnp.int32).reshape(N, 1)

    def tile(ctx):
        q_ref, k_ref, v_ref, len_ref = ctx.ins[:4]
        acc_ref, m_ref, l_ref = ctx.scratch
        qb = q_ref[0]                              # [H, D]
        kb = _stage_dequant(k_ref[0].transpose(1, 0, 2),
                            jnp.float32)           # [H, BKV, D]
        vb = _stage_dequant(v_ref[0].transpose(1, 0, 2), jnp.float32)
        length = len_ref[0, 0]
        # elementwise-multiply + lane reduction instead of a matmul:
        # one query row per head makes this VPU work, and the step is
        # memory-bound on the K/V stream anyway (ROOFLINE.md)
        s = jnp.sum(qb[:, None, :].astype(jnp.float32) * kb,
                    axis=-1) * scale               # [H, BKV]
        if quant:
            # per-head K scale folds into the score scale, once per
            # score element — never per streamed cache element
            s = s * ctx.ins[4][0]                  # [H, 1] broadcast
        kpos = ctx.reduce_id * bkv + jax.lax.broadcasted_iota(
            jnp.int32, (H, bkv), 1)
        s = jnp.where(kpos >= length, _NEG_INF, s)
        _online_softmax_tile(
            s, lambda p: jnp.sum(p[:, :, None] * vb, axis=1),
            acc_ref, m_ref, l_ref)

    def finalize(ctx):
        o_ref = ctx.outs[0]
        acc_ref, m_ref, l_ref = ctx.scratch
        o, _ = _softmax_finalize(acc_ref, m_ref, l_ref)
        if quant:
            o = o * ctx.ins[4][1]                  # per-head V scale
        o_ref[0] = o.astype(o_ref.dtype)

    operands = [q, k_cache, v_cache, lengths2d]
    in_specs = [
        pl.BlockSpec((1, H, D), lambda b, j: (b, 0, 0)),
        pl.BlockSpec((1, bkv, H, D), lambda b, j: (b, j, 0, 0)),
        pl.BlockSpec((1, bkv, H, D), lambda b, j: (b, j, 0, 0)),
        pl.BlockSpec((1, 1), lambda b, j: (b, 0)),
    ]
    if quant:
        operands.append(jnp.asarray(kv_scales, jnp.float32).reshape(
            2, H, 1))
        in_specs.append(pl.BlockSpec((2, H, 1),
                                     lambda b, j: (0, 0, 0)))
    return tiled_contraction(
        tuple(operands),
        grid=(N, S // bkv),
        reduce_axis=1,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, D), lambda b, j: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((N, H, D), q.dtype),
        scratch=[pltpu.VMEM((H, D), jnp.float32),
                 pltpu.VMEM((H, _MIN_LANES), jnp.float32),
                 pltpu.VMEM((H, _MIN_LANES), jnp.float32)],
        scratch_fill=(0.0, _NEG_INF, 0.0),
        tile=tile, finalize=finalize,
        interpret=interpret)


def decode_attention_head_slice(q, k_cache, v_cache, lengths, head_offset,
                                n_local_heads, scale=None, block_kv=None,
                                interpret=None, kv_scales=None):
    """Tensor-parallel entry (SERVING.md "Tensor-parallel compute"):
    decode attention over one member's RESIDENT head block of the slot
    table. q/k_cache/v_cache are already the LOCAL head shards
    ([N, Hl, D] / [N, S, Hl, D], Hl = n_local_heads), but `kv_scales`
    arrives as the FULL per-layer table [2, H_total] (or [2, H_total,
    1]) — the scales are baked compile-time constants shared by every
    member, so each member dynamic-slices its own [2, Hl] window at
    `head_offset` (a traced `lax.axis_index * Hl` inside shard_map)
    and the in-register dequant stays local. Heads are independent,
    so per head the math is identical to `decode_attention` on the
    full table — bit-exact while XLA preserves the compiled reduction
    shape of the head block, ULP-level otherwise (a 1-head-wide block
    schedules the score contraction differently; pinned either way by
    tests/test_mesh_tp.py)."""
    import jax
    import jax.numpy as jnp
    Hl = int(n_local_heads)
    sc = None
    if kv_scales is not None:
        full = jnp.asarray(kv_scales, jnp.float32)
        full = full.reshape(2, -1)                  # [2, H_total]
        sc = jax.lax.dynamic_slice_in_dim(
            full, jnp.asarray(head_offset, jnp.int32), Hl, axis=1)
    return decode_attention(q, k_cache, v_cache, lengths, scale=scale,
                            block_kv=block_kv, interpret=interpret,
                            kv_scales=sc)


# ---------------------------------------------------------------------------
# fused dequant-matmul: the quantized-inference contraction (QUANTIZE.md).
# The serving flagship sits at 97% of HBM peak (bench.py MFU note) — on
# that roofline, weight BYTES are the step time, so the int8 weight tile
# is streamed from HBM as int8 and dequantized in-register against the
# resident activation tile (Tensor Processing Primitives' fused
# dequant-contraction shape, PAPERS.md): fp32/bf16 weights never touch
# HBM. Per-OUTPUT-channel scales distribute over the K reduction, so
# dequantization folds into the finalize step: acc[m, n] * scale[n] —
# one multiply per output element, not one per weight element.
# ---------------------------------------------------------------------------


def dequant_matmul_reference(x, w_q, scale, out_dtype=None):
    """Plain-XLA oracle/fallback with identical numerics contract:
    x [M, K] float, w_q [K, N] int8, scale [N] f32 per-output-channel ->
    [M, N].  The weight dequantizes through the ACTIVATION dtype (bf16
    activations see a bf16 weight — the same cast the kernel makes
    in-register) and the scale applies to the fp32 accumulator."""
    import jax
    import jax.numpy as jnp
    acc = jax.lax.dot_general(
        x, w_q.astype(x.dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    out = acc * scale.astype(jnp.float32)
    return out.astype(out_dtype or x.dtype)


def dequant_matmul(x, w_q, scale, out_dtype=None, block_m=None,
                   block_k=None, block_n=None, interpret=None):
    """Fused dequant-matmul: x [M, K] (fp32/bf16 activations), w_q
    [K, N] int8 per-output-channel-quantized weights, scale [N] f32 ->
    [M, N] in `out_dtype` (default: x.dtype).

    Pallas instantiation of the tiled-contraction core on TPU
    (interpret emulation elsewhere) streaming int8 weight tiles under a
    resident activation tile with fp32 accumulation — the in-register
    dequant is the _stage_dequant cast, the per-channel scale applies
    once at finalize; block geometry resolves through the kernel-tuning
    registry namespace ``dequant_matmul``
    (attention_tuning.get_dequant_config: tuned entry > MXU-aligned
    heuristic; explicit block args override).  Falls back to the
    plain-XLA composition when no geometry tiles the shape — channel
    counts not divisible by any candidate block edge included."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, K = x.shape
    N = w_q.shape[1]
    cfg = attention_tuning.get_dequant_config(
        M, K, N, jnp.dtype(x.dtype).name)
    bm = int(block_m or (cfg[0] if cfg else 0))
    bk = int(block_k or (cfg[1] if cfg else 0))
    bn = int(block_n or (cfg[2] if cfg else 0))
    if (not bm or not bk or not bn
            or M % bm or K % bk or N % bn):
        return dequant_matmul_reference(x, w_q, scale,
                                        out_dtype=out_dtype)
    scale2d = scale.reshape(1, N).astype(jnp.float32)

    def tile(ctx):
        x_ref, w_ref = ctx.ins[:2]
        (acc_ref,) = ctx.scratch
        xb = x_ref[...]                          # [BM, BK] activation
        wb = _stage_dequant(w_ref[...], xb.dtype)  # [BK, BN] int8->act
        acc_ref[...] = acc_ref[...] + jax.lax.dot_general(
            xb, wb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    def finalize(ctx):
        s_ref = ctx.ins[2]
        o_ref = ctx.outs[0]
        o_ref[...] = (ctx.scratch[0][...]
                      * s_ref[0].astype(jnp.float32)).astype(o_ref.dtype)

    return tiled_contraction(
        (x, w_q, scale2d),
        grid=(M // bm, N // bn, K // bk),
        reduce_axis=2,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct(
            (M, N), jnp.dtype(out_dtype or x.dtype)),
        scratch=[pltpu.VMEM((bm, bn), jnp.float32)],
        tile=tile, finalize=finalize,
        interpret=interpret)


# ---------------------------------------------------------------------------
# framework op wrapper: fluid programs reach the kernel via this op type
# ---------------------------------------------------------------------------

from .registry import register_op  # noqa: E402


@register_op("flash_attention")
def _flash_attention_op(ctx):
    q = ctx.input("Q")
    k = ctx.input("K")
    v = ctx.input("V")
    if ctx.mesh is not None:
        # Mosaic kernels cannot be auto-partitioned by the SPMD
        # partitioner; ANY mesh-built program uses the plain-XLA
        # composition (partitionable, numerically equivalent). The
        # TRACE mesh's device count is deliberately not consulted —
        # programs are traced on small virtual meshes and exported
        # against bigger abstract ones, so mesh-present is the only
        # reliable "will be partitioned" signal. Sharded long-context
        # attention is served by the dedicated ring/Ulysses paths
        # (parallel/ring_attention.py), not by auto-sharding this
        # kernel; the mesh-free (single-device) path keeps Mosaic.
        from ..parallel.ring_attention import local_attention
        return _attention_via(ctx, q, k, v, local_attention)
    return _attention_via(ctx, q, k, v, flash_attention)


def _attention_via(ctx, q, k, v, attn_fn):
    reshaped = False
    if q.ndim == 3:           # [B, S, D] with num_heads attr
        H = int(ctx.attr("num_heads", 1))
        B, S, Dm = q.shape
        if Dm % H:
            raise ValueError(
                "flash_attention: hidden size %d not divisible by "
                "num_heads %d" % (Dm, H))
        q = q.reshape(B, S, H, Dm // H)
        k = k.reshape(B, S, H, Dm // H)
        v = v.reshape(B, S, H, Dm // H)
        reshaped = True
    out = attn_fn(q, k, v, causal=bool(ctx.attr("causal", False)))
    if reshaped:
        out = out.reshape(B, S, Dm)
    return {"Out": out}


# ---------------------------------------------------------------------------
# Fused ResNet bottleneck (inference): the whole residual block — three
# BN-folded convs, both relus, and the shortcut add — in one VMEM-resident
# kernel. This is the "cross-layer fused conv pipeline" lever from
# ROOFLINE.md: the unfused block round-trips every intermediate activation
# through HBM; fused, only the block input and output touch HBM, roughly
# halving activation traffic for the inference graph.
#
# Reference analogue: inference-time conv+bn+act fusion passes
# (paddle/fluid/framework/ir/conv_bn_fuse_pass.cc and the TensorRT engine's
# layer fusion); the reference stops at per-conv epilogue fusion — this
# kernel fuses ACROSS the three convs of a block, which only makes sense on
# TPU where VMEM is large enough to hold the intermediate tiles.
#
# Layout: NHWC only (channels in the lane dimension). 1x1 convs are plain
# [rows, Cin] @ [Cin, Cout] matmuls on the MXU; the 3x3 is nine shifted
# matmuls accumulated in fp32. Stride 2 (on the 3x3, ResNet v1.5 style like
# paddle_tpu/models/resnet.py) is handled with reshape-decimation — Mosaic
# has no general strided slice, but slicing an even run and dropping every
# other row via reshape lowers cleanly.
# ---------------------------------------------------------------------------


def _bottleneck_kernel(x_ref, w0_ref, b0_ref, w1_ref, b1_ref, w2_ref,
                       b2_ref, ws_ref, bs_ref, o_ref, *, H, W, stride,
                       block_h, has_branch):
    """One (batch, row-block) program.

    x_ref    [1, H+2, W, C]   input, pre-padded by one zero row top/bottom
    w0_ref   [C, F]           1x1 reduce (BN-folded)      b0_ref [1, F]
    w1_ref   [9, F, F]        3x3 taps (BN-folded)        b1_ref [1, F]
    w2_ref   [F, C4]          1x1 expand (BN-folded)      b2_ref [1, C4]
    ws_ref   [C, C4]          projection shortcut         bs_ref [1, C4]
                              (aliased to w0/b0 when has_branch is False)
    o_ref    [1, block_h, Wo, C4]
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    s = stride
    bh = block_h
    Wo = W // s if s > 1 else W
    F = w0_ref.shape[1]
    C4 = w2_ref.shape[1]
    io = pl.program_id(1)
    o0 = io * bh                       # first output row of this program
    ext = s * bh + 2                   # conv0 rows incl. the 3x3 halo

    # -- conv0 (1x1) + bias + relu on the extended row window ------------
    # padded-row r of the window corresponds to padded image row s*o0 + r;
    # padded rows 0 and H+1 are the zero-pad ring: conv0 of a zero row is
    # relu(b0) != 0, but the 3x3's true pad operates on a1, so those rows
    # must be exact zeros — mask them.
    x_ext = x_ref[0, pl.ds(o0 * s, ext), :, :]           # [ext, W, C]
    a1 = jax.lax.dot_general(
        x_ext.reshape(ext * W, x_ext.shape[-1]), w0_ref[...],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    a1 = jnp.maximum(a1 + b0_ref[0], 0.0).reshape(ext, W, F)
    row_ids = o0 * s + jax.lax.broadcasted_iota(jnp.int32, (ext, 1, 1), 0)
    a1 = jnp.where((row_ids >= 1) & (row_ids <= H), a1, 0.0)
    a1 = a1.astype(x_ref.dtype)

    # -- conv1 (3x3, stride s) as nine shifted matmuls -------------------
    zcol = jnp.zeros((ext, 1, F), a1.dtype)
    a1p = jnp.concatenate([zcol, a1, zcol], axis=1)      # [ext, W+2, F]
    acc = jnp.zeros((bh * Wo, F), jnp.float32)
    for dy in range(3):
        if s == 1:
            rows = a1p[dy:dy + bh]                       # [bh, W+2, F]
        else:
            rows = a1p[dy:dy + s * bh].reshape(
                bh, s, W + 2, F)[:, 0]                   # decimate rows
        for dx in range(3):
            if s == 1:
                tap = rows[:, dx:dx + Wo]                # [bh, Wo, F]
            else:
                tap = rows[:, dx:dx + s * Wo].reshape(
                    bh, Wo, s, F)[:, :, 0]               # decimate cols
            acc = acc + jax.lax.dot_general(
                tap.reshape(bh * Wo, F), w1_ref[dy * 3 + dx],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    h = jnp.maximum(acc + b1_ref[0], 0.0).astype(x_ref.dtype)

    # -- conv2 (1x1 expand) + shortcut + final relu ----------------------
    y = jax.lax.dot_general(h, w2_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    y = y + b2_ref[0]

    if has_branch:
        # projection shortcut: x strided by s in both dims, then 1x1
        xs = x_ref[0, pl.ds(o0 * s + 1, s * bh), :, :]
        if s > 1:
            xs = xs.reshape(bh, s, W, xs.shape[-1])[:, 0]
            xs = xs.reshape(bh, Wo, s, xs.shape[-1])[:, :, 0]
        short = jax.lax.dot_general(
            xs.reshape(bh * Wo, xs.shape[-1]), ws_ref[...],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) + bs_ref[0]
    else:
        # identity: C == C4 and s == 1
        xs = x_ref[0, pl.ds(o0 + 1, bh), :, :]
        short = xs.reshape(bh * Wo, C4).astype(jnp.float32)

    out = jnp.maximum(y + short, 0.0)
    o_ref[0] = out.reshape(bh, Wo, C4).astype(o_ref.dtype)


def _pick_block_h(Ho):
    for cand in (16, 14, 12, 8, 7, 6, 4, 2, 1):
        if Ho % cand == 0:
            return cand
    return 1


def _bottleneck_vmem_bytes(H, W, C, F, C4, stride, block_h, dtype_bytes,
                           has_branch=True):
    """Rough VMEM budget for one program: the padded input image, the
    fp32 conv0 window, all weight operands (the identity case passes
    w0 aliased in the ws slot, so its footprint is C*F, not C*C4), and
    the fp32 accumulator/shortcut/output tiles of the epilogue — a
    geometry that passes the gate without those could clear the estimate
    yet fail Mosaic VMEM allocation on chip instead of taking the XLA
    fallback."""
    ext = stride * block_h + 2
    ws_elems = C * C4 if has_branch else C * F
    Wo = W // stride
    return ((H + 2) * W * C * dtype_bytes            # x image block
            + ext * W * F * 4                        # a1 window (fp32)
            + ext * (W + 2) * F * dtype_bytes        # a1p
            + C * F * dtype_bytes + 9 * F * F * dtype_bytes
            + F * C4 * dtype_bytes + ws_elems * dtype_bytes
            + block_h * Wo * F * 4                   # conv1 acc (fp32)
            + block_h * Wo * C4 * 4 * 2              # y + shortcut (fp32)
            + block_h * Wo * C4 * dtype_bytes)       # output block


def bottleneck_reference(x, w0, b0, w1, b1, w2, b2, ws, bs, stride):
    """Plain-XLA oracle/fallback: the same BN-folded block as three
    conv_general_dilated calls (NHWC, HWIO filters)."""
    import jax
    import jax.numpy as jnp

    def conv(v, w, s, pad):
        return jax.lax.conv_general_dilated(
            v, w.astype(v.dtype), (s, s), pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32)

    a = jnp.maximum(conv(x, w0[None, None], 1, "VALID") + b0, 0.0)
    a = a.astype(x.dtype)
    h = jnp.maximum(
        conv(a, w1, stride, [(1, 1), (1, 1)]) + b1, 0.0).astype(x.dtype)
    y = conv(h, w2[None, None], 1, "VALID") + b2
    if ws is not None:
        short = conv(x, ws[None, None], stride, "VALID") + bs
    else:
        short = x.astype(jnp.float32)
    return jnp.maximum(y + short, 0.0).astype(x.dtype)


_VMEM_CAP = 13 * 1024 * 1024


def fused_bottleneck(x, w0, b0, w1, b1, w2, b2, ws=None, bs=None,
                     stride=1, interpret=None, block_h=None):
    """Fused ResNet bottleneck, inference only. NHWC activations.

    x  [N, H, W, C]
    w0 [C, F]  b0 [F]          1x1 reduce   (BN folded into w/b)
    w1 [3, 3, F, F]  b1 [F]    3x3, stride `stride`, pad 1
    w2 [F, C4]  b2 [C4]        1x1 expand
    ws [C, C4]  bs [C4]        projection shortcut (None -> identity)

    Falls back to the plain-XLA composition when the geometry doesn't
    tile (odd W under stride 2, indivisible rows) or the block would
    blow the VMEM budget.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, H, W, C = x.shape
    F = w0.shape[1]
    C4 = w2.shape[1]
    if w1.shape != (3, 3, F, F):
        raise ValueError("w1 must be [3, 3, F, F] with F matching w0; "
                         "got %s" % (w1.shape,))
    s = int(stride)
    has_branch = ws is not None
    if not has_branch and (s != 1 or C != C4):
        raise ValueError("identity shortcut requires stride 1 and C == C4")
    Ho = H // s if s > 1 else H
    Wo = W // s if s > 1 else W
    bh = block_h or _pick_block_h(Ho)
    dtype_bytes = jnp.dtype(x.dtype).itemsize
    # the reshape-decimation trick only handles s in (1, 2) with evenly
    # divisible geometry — anything else takes the plain-XLA path
    tileable = (s in (1, 2) and Ho % bh == 0
                and (s == 1 or (H % s == 0 and W % s == 0))
                and _bottleneck_vmem_bytes(
                    H, W, C, F, C4, s, bh, dtype_bytes,
                    has_branch) <= _VMEM_CAP)
    if not tileable:
        return bottleneck_reference(x, w0, b0, w1, b1, w2, b2, ws, bs, s)

    xp = jnp.pad(x, ((0, 0), (1, 1), (0, 0), (0, 0)))
    w1f = w1.reshape(9, F, F)
    wsx = ws if has_branch else w0          # alias: unused when no branch
    bsx = bs if has_branch else b0
    kern = functools.partial(
        _bottleneck_kernel, H=H, W=W, stride=s, block_h=bh,
        has_branch=has_branch)
    full = lambda a: pl.BlockSpec(a.shape, lambda b, i: (0,) * a.ndim)
    args = (w0, b0.reshape(1, F), w1f, b1.reshape(1, F), w2,
            b2.reshape(1, C4), wsx,
            bsx.reshape(1, -1))

    def call(interp, *ops):
        return pl.pallas_call(
            kern,
            grid=(N, Ho // bh),
            in_specs=[pl.BlockSpec((1, H + 2, W, C),
                                   lambda b, i: (b, 0, 0, 0))]
            + [full(a) for a in args],
            out_specs=pl.BlockSpec((1, bh, Wo, C4),
                                   lambda b, i: (b, i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((N, Ho, Wo, C4), x.dtype),
            compiler_params=_compiler_params(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interp,
        )(*ops)

    return _interpret_dispatch(call, interpret, xp, *args)


def _oihw_to_mat(w):
    """OIHW 1x1 filter [O, I, 1, 1] -> matmul layout [I, O]."""
    return w.reshape(w.shape[0], w.shape[1]).T


@register_op("fused_bottleneck")
def _fused_bottleneck_op(ctx):
    """Program-level fused bottleneck. Filters arrive in the framework's
    OIHW layout (layout-independent parameters, models/resnet.py) and are
    re-laid for the matmul kernel at trace time — XLA constant-folds the
    transposes of persistable weights into the compiled executable."""
    x = ctx.input("X")
    w0 = _oihw_to_mat(ctx.input("W0"))
    w1 = ctx.input("W1").transpose(2, 3, 1, 0)       # OIHW -> HWIO
    w2 = _oihw_to_mat(ctx.input("W2"))
    ws = ctx.input("Ws") if ctx.has_input("Ws") else None
    out = fused_bottleneck(
        x, w0, ctx.input("B0"), w1, ctx.input("B1"), w2, ctx.input("B2"),
        ws=None if ws is None else _oihw_to_mat(ws),
        bs=ctx.input("Bs") if ctx.has_input("Bs") else None,
        stride=int(ctx.attr("stride", 1)))
    return {"Out": out}
