"""Detection op lowerings (SSD / Faster-RCNN families).

Reference analogues: paddle/fluid/operators/detection/ — prior_box_op.cc,
density_prior_box_op.cc, box_coder_op.cc, iou_similarity_op.cc,
bipartite_match_op.cc, target_assign_op.cc, mine_hard_examples_op.cc,
multiclass_nms_op.cc, anchor_generator_op.cc, generate_proposals_op.cc,
roi_pool_op.cc (operators/), roi_align_op.cc, polygon_box_transform_op.cc,
box_clip (and SURVEY.md §2.2 "Detection" row).

TPU-first redesign: the reference emits LoD (ragged) outputs for NMS-style
ops, with data-dependent row counts computed on the host. XLA requires static
shapes, so every "variable number of boxes" output here is a fixed-capacity
padded tensor plus an int32 count carried as the `@LOD_LEN` companion (the
framework-wide ragged encoding, see fluid/lod.py). Greedy algorithms
(bipartite match, NMS) become fixed-trip-count `lax.fori_loop`s over
precomputed pairwise IoU matrices — O(M^2) matrices are small (M = boxes per
class) and map onto the VPU/MXU far better than the reference's host-side
pointer chasing.
"""

import numpy as np

from .registry import register_op


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# geometry helpers
# ---------------------------------------------------------------------------

def box_area(boxes, normalized=True):
    jnp = _jnp()
    off = 0.0 if normalized else 1.0
    w = jnp.maximum(boxes[..., 2] - boxes[..., 0] + off, 0.0)
    h = jnp.maximum(boxes[..., 3] - boxes[..., 1] + off, 0.0)
    return w * h


def iou_matrix(a, b, normalized=True):
    """a [N,4], b [M,4] -> IoU [N,M] (reference iou_similarity_op.h)."""
    jnp = _jnp()
    off = 0.0 if normalized else 1.0
    xmin = jnp.maximum(a[:, None, 0], b[None, :, 0])
    ymin = jnp.maximum(a[:, None, 1], b[None, :, 1])
    xmax = jnp.minimum(a[:, None, 2], b[None, :, 2])
    ymax = jnp.minimum(a[:, None, 3], b[None, :, 3])
    iw = jnp.maximum(xmax - xmin + off, 0.0)
    ih = jnp.maximum(ymax - ymin + off, 0.0)
    inter = iw * ih
    union = box_area(a, normalized)[:, None] + \
        box_area(b, normalized)[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register_op("iou_similarity")
def _iou_similarity(ctx):
    x = ctx.input("X")      # [N,4] or [B,N,4]
    y = ctx.input("Y")      # [M,4]
    normalized = bool(ctx.attr("box_normalized", True))
    if x.ndim == 3:
        import jax
        out = jax.vmap(lambda xb: iou_matrix(xb, y, normalized))(x)
    else:
        out = iou_matrix(x, y, normalized)
    return {"Out": out}


# ---------------------------------------------------------------------------
# prior / anchor generation (prior_box_op.h, anchor_generator_op.h)
# ---------------------------------------------------------------------------

def _prior_cell_sizes(min_sizes, max_sizes, aspect_ratios, flip,
                      min_max_order=False):
    """Per-cell (w, h) half-extent list in the reference's emission order
    (prior_box_op.h: per min_size -> each aspect ratio -> the max_size
    prior; with min_max_aspect_ratios_order=True: min, max, then the non-1
    aspect ratios), with aspect_ratios expanded to include 1.0 first."""
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - e) > 1e-6 for e in ars):
            ars.append(float(ar))
            if flip and abs(ar) > 1e-6:
                inv = 1.0 / float(ar)
                if all(abs(inv - e) > 1e-6 for e in ars):
                    ars.append(inv)
    sizes = []
    for i, ms in enumerate(min_sizes):
        if min_max_order:
            sizes.append((ms, ms))
            if max_sizes:
                s = np.sqrt(ms * max_sizes[i])
                sizes.append((s, s))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                sizes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                sizes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                s = np.sqrt(ms * max_sizes[i])
                sizes.append((s, s))
    return sizes


@register_op("prior_box")
def _prior_box(ctx):
    jnp = _jnp()
    feat = ctx.input("Input")   # [N, C, H, W]
    image = ctx.input("Image")  # [N, C, imH, imW]
    H, W = feat.shape[2], feat.shape[3]
    im_h, im_w = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in ctx.attr("min_sizes")]
    max_sizes = [float(s) for s in ctx.attr("max_sizes", []) or []]
    ars = [float(a) for a in ctx.attr("aspect_ratios", [1.0]) or [1.0]]
    flip = bool(ctx.attr("flip", True))
    clip = bool(ctx.attr("clip", True))
    variances = [float(v) for v in
                 ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    step_w = float(ctx.attr("step_w", 0.0) or 0.0)
    step_h = float(ctx.attr("step_h", 0.0) or 0.0)
    offset = float(ctx.attr("offset", 0.5))
    if step_w <= 0:
        step_w = im_w / float(W)
    if step_h <= 0:
        step_h = im_h / float(H)

    sizes = _prior_cell_sizes(
        min_sizes, max_sizes, ars, flip,
        bool(ctx.attr("min_max_aspect_ratios_order", False)))
    P = len(sizes)
    half = np.asarray(sizes, np.float32) / 2.0          # [P, 2] (w/2, h/2)
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w   # [W]
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h   # [H]
    cxg = jnp.broadcast_to(cx[None, :, None], (H, W, P))
    cyg = jnp.broadcast_to(cy[:, None, None], (H, W, P))
    hw = jnp.asarray(half[:, 0])[None, None, :]
    hh = jnp.asarray(half[:, 1])[None, None, :]
    boxes = jnp.stack([(cxg - hw) / im_w, (cyg - hh) / im_h,
                       (cxg + hw) / im_w, (cyg + hh) / im_h], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, boxes.dtype),
                           (H, W, P, 4))
    return {"Boxes": boxes, "Variances": var}


@register_op("density_prior_box")
def _density_prior_box(ctx):
    """density_prior_box_op.cc: densified fixed-size priors."""
    jnp = _jnp()
    feat = ctx.input("Input")
    image = ctx.input("Image")
    H, W = feat.shape[2], feat.shape[3]
    im_h, im_w = image.shape[2], image.shape[3]
    fixed_sizes = [float(s) for s in ctx.attr("fixed_sizes", [])]
    fixed_ratios = [float(r) for r in ctx.attr("fixed_ratios", [])]
    densities = [int(d) for d in ctx.attr("densities", [])]
    variances = [float(v) for v in
                 ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    offset = float(ctx.attr("offset", 0.5))
    step_w = float(ctx.attr("step_w", 0.0) or 0.0) or im_w / float(W)
    step_h = float(ctx.attr("step_h", 0.0) or 0.0) or im_h / float(H)

    # per-cell offsets/sizes computed in numpy (static), broadcast on
    # device. The density grid spans STEP_AVERAGE (integer), shifted by
    # the integer quotient step_average // density — not the fixed_size
    # (density_prior_box_op.h:67,:82-90; r5 audit)
    step_average = int((step_w + step_h) * 0.5)
    offs = []  # (dx, dy, w/2, h/2) relative to cell center
    for k, fs in enumerate(fixed_sizes):
        d = densities[k]
        shift = step_average // d
        for ar in fixed_ratios:
            bw = fs * np.sqrt(ar)
            bh = fs / np.sqrt(ar)
            for di in range(d):
                for dj in range(d):
                    dx = -step_average / 2.0 + shift / 2.0 + dj * shift
                    dy = -step_average / 2.0 + shift / 2.0 + di * shift
                    offs.append((dx, dy, bw / 2.0, bh / 2.0))
    offs = np.asarray(offs, np.float32)   # [P, 4]
    P = len(offs)
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg = jnp.broadcast_to(
        cx[None, :, None] + jnp.asarray(offs[:, 0])[None, None, :],
        (H, W, P))
    cyg = jnp.broadcast_to(
        cy[:, None, None] + jnp.asarray(offs[:, 1])[None, None, :],
        (H, W, P))
    hw = jnp.broadcast_to(jnp.asarray(offs[:, 2])[None, None, :], (H, W, P))
    hh = jnp.broadcast_to(jnp.asarray(offs[:, 3])[None, None, :], (H, W, P))
    boxes = jnp.stack([(cxg - hw) / im_w, (cyg - hh) / im_h,
                       (cxg + hw) / im_w, (cyg + hh) / im_h], axis=-1)
    # the reference clamps density boxes to [0,1] UNCONDITIONALLY
    # (density_prior_box_op.h:92-105 ternaries), independent of `clip`
    boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, boxes.dtype),
                           (H, W, P, 4))
    return {"Boxes": boxes, "Variances": var}


@register_op("anchor_generator")
def _anchor_generator(ctx):
    """anchor_generator_op.h: anchors from sizes x aspect ratios on a stride
    grid, in input-image (pixel) coordinates."""
    jnp = _jnp()
    feat = ctx.input("Input")
    H, W = feat.shape[2], feat.shape[3]
    sizes = [float(s) for s in ctx.attr("anchor_sizes")]
    ars = [float(a) for a in ctx.attr("aspect_ratios")]
    stride = [float(s) for s in ctx.attr("stride")]
    variances = [float(v) for v in
                 ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    offset = float(ctx.attr("offset", 0.5))

    half = []
    for ar in ars:
        for s in sizes:
            area = stride[0] * stride[1]
            area_ratios = area / ar
            # C round() is half-away-from-zero (same fix as roi_pool);
            # np.round's half-to-even gives 22 for 22.5 where the
            # reference gives 23
            base_w = np.floor(np.sqrt(area_ratios) + 0.5)
            base_h = np.floor(base_w * ar + 0.5)
            scale_w = s / stride[0]
            scale_h = s / stride[1]
            # pixel-inclusive extents: +/- (w-1)/2, not w/2
            # (anchor_generator_op.h:74-81)
            half.append(((scale_w * base_w - 1.0) / 2.0,
                         (scale_h * base_h - 1.0) / 2.0))
    half = np.asarray(half, np.float32)
    A = len(half)
    # centers at idx*stride + offset*(stride - 1) — the reference's
    # pixel-grid convention, NOT (idx + offset)*stride
    cx = jnp.arange(W, dtype=jnp.float32) * stride[0] + \
        offset * (stride[0] - 1.0)
    cy = jnp.arange(H, dtype=jnp.float32) * stride[1] + \
        offset * (stride[1] - 1.0)
    cxg = jnp.broadcast_to(cx[None, :, None], (H, W, A))
    cyg = jnp.broadcast_to(cy[:, None, None], (H, W, A))
    hw = jnp.asarray(half[:, 0])[None, None, :]
    hh = jnp.asarray(half[:, 1])[None, None, :]
    anchors = jnp.stack([cxg - hw, cyg - hh, cxg + hw, cyg + hh], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, anchors.dtype),
                           (H, W, A, 4))
    return {"Anchors": anchors, "Variances": var}


# ---------------------------------------------------------------------------
# box coder (box_coder_op.h)
# ---------------------------------------------------------------------------

def _encode_center_size(target, prior, pvar, wh_offset=0.0):
    """target [N,4] gt, prior [M,4] -> [N,M,4] deltas. wh_offset=1 for
    pixel-coordinate boxes (reference box_coder_op.h +1 widths)."""
    jnp = _jnp()
    pw = prior[:, 2] - prior[:, 0] + wh_offset
    ph = prior[:, 3] - prior[:, 1] + wh_offset
    # centers are (min+max)/2 in BOTH normalized modes -- the +1 width
    # does not shift the center (box_coder_op.h:55-57)
    pcx = (prior[:, 0] + prior[:, 2]) * 0.5
    pcy = (prior[:, 1] + prior[:, 3]) * 0.5
    tw = target[:, None, 2] - target[:, None, 0] + wh_offset
    th = target[:, None, 3] - target[:, None, 1] + wh_offset
    tcx = (target[:, None, 0] + target[:, None, 2]) * 0.5
    tcy = (target[:, None, 1] + target[:, None, 3]) * 0.5
    ox = (tcx - pcx[None, :]) / pw[None, :]
    oy = (tcy - pcy[None, :]) / ph[None, :]
    ow = jnp.log(jnp.abs(tw / pw[None, :]))
    oh = jnp.log(jnp.abs(th / ph[None, :]))
    out = jnp.stack([ox, oy, ow, oh], axis=-1)
    if pvar is not None:
        out = out / pvar[None, :, :]
    return out


def _decode_center_size(target, prior, pvar, wh_offset=0.0):
    """target [N,M,4] (or [M,4]) deltas, prior [M,4] -> corner boxes of the
    same rank. wh_offset=1 for pixel coordinates: +1 widths and -1 on the
    decoded xmax/ymax (reference box_coder_op.h)."""
    jnp = _jnp()
    squeeze = target.ndim == 2
    if squeeze:
        target = target[None]
    pw = prior[:, 2] - prior[:, 0] + wh_offset
    ph = prior[:, 3] - prior[:, 1] + wh_offset
    # (min+max)/2, NOT min + (w+1)/2: the earlier form shifted decoded
    # pixel-coordinate boxes by +0.5 (r5 audit vs box_coder_op.h:118)
    pcx = (prior[:, 0] + prior[:, 2]) * 0.5
    pcy = (prior[:, 1] + prior[:, 3]) * 0.5
    if pvar is not None:
        target = target * pvar[None, :, :]
    cx = target[..., 0] * pw[None, :] + pcx[None, :]
    cy = target[..., 1] * ph[None, :] + pcy[None, :]
    w = jnp.exp(target[..., 2]) * pw[None, :]
    h = jnp.exp(target[..., 3]) * ph[None, :]
    out = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                     cx + w * 0.5 - wh_offset,
                     cy + h * 0.5 - wh_offset], axis=-1)
    return out[0] if squeeze else out


@register_op("box_coder")
def _box_coder(ctx):
    jnp = _jnp()
    prior = ctx.input("PriorBox")       # [M, 4]
    pvar = ctx.input("PriorBoxVar")     # [M, 4] or None
    target = ctx.input("TargetBox")
    code_type = ctx.attr("code_type", "encode_center_size")
    if pvar is None:
        v = ctx.attr("variance", []) or []
        if v:
            pvar = jnp.broadcast_to(jnp.asarray(v, prior.dtype),
                                    (prior.shape[0], 4))
    norm = bool(ctx.attr("box_normalized", True))
    wh_offset = 0.0 if norm else 1.0
    if code_type.lower() == "encode_center_size":
        if target.ndim == 3:       # [B, G, 4] padded batch of gt boxes
            import jax
            out = jax.vmap(
                lambda t: _encode_center_size(t, prior, pvar,
                                              wh_offset))(target)
        else:
            out = _encode_center_size(target, prior, pvar, wh_offset)
    else:
        out = _decode_center_size(target, prior, pvar, wh_offset)
    return {"OutputBox": out}


# ---------------------------------------------------------------------------
# bipartite matching (bipartite_match_op.cc)
# ---------------------------------------------------------------------------

def _bipartite_match_one(dist):
    """dist [N, M] -> (match_idx [M] int32, match_dist [M]).
    Greedy global-max matching: repeatedly take the largest remaining entry,
    match its row/col, until nothing positive is left."""
    import jax
    jnp = _jnp()
    N, M = dist.shape
    steps = min(N, M)

    def body(_, state):
        d, midx, mdist = state
        flat = jnp.argmax(d)
        r, c = flat // M, flat % M
        val = d[r, c]
        do = val > 0
        midx = jnp.where(do, midx.at[c].set(r.astype(jnp.int32)), midx)
        mdist = jnp.where(do, mdist.at[c].set(val), mdist)
        d = jnp.where(do, d.at[r, :].set(-1.0).at[:, c].set(-1.0), d)
        return d, midx, mdist

    midx = jnp.full((M,), -1, jnp.int32)
    mdist = jnp.zeros((M,), dist.dtype)
    _, midx, mdist = jax.lax.fori_loop(
        0, steps, body, (dist, midx, mdist))
    return midx, mdist


@register_op("bipartite_match")
def _bipartite_match(ctx):
    """DistMat [B, N, M] (padded batch; reference uses LoD rows). Per-image
    greedy bipartite match + optional per_prediction augmentation."""
    import jax
    jnp = _jnp()
    dist = ctx.input("DistMat")
    lens = ctx.lod_len("DistMat")       # rows per image, or None
    if dist.ndim == 2:
        dist = dist[None]
    B, N, M = dist.shape
    if lens is not None:
        row_ok = jnp.arange(N)[None, :] < lens[:, None]
        dist = jnp.where(row_ok[:, :, None], dist, -1.0)
    midx, mdist = jax.vmap(_bipartite_match_one)(dist)
    if ctx.attr("match_type", "bipartite") == "per_prediction":
        thr = float(ctx.attr("dist_threshold", 0.5))
        best_row = jnp.argmax(dist, axis=1).astype(jnp.int32)   # [B, M]
        best_val = jnp.max(dist, axis=1)
        # >= like ArgMaxMatch (bipartite_match_op.cc:160: dist >=
        # overlap_threshold), not strict >
        fill = (midx < 0) & (best_val >= thr)
        midx = jnp.where(fill, best_row, midx)
        mdist = jnp.where(fill, best_val, mdist)
    return {"ColToRowMatchIndices": midx, "ColToRowMatchDist": mdist}


# ---------------------------------------------------------------------------
# target assign (target_assign_op.h)
# ---------------------------------------------------------------------------

@register_op("target_assign")
def _target_assign(ctx):
    """X [B, N, K] per-image gt rows (padded, lens companion; reference: LoD
    [M, P, K] with the rows-per-image grouping in the LoD), MatchIndices
    [B, P] -> Out [B, P, K], OutWeight [B, P, 1]. X may also be
    [B, N, P, K] (per-prior targets, e.g. encoded gt boxes): out[b,p] =
    x[b, match[b,p], p]. NegIndices [B, Q] padded (lens companion) marks
    negatives whose weight is forced to 1."""
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    midx = ctx.input("MatchIndices")
    B, P = midx.shape
    K = x.shape[-1]
    mismatch = jnp.asarray(ctx.attr("mismatch_value", 0), x.dtype)
    safe = jnp.maximum(midx, 0).astype(jnp.int32)
    if x.ndim == 4:
        out = jax.vmap(lambda xb, mb: xb[mb, jnp.arange(P)])(x, safe)
    else:
        out = jnp.take_along_axis(
            x, safe[:, :, None].repeat(K, axis=2), axis=1)
    matched = (midx >= 0)[:, :, None]
    out = jnp.where(matched, out, mismatch)
    w = matched.astype(x.dtype)
    neg = ctx.input("NegIndices")
    if neg is not None:
        nlens = ctx.lod_len("NegIndices")
        Q = neg.shape[1]
        valid = jnp.ones((B, Q), bool) if nlens is None else \
            jnp.arange(Q)[None, :] < nlens[:, None]
        onehot = (jnp.arange(P)[None, None, :] ==
                  neg[:, :, None]) & valid[:, :, None]
        negmask = jnp.any(onehot, axis=1)[:, :, None]
        w = jnp.where(negmask, jnp.asarray(1.0, x.dtype), w)
    return {"Out": out, "OutWeight": w}


# ---------------------------------------------------------------------------
# hard-negative mining (mine_hard_examples_op.cc)
# ---------------------------------------------------------------------------

@register_op("mine_hard_examples")
def _mine_hard_examples(ctx):
    """Hard-example mining. ClsLoss [B, P], MatchIndices [B, P],
    MatchDist [B, P] -> NegIndices [B, P] padded + lens, UpdatedMatchIndices.

    max_negative (default): negatives = unmatched priors with dist <
    neg_dist_threshold, ranked by loss desc, capped at
    neg_pos_ratio * num_pos (or sample_size). Match indices unchanged.

    hard_example: ALL priors ranked by loss desc, the top sample_size
    selected; selected unmatched priors become the negatives, and positives
    that were NOT selected are dropped from UpdatedMatchIndices
    (mine_hard_examples_op.cc kHardExample)."""
    jnp = _jnp()
    cls_loss = ctx.input("ClsLoss")
    loc_loss = ctx.input("LocLoss")
    midx = ctx.input("MatchIndices")
    mdist = ctx.input("MatchDist")
    ratio = float(ctx.attr("neg_pos_ratio", 1.0))
    dist_thr = float(ctx.attr("neg_dist_threshold", 0.5))
    sample_size = int(ctx.attr("sample_size", 0))
    mining_type = ctx.attr("mining_type", "max_negative")
    B, P = midx.shape
    import jax

    def _ascending_pack(sel, cap):
        # reference emits neg indices from a std::set<int> — ascending
        # prior-index order, NOT loss order (mine_hard_examples_op.cc
        # sel_indices copy)
        key = jnp.where(sel, jnp.arange(P)[None, :], P)
        asc = jnp.argsort(key, axis=1).astype(jnp.int32)
        keep = jnp.arange(P)[None, :] < cap[:, None]
        return jnp.where(keep, asc, 0)

    def _top_sel(loss_masked, cap):
        # boolean mask of the top-`cap` eligible priors by loss desc
        order = jnp.argsort(-loss_masked, axis=1).astype(jnp.int32)
        keep = jnp.arange(P)[None, :] < cap[:, None]
        return jax.vmap(
            lambda o, r: jnp.zeros((P,), bool).at[o].set(r))(order, keep)

    if mining_type == "hard_example":
        # eligibility is ALL priors; ranking loss is cls (+loc when
        # given); selected unmatched priors become negatives with NO
        # dist filter (IsEligibleMining kHardExample returns true)
        loss = cls_loss if loc_loss is None else cls_loss + loc_loss
        S = min(sample_size if sample_size > 0 else P, P)
        selected = _top_sel(loss, jnp.full((B,), S, jnp.int32))
        neg_sel = selected & (midx < 0)
        cap = jnp.sum(neg_sel.astype(jnp.int32), axis=1)
        updated = jnp.where(selected | (midx < 0), midx, -1)
        return {"NegIndices": _ascending_pack(neg_sel, cap),
                "NegIndices@LOD_LEN": cap,
                "UpdatedMatchIndices": updated}

    # max_negative: eligibility = unmatched & dist < threshold; ranking
    # loss is cls ONLY (the reference adds loc_loss only in
    # hard_example mode, mine_hard_examples_op.cc:99-101)
    loss = cls_loss
    is_neg_cand = (midx < 0) & (mdist < dist_thr)
    num_pos = jnp.sum((midx >= 0).astype(jnp.int32), axis=1)
    cap = (num_pos.astype(jnp.float32) * ratio).astype(jnp.int32)
    if sample_size > 0:
        cap = jnp.full_like(cap, sample_size)
    cap = jnp.minimum(cap, jnp.sum(is_neg_cand.astype(jnp.int32), axis=1))
    masked = jnp.where(is_neg_cand, loss, -jnp.inf)
    neg_sel = _top_sel(masked, cap) & is_neg_cand
    return {"NegIndices": _ascending_pack(neg_sel, cap),
            "NegIndices@LOD_LEN": cap,
            "UpdatedMatchIndices": midx}


# ---------------------------------------------------------------------------
# NMS (multiclass_nms_op.cc)
# ---------------------------------------------------------------------------

def nms_mask(boxes, scores, valid, iou_threshold, top_k, normalized=True,
             eta=1.0):
    """Greedy NMS. boxes [M,4], scores [M], valid [M] bool -> keep [M] bool.
    Classic O(M^2): precompute the IoU matrix, walk boxes in score order with
    a fori_loop, suppressing later overlaps. eta < 1 decays the threshold
    after each kept box once it exceeds 0.5 (adaptive NMS, multiclass_nms_op
    nms_eta)."""
    import jax
    jnp = _jnp()
    M = boxes.shape[0]
    order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
    bs = boxes[order]
    vs = valid[order]
    if top_k is not None and top_k > 0:
        vs = vs & (jnp.arange(M) < top_k)
    iou = iou_matrix(bs, bs, normalized)
    eta = float(eta)

    def body(i, state):
        # candidate-time evaluation (NMSFast:173-205): box i is kept iff
        # its overlap with every ALREADY-KEPT box is <= the CURRENT
        # adaptive threshold — under eta < 1 the threshold has decayed
        # once per prior keep, so deciding suppression at keep time with
        # the older threshold (the r5 audit's previous formulation)
        # under-suppresses
        keep, thr = state
        max_ov = jnp.max(jnp.where(keep & (jnp.arange(M) < i),
                                   iou[i], 0.0))
        ok = vs[i] & (max_ov <= thr)
        keep = keep.at[i].set(ok)
        if eta < 1.0:
            thr = jnp.where(ok & (thr > 0.5), thr * eta, thr)
        return keep, thr

    keep0 = jnp.zeros((M,), bool)
    thr0 = jnp.asarray(iou_threshold, jnp.float32)
    keep_sorted, _ = jax.lax.fori_loop(0, M, body, (keep0, thr0))
    keep = jnp.zeros((M,), bool).at[order].set(keep_sorted)
    return keep


def _multiclass_nms_one(scores, bboxes, background_label, score_threshold,
                        nms_top_k, nms_threshold, keep_top_k, normalized,
                        eta=1.0):
    """scores [C, M], bboxes [M, 4] -> out [keep_top_k, 6], count scalar."""
    import jax
    jnp = _jnp()
    C, M = scores.shape

    def per_class(c_scores):
        valid = c_scores > score_threshold
        return nms_mask(bboxes, c_scores, valid, nms_threshold,
                        nms_top_k, normalized, eta)

    keep = jax.vmap(per_class)(scores)                        # [C, M]
    if background_label >= 0:
        keep = keep.at[background_label].set(False)
    flat_keep = keep.reshape(-1)
    flat_scores = jnp.where(flat_keep, scores.reshape(-1), -jnp.inf)
    K = int(keep_top_k) if keep_top_k > 0 else C * M
    K = min(K, C * M)
    top_scores, top_idx = jax.lax.top_k(flat_scores, K)
    sel_class = (top_idx // M).astype(jnp.float32)
    sel_box = bboxes[top_idx % M]
    valid_out = top_scores > -jnp.inf
    out = jnp.concatenate([
        jnp.where(valid_out, sel_class, -1.0)[:, None],
        jnp.where(valid_out, top_scores, 0.0)[:, None],
        jnp.where(valid_out[:, None], sel_box, 0.0)], axis=1)
    count = jnp.sum(valid_out.astype(jnp.int32))
    return out, count


@register_op("multiclass_nms")
def _multiclass_nms(ctx):
    """Scores [B, C, M], BBoxes [B, M, 4] -> Out [B, keep_top_k, 6] padded
    (rows are [label, score, xmin, ymin, xmax, ymax]) + per-image counts as
    the LoD companion (reference emits an LoD tensor)."""
    import jax
    scores = ctx.input("Scores")
    bboxes = ctx.input("BBoxes")
    bg = int(ctx.attr("background_label", 0))
    score_thr = float(ctx.attr("score_threshold", 0.0))
    nms_top_k = int(ctx.attr("nms_top_k", -1))
    nms_thr = float(ctx.attr("nms_threshold", 0.3))
    keep_top_k = int(ctx.attr("keep_top_k", -1))
    normalized = bool(ctx.attr("normalized", True))
    eta = float(ctx.attr("nms_eta", 1.0))
    out, count = jax.vmap(
        lambda s, b: _multiclass_nms_one(s, b, bg, score_thr, nms_top_k,
                                         nms_thr, keep_top_k, normalized,
                                         eta)
    )(scores, bboxes)
    return {"Out": out, "Out@LOD_LEN": count}


# ---------------------------------------------------------------------------
# proposals (generate_proposals_op.cc)
# ---------------------------------------------------------------------------

@register_op("generate_proposals")
def _generate_proposals(ctx):
    """Scores [B, A, H, W], BboxDeltas [B, 4A, H, W], ImInfo [B, 3],
    Anchors [H, W, A, 4], Variances [H, W, A, 4] ->
    RpnRois [B, post_nms_topN, 4] + counts, RpnRoiProbs [B, post_nms_topN, 1].
    """
    import jax
    jnp = _jnp()
    scores = ctx.input("Scores")
    deltas = ctx.input("BboxDeltas")
    im_info = ctx.input("ImInfo")
    anchors = ctx.input("Anchors").reshape(-1, 4)
    variances = ctx.input("Variances").reshape(-1, 4)
    pre_n = int(ctx.attr("pre_nms_topN", 6000))
    post_n = int(ctx.attr("post_nms_topN", 1000))
    nms_thr = float(ctx.attr("nms_thresh", 0.7))
    min_size = float(ctx.attr("min_size", 0.1))
    B, A, H, W = scores.shape
    M = A * H * W
    pre_n = min(pre_n, M)
    post_n = min(post_n, pre_n)

    def one(sc, dl, info):
        # to [M] / [M, 4]: scores laid out [A,H,W]; deltas [4A,H,W] with
        # 4 consecutive channels per anchor (reference transposes to HWA);
        # anchors/variances arrive [H,W,A,4] and were flattened above in
        # the same HWA order
        s = sc.transpose(1, 2, 0).reshape(-1)                 # [H,W,A]->[M]
        d = dl.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        anc = anchors
        var = variances
        top_s, top_i = jax.lax.top_k(s, pre_n)
        d = d[top_i]
        anc = anc[top_i]
        var = var[top_i]
        # decode (pixel-coordinate center-size decode, +1 widths)
        pw = anc[:, 2] - anc[:, 0] + 1.0
        ph = anc[:, 3] - anc[:, 1] + 1.0
        pcx = anc[:, 0] + pw * 0.5
        pcy = anc[:, 1] + ph * 0.5
        dx, dy, dw, dh = (d * var).T
        cx = dx * pw + pcx
        cy = dy * ph + pcy
        w = jnp.exp(jnp.minimum(dw, 10.0)) * pw
        h = jnp.exp(jnp.minimum(dh, 10.0)) * ph
        boxes = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                           cx + w * 0.5 - 1.0, cy + h * 0.5 - 1.0], axis=1)
        # clip to image
        imh, imw = info[0], info[1]
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0.0, imw - 1.0),
            jnp.clip(boxes[:, 1], 0.0, imh - 1.0),
            jnp.clip(boxes[:, 2], 0.0, imw - 1.0),
            jnp.clip(boxes[:, 3], 0.0, imh - 1.0)], axis=1)
        # min-size filter in ORIGIN-image scale: width/im_scale + 1 >=
        # max(min_size, 1) (generate_proposals_op.cc FilterBoxes:168-183;
        # scaling min_size up instead diverges whenever im_scale != 1)
        ms = jnp.maximum(min_size, 1.0)
        keep_sz = (((boxes[:, 2] - boxes[:, 0]) / info[2] + 1.0) >= ms) & \
                  (((boxes[:, 3] - boxes[:, 1]) / info[2] + 1.0) >= ms)
        keep = nms_mask(boxes, top_s, keep_sz, nms_thr, -1, normalized=False)
        sc_kept = jnp.where(keep, top_s, -jnp.inf)
        out_s, out_i = jax.lax.top_k(sc_kept, post_n)
        rois = boxes[out_i]
        ok = out_s > -jnp.inf
        rois = jnp.where(ok[:, None], rois, 0.0)
        probs = jnp.where(ok, out_s, 0.0)[:, None]
        return rois, probs, jnp.sum(ok.astype(jnp.int32))

    rois, probs, counts = jax.vmap(one)(scores, deltas, im_info)
    return {"RpnRois": rois, "RpnRois@LOD_LEN": counts,
            "RpnRoiProbs": probs, "RpnRoiProbs@LOD_LEN": counts}


# ---------------------------------------------------------------------------
# RoI pooling (roi_pool_op.cc, roi_align_op.cc)
# ---------------------------------------------------------------------------

@register_op("roi_pool")
def _roi_pool(ctx):
    """X [B, C, H, W], ROIs [B, R, 4] (padded per-image, lens companion;
    reference: LoD [K, 4]) -> Out [B, R, C, ph, pw]. Max pool over integer
    bin grids, matching roi_pool_op.h quantization."""
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    rois = ctx.input("ROIs")
    lens = ctx.lod_len("ROIs")
    ph = int(ctx.attr("pooled_height", 1))
    pw = int(ctx.attr("pooled_width", 1))
    scale = float(ctx.attr("spatial_scale", 1.0))
    B, C, H, W = x.shape
    squeeze = rois.ndim == 2
    if squeeze:
        rois = rois[None]
    R = rois.shape[1]

    hi = jnp.arange(H)
    wi = jnp.arange(W)

    def one_roi(feat, roi):
        # C round() is half-away-from-zero, not numpy's half-to-even —
        # spatial_scale=0.5 with odd pixel coords lands on .5 exactly
        # (roi_pool_op.h:78-81); coords are non-negative so floor(x+0.5)
        x1 = jnp.floor(roi[0] * scale + 0.5)
        y1 = jnp.floor(roi[1] * scale + 0.5)
        x2 = jnp.floor(roi[2] * scale + 0.5)
        y2 = jnp.floor(roi[3] * scale + 0.5)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        ib = jnp.arange(ph, dtype=feat.dtype)
        jb = jnp.arange(pw, dtype=feat.dtype)
        hstart = jnp.clip(jnp.floor(ib * bin_h) + y1, 0, H)     # [ph]
        hend = jnp.clip(jnp.ceil((ib + 1) * bin_h) + y1, 0, H)
        wstart = jnp.clip(jnp.floor(jb * bin_w) + x1, 0, W)
        wend = jnp.clip(jnp.ceil((jb + 1) * bin_w) + x1, 0, W)
        hmask = (hi[None, :] >= hstart[:, None]) & \
                (hi[None, :] < hend[:, None])                   # [ph, H]
        wmask = (wi[None, :] >= wstart[:, None]) & \
                (wi[None, :] < wend[:, None])                   # [pw, W]
        m = hmask[:, None, :, None] & wmask[None, :, None, :]   # [ph,pw,H,W]
        big = jnp.where(m[None], feat[:, None, None, :, :],
                        jnp.asarray(-np.inf, feat.dtype))
        out = jnp.max(big, axis=(3, 4))                          # [C, ph, pw]
        empty = ~jnp.any(m, axis=(2, 3))                         # [ph, pw]
        return jnp.where(empty[None], 0.0, out)

    out = jax.vmap(lambda feat, rs: jax.vmap(
        lambda r: one_roi(feat, r))(rs))(x, rois)
    if lens is not None:
        valid = (jnp.arange(R)[None, :] < lens[:, None])
        out = jnp.where(valid[:, :, None, None, None], out, 0.0)
    if squeeze:
        out = out[0]
    return {"Out": out, "Argmax": None}


# default for FLAGS.roi_align_adaptive_cap (kept as a module constant so
# existing imports keep meaning "the built-in default")
_ROI_ALIGN_ADAPTIVE_CAP = 8

_roi_cap_warned = [False]


def _warn_roi_cap_clip(rois, ph, pw, scale, cap):
    """One-time warning when a CONCRETE roi's adaptive grid actually
    exceeds the cap (traced rois are data-dependent; nothing to check)."""
    import jax
    if _roi_cap_warned[0] or isinstance(rois, jax.core.Tracer):
        return
    import warnings
    r = np.asarray(rois, np.float64).reshape(-1, rois.shape[-1])
    rw = np.maximum(r[:, 2] * scale - r[:, 0] * scale, 1.0)
    rh = np.maximum(r[:, 3] * scale - r[:, 1] * scale, 1.0)
    need = max(float(np.max(np.ceil(rh / ph), initial=0.0)),
               float(np.max(np.ceil(rw / pw), initial=0.0)))
    if need > cap:
        _roi_cap_warned[0] = True
        warnings.warn(
            "roi_align: a roi's adaptive sampling grid needs %d points "
            "per bin but FLAGS.roi_align_adaptive_cap=%d clips it to a "
            "%dx%d uniform subsample; raise the flag for exact "
            "reference parity on large rois (warning fires once)"
            % (int(need), cap, cap, cap))


@register_op("roi_align")
def _roi_align(ctx):
    """RoI Align (roi_align_op.h): average of bilinear samples per bin.
    sampling_ratio > 0 is a fixed grid; <= 0 is the reference's
    per-roi ADAPTIVE grid of ceil(roi_h/ph) x ceil(roi_w/pw) points —
    emulated exactly under static shapes by evaluating a capped
    [S_max, S_max] grid and masking samples beyond the roi's own count.
    The cap is FLAGS.roi_align_adaptive_cap (default 8: a roi would need
    to span >8 bins' worth of feature rows per pooled cell to clip, and
    the cap then degrades gracefully to a cap x cap subsample; a one-time
    warning fires when eager inputs actually clip). Pinned by
    tests/test_roi_align_oracle.py."""
    import jax
    jnp = _jnp()
    x = ctx.input("X")
    rois = ctx.input("ROIs")
    lens = ctx.lod_len("ROIs")
    ph = int(ctx.attr("pooled_height", 1))
    pw = int(ctx.attr("pooled_width", 1))
    scale = float(ctx.attr("spatial_scale", 1.0))
    ratio = int(ctx.attr("sampling_ratio", -1))
    B, C, H, W = x.shape
    squeeze = rois.ndim == 2
    if squeeze:
        rois = rois[None]
    R = rois.shape[1]
    if ratio > 0:
        S = ratio
    else:
        from ..flags import FLAGS
        S = int(FLAGS.roi_align_adaptive_cap)
        _warn_roi_cap_clip(rois, ph, pw, scale, S)

    def bilinear(feat, ys, xs):
        """feat [C, H, W]; ys/xs [...]: bilinear sample -> [C, ...]"""
        y0 = jnp.floor(ys)
        x0 = jnp.floor(xs)
        y1 = y0 + 1
        x1 = x0 + 1
        wy1 = ys - y0
        wx1 = xs - x0
        wy0 = 1.0 - wy1
        wx0 = 1.0 - wx1

        def at(yy, xx):
            yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
            return feat[:, yi, xi]

        oob = (ys < -1.0) | (ys > H) | (xs < -1.0) | (xs > W)
        val = (at(y0, x0) * (wy0 * wx0) + at(y0, x1) * (wy0 * wx1) +
               at(y1, x0) * (wy1 * wx0) + at(y1, x1) * (wy1 * wx1))
        return jnp.where(oob[None], 0.0, val)

    def one_roi(feat, roi):
        x1 = roi[0] * scale
        y1 = roi[1] * scale
        rw = jnp.maximum(roi[2] * scale - x1, 1.0)
        rh = jnp.maximum(roi[3] * scale - y1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        if ratio > 0:
            gh = gw = jnp.asarray(float(ratio), feat.dtype)
        else:
            gh = jnp.clip(jnp.ceil(rh / ph), 1, S)
            gw = jnp.clip(jnp.ceil(rw / pw), 1, S)
        ib = jnp.arange(ph, dtype=feat.dtype)[:, None, None, None]
        jb = jnp.arange(pw, dtype=feat.dtype)[None, :, None, None]
        si = jnp.arange(S, dtype=feat.dtype)[None, None, :, None]
        sj = jnp.arange(S, dtype=feat.dtype)[None, None, None, :]
        ys = y1 + ib * bin_h + (si + 0.5) * bin_h / gh   # [ph,pw,S,S]
        xs = x1 + jb * bin_w + (sj + 0.5) * bin_w / gw
        live = (si < gh) & (sj < gw)                      # [1,1,S,S]
        vals = bilinear(feat, ys, xs)                     # [C,ph,pw,S,S]
        vals = vals * live.astype(feat.dtype)[None]
        return jnp.sum(vals, axis=(3, 4)) / (gh * gw)

    out = jax.vmap(lambda feat, rs: jax.vmap(
        lambda r: one_roi(feat, r))(rs))(x, rois)
    if lens is not None:
        valid = (jnp.arange(R)[None, :] < lens[:, None])
        out = jnp.where(valid[:, :, None, None, None], out, 0.0)
    if squeeze:
        out = out[0]
    return {"Out": out}


# ---------------------------------------------------------------------------
# RPN target assign (rpn_target_assign_op.cc)
# ---------------------------------------------------------------------------

@register_op("rpn_target_assign")
def _rpn_target_assign(ctx):
    """Loc [N,A,4], Scores [N,A,1], Anchor [A,4], AnchorVar [A,4],
    GtBox [N,G,4] (padded, lens companion) ->
    (PredictedLocation [N,S,4], PredictedScores [N,S,1],
     TargetLabel [N,S,1], TargetBBox [N,S,4]) + counts; S =
    rpn_batch_size_per_im.

    Sampling is the reference's fg/bg-balanced scheme made deterministic for
    jit: positives (IoU > pos_overlap, plus the best anchor per gt) ranked by
    IoU desc capped at fg_fraction*S; negatives (IoU < neg_overlap) ranked by
    IoU asc fill the remainder. The reference samples randomly; ranking keeps
    identical fg/bg counts with reproducible selection (documented
    deviation)."""
    import jax
    jnp = _jnp()
    loc = ctx.input("Loc")
    scores = ctx.input("Scores")
    anchor = ctx.input("Anchor")
    avar = ctx.input("AnchorVar")
    gt = ctx.input("GtBox")
    lens = ctx.lod_len("GtBox")
    S = int(ctx.attr("rpn_batch_size_per_im", 256))
    fg_frac = float(ctx.attr("fg_fraction", 0.25))
    pos_thr = float(ctx.attr("rpn_positive_overlap", 0.7))
    neg_thr = float(ctx.attr("rpn_negative_overlap", 0.3))
    N, A = loc.shape[0], loc.shape[1]
    G = gt.shape[1]
    S = min(S, A)
    fg_cap = int(S * fg_frac)

    def one(loc_i, sc_i, gt_i, n_gt):
        iou = iou_matrix(gt_i, anchor)                     # [G, A]
        gt_ok = jnp.arange(G) < n_gt
        iou = jnp.where(gt_ok[:, None], iou, 0.0)
        best = jnp.max(iou, axis=0)                        # [A]
        best_gt = jnp.argmax(iou, axis=0).astype(jnp.int32)
        # best anchor per gt is positive too; padded gt rows must not
        # scatter (their argmax is a bogus 0) — route them out of range
        best_anchor = jnp.argmax(iou, axis=1)              # [G]
        safe_anchor = jnp.where(gt_ok, best_anchor, A)
        per_gt_pos = jnp.zeros((A,), bool).at[safe_anchor].set(
            True, mode="drop")
        is_pos = (best > pos_thr) | per_gt_pos
        is_neg = (best < neg_thr) & ~is_pos
        # deterministic fg: top IoU positives
        fg_rank = jnp.argsort(-jnp.where(is_pos, best, -jnp.inf))
        n_fg = jnp.minimum(jnp.sum(is_pos.astype(jnp.int32)), fg_cap)
        # deterministic bg: lowest-IoU negatives
        bg_rank = jnp.argsort(jnp.where(is_neg, best, jnp.inf))
        n_bg = jnp.minimum(jnp.sum(is_neg.astype(jnp.int32)), S - n_fg)
        pick_fg = jnp.arange(S) < n_fg
        idx = jnp.where(pick_fg, fg_rank[jnp.arange(S) % A],
                        bg_rank[jnp.maximum(jnp.arange(S) - n_fg, 0) % A])
        idx = idx.astype(jnp.int32)
        count = n_fg + n_bg
        valid = jnp.arange(S) < count
        lab = jnp.where(pick_fg, 1, 0).astype(jnp.int32)[:, None]
        enc = _encode_center_size(gt_i, anchor, avar)      # [G, A, 4]
        tb = enc[best_gt[idx], idx]                        # [S, 4]
        tb = jnp.where((pick_fg & valid)[:, None], tb, 0.0)
        pl = jnp.where(valid[:, None], loc_i[idx], 0.0)
        ps = jnp.where(valid[:, None], sc_i[idx], 0.0)
        return pl, ps, jnp.where(valid[:, None], lab, 0), tb, count

    if lens is None:
        lens = jnp.full((N,), G, jnp.int32)
    if scores.ndim == 2:
        scores = scores[:, :, None]
    pl, ps, lab, tb, counts = jax.vmap(one)(loc, scores, gt, lens)
    return {"PredictedLocation": pl, "PredictedLocation@LOD_LEN": counts,
            "PredictedScores": ps, "PredictedScores@LOD_LEN": counts,
            "TargetLabel": lab, "TargetLabel@LOD_LEN": counts,
            "TargetBBox": tb, "TargetBBox@LOD_LEN": counts}


# ---------------------------------------------------------------------------
# misc (polygon_box_transform_op.cc, box_clip)
# ---------------------------------------------------------------------------

@register_op("polygon_box_transform")
def _polygon_box_transform(ctx):
    """polygon_box_transform_op.cc: out = 4*w - x on even planes (x
    offsets), 4*h - x on odd. The reference's parity index is the
    COMBINED n*C + c counter (its loop runs over batch*channels), so
    with an odd channel count the parity flips per batch item —
    replicated bug-for-bug; for the even C every real geometry uses
    this equals plain channel parity."""
    jnp = _jnp()
    x = ctx.input("Input")
    N, C, H, W = x.shape
    wgrid = jnp.broadcast_to(jnp.arange(W, dtype=x.dtype), (H, W))
    hgrid = jnp.broadcast_to(jnp.arange(H, dtype=x.dtype)[:, None], (H, W))
    nc = (jnp.arange(N)[:, None] * C + jnp.arange(C)[None, :])
    even = (nc % 2 == 0)[:, :, None, None]
    base = jnp.where(even, wgrid[None, None], hgrid[None, None])
    return {"Output": 4.0 * base - x}


@register_op("box_clip")
def _box_clip(ctx):
    jnp = _jnp()
    boxes = ctx.input("Input")          # [..., 4] or [B, R, 4]
    im_info = ctx.input("ImInfo")       # [B, 3] (h, w, scale)
    if boxes.ndim == 2:
        h = im_info[0, 0] / im_info[0, 2] - 1.0
        w = im_info[0, 1] / im_info[0, 2] - 1.0
        out = jnp.stack([jnp.clip(boxes[:, 0], 0, w),
                         jnp.clip(boxes[:, 1], 0, h),
                         jnp.clip(boxes[:, 2], 0, w),
                         jnp.clip(boxes[:, 3], 0, h)], axis=1)
    else:
        h = (im_info[:, 0] / im_info[:, 2] - 1.0)[:, None]
        w = (im_info[:, 1] / im_info[:, 2] - 1.0)[:, None]
        out = jnp.stack([jnp.clip(boxes[..., 0], 0, w),
                         jnp.clip(boxes[..., 1], 0, h),
                         jnp.clip(boxes[..., 2], 0, w),
                         jnp.clip(boxes[..., 3], 0, h)], axis=-1)
    return {"Output": out}


@register_op("roi_perspective_transform")
def _roi_perspective_transform(ctx):
    """detection/roi_perspective_transform_op.cc: each ROI is a
    quadrilateral (8 coords, clockwise from top-left); the op warps it to
    a [transformed_height, transformed_width] rectangle with bilinear
    sampling via the standard 4-point homography."""
    import jax
    jnp = _jnp()
    x = ctx.input("X")          # [B, C, H, W]
    rois = ctx.input("ROIs")    # [N, 8]
    lens = ctx.lod_len("ROIs")
    out_h = int(ctx.attr("transformed_height", 1))
    out_w = int(ctx.attr("transformed_width", 1))
    scale = float(ctx.attr("spatial_scale", 1.0))
    B, C, H, W = x.shape
    N = rois.shape[0]
    if lens is None:
        batch_idx = jnp.zeros((N,), jnp.int32)
    else:
        batch_idx = _roi_batch_index(lens, N)

    quad = rois.reshape(N, 4, 2) * scale     # [N, 4, (x,y)]
    # homography: solve the 8x8 system mapping unit square corners to quad
    # (u,v) in [0,1]^2 -> (x,y); dst corners order: tl, tr, br, bl
    src = jnp.asarray([[0.0, 0.0], [1.0, 0.0], [1.0, 1.0], [0.0, 1.0]],
                      x.dtype)

    def solve_h(q):
        rows = []
        rhs = []
        for i in range(4):
            u, v = src[i, 0], src[i, 1]
            xx, yy = q[i, 0], q[i, 1]
            rows.append(jnp.stack([u, v, 1.0, 0.0, 0.0, 0.0,
                                   -u * xx, -v * xx]))
            rhs.append(xx)
            rows.append(jnp.stack([0.0, 0.0, 0.0, u, v, 1.0,
                                   -u * yy, -v * yy]))
            rhs.append(yy)
        A = jnp.stack(rows)
        b = jnp.stack(rhs)
        h = jnp.linalg.solve(A, b)
        return jnp.concatenate([h, jnp.ones(1, h.dtype)]).reshape(3, 3)

    Hm = jax.vmap(solve_h)(quad)            # [N, 3, 3]
    u = (jnp.arange(out_w, dtype=x.dtype) + 0.5) / out_w
    v = (jnp.arange(out_h, dtype=x.dtype) + 0.5) / out_h
    uu, vv = jnp.meshgrid(u, v)             # [out_h, out_w]
    grid = jnp.stack([uu, vv, jnp.ones_like(uu)], axis=-1)  # [h, w, 3]
    mapped = jnp.einsum("nij,hwj->nhwi", Hm, grid)
    px = mapped[..., 0] / jnp.maximum(mapped[..., 2], 1e-8)
    py = mapped[..., 1] / jnp.maximum(mapped[..., 2], 1e-8)

    def sample(img, sx, sy):
        # img [C, H, W]; sx/sy [h, w] source coords; bilinear w/ border 0
        x0 = jnp.floor(sx)
        y0 = jnp.floor(sy)
        wx = sx - x0
        wy = sy - y0
        val = 0.0
        for dy in (0, 1):
            for dx in (0, 1):
                xi = jnp.clip(x0 + dx, 0, W - 1).astype(jnp.int32)
                yi = jnp.clip(y0 + dy, 0, H - 1).astype(jnp.int32)
                wgt = ((wx if dx else 1 - wx) * (wy if dy else 1 - wy))
                inb = ((x0 + dx >= 0) & (x0 + dx <= W - 1) &
                       (y0 + dy >= 0) & (y0 + dy <= H - 1))
                val = val + img[:, yi, xi] * (wgt * inb)[None]
        return val

    imgs = jnp.take(x, batch_idx, axis=0)   # [N, C, H, W]
    out = jax.vmap(sample)(imgs, px, py)    # [N, C, h, w]
    return {"Out": out}


def _roi_batch_index(lens, N):
    jnp = _jnp()
    # rois are grouped by image with per-image counts `lens`
    ends = jnp.cumsum(lens)
    idx = jnp.sum(jnp.arange(N)[:, None] >= ends[None, :], axis=1)
    return idx.astype(jnp.int32)


@register_op("generate_proposal_labels")
def _generate_proposal_labels(ctx):
    """detection/generate_proposal_labels_op.cc: Faster-RCNN second-stage
    sampler — label RPN proposals against ground truth, subsample a fixed
    foreground fraction, emit regression targets. Data-dependent output
    sizes: host/eager path (the reference runs it on CPU too)."""
    import jax
    jnp = _jnp()
    rois = ctx.input("RpnRois")
    gt_classes = ctx.input("GtClasses")
    gt_boxes = ctx.input("GtBoxes")
    if any(isinstance(v, jax.core.Tracer)
           for v in (rois, gt_classes, gt_boxes)):
        raise NotImplementedError(
            "generate_proposal_labels has data-dependent output shapes — "
            "host path only (reference runs it as a CPU kernel)")
    rois = np.asarray(rois).reshape(-1, 4)
    gtc = np.asarray(gt_classes).reshape(-1)
    gtb = np.asarray(gt_boxes).reshape(-1, 4)
    is_crowd_in = ctx.input("IsCrowd")
    crowd = (np.asarray(is_crowd_in).reshape(-1).astype(bool)
             if is_crowd_in is not None else np.zeros(len(gtb), bool))
    batch_size = int(ctx.attr("batch_size_per_im", 256))
    fg_frac = float(ctx.attr("fg_fraction", 0.25))
    fg_thresh = float(ctx.attr("fg_thresh", 0.5))
    bg_hi = float(ctx.attr("bg_thresh_hi", 0.5))
    bg_lo = float(ctx.attr("bg_thresh_lo", 0.0))
    use_random = bool(ctx.attr("use_random", True))
    class_nums = int(ctx.attr("class_nums", 0) or 0)
    # resample every step: fold the executor's step counter into the rng
    rng = np.random.RandomState(
        (int(ctx.attr("seed", 0) or 0) + int(getattr(ctx, "step", 0)))
        & 0x7FFFFFFF)

    # per-image segmentation from the LoD companions (flattening the
    # batch would match proposals against other images' ground truth)
    roi_lens = ctx.lod_len("RpnRois")
    gt_lens = ctx.lod_len("GtBoxes")
    roi_lens = (np.asarray(roi_lens).astype(int)
                if roi_lens is not None else np.array([len(rois)]))
    gt_lens = (np.asarray(gt_lens).astype(int)
               if gt_lens is not None else np.array([len(gtb)]))
    r_off = np.concatenate([[0], np.cumsum(roi_lens)])
    g_off = np.concatenate([[0], np.cumsum(gt_lens)])

    def iou_mat(a, b):
        ax1, ay1, ax2, ay2 = a[:, 0, None], a[:, 1, None], \
            a[:, 2, None], a[:, 3, None]
        bx1, by1, bx2, by2 = b[None, :, 0], b[None, :, 1], \
            b[None, :, 2], b[None, :, 3]
        iw = np.maximum(np.minimum(ax2, bx2) - np.maximum(ax1, bx1), 0)
        ih = np.maximum(np.minimum(ay2, by2) - np.maximum(ay1, by1), 0)
        inter = iw * ih
        ua = ((ax2 - ax1) * (ay2 - ay1)
              + (bx2 - bx1) * (by2 - by1) - inter)
        return inter / np.maximum(ua, 1e-9)

    all_rois, all_labels, all_t, all_in, all_out, out_lens = \
        [], [], [], [], [], []
    for im in range(len(roi_lens)):
        rois_i = rois[r_off[im]:r_off[im + 1]]
        gtb_i = gtb[g_off[im]:g_off[im + 1]]
        gtc_i = gtc[g_off[im]:g_off[im + 1]]
        crowd_i = crowd[g_off[im]:g_off[im + 1]] \
            if len(crowd) >= g_off[im + 1] else \
            np.zeros(len(gtb_i), bool)
        # crowd regions never serve as match targets
        match_b = gtb_i[~crowd_i]
        match_c = gtc_i[~crowd_i]
        cand = np.concatenate([rois_i, match_b], axis=0)
        overlaps = iou_mat(cand, match_b) if len(match_b) else \
            np.zeros((len(cand), 0))
        max_ov = overlaps.max(axis=1) if overlaps.size else \
            np.zeros(len(cand))
        argmax_ov = overlaps.argmax(axis=1) if overlaps.size else \
            np.zeros(len(cand), np.int64)

        fg = np.where(max_ov >= fg_thresh)[0]
        bg = np.where((max_ov < bg_hi) & (max_ov >= bg_lo))[0]
        n_fg = min(int(batch_size * fg_frac), len(fg))
        if len(fg) > n_fg:
            fg = rng.choice(fg, n_fg, replace=False) if use_random \
                else fg[:n_fg]
        n_bg = min(batch_size - n_fg, len(bg))
        if len(bg) > n_bg:
            bg = rng.choice(bg, n_bg, replace=False) if use_random \
                else bg[:n_bg]
        keep = np.concatenate([fg, bg]).astype(np.int64)

        labels = np.zeros(len(keep), np.int32)
        if len(match_b):
            labels[:len(fg)] = match_c[argmax_ov[fg]].astype(np.int32)
        targets4 = np.zeros((len(keep), 4), np.float32)
        if len(fg) and len(match_b):
            p = cand[fg]
            g = match_b[argmax_ov[fg]]
            pw = np.maximum(p[:, 2] - p[:, 0], 1e-6)
            ph = np.maximum(p[:, 3] - p[:, 1], 1e-6)
            gw = np.maximum(g[:, 2] - g[:, 0], 1e-6)
            gh = np.maximum(g[:, 3] - g[:, 1], 1e-6)
            targets4[:len(fg), 0] = ((g[:, 0] + g[:, 2]) / 2
                                     - (p[:, 0] + p[:, 2]) / 2) / pw
            targets4[:len(fg), 1] = ((g[:, 1] + g[:, 3]) / 2
                                     - (p[:, 1] + p[:, 3]) / 2) / ph
            targets4[:len(fg), 2] = np.log(gw / pw)
            targets4[:len(fg), 3] = np.log(gh / ph)
        width = 4 * class_nums if class_nums else 4
        targets = np.zeros((len(keep), width), np.float32)
        inside = np.zeros((len(keep), width), np.float32)
        if class_nums:
            # class-expanded layout (bbox_util: one 4-slot per class)
            for k in range(len(fg)):
                c = int(labels[k])
                targets[k, 4 * c:4 * c + 4] = targets4[k]
                inside[k, 4 * c:4 * c + 4] = 1.0
        else:
            targets[:] = targets4
            inside[:len(fg)] = 1.0
        all_rois.append(cand[keep].astype(np.float32))
        all_labels.append(labels)
        all_t.append(targets)
        all_in.append(inside)
        all_out.append(inside.copy())
        out_lens.append(len(keep))

    return {"Rois": jnp.asarray(np.concatenate(all_rois)),
            "LabelsInt32": jnp.asarray(
                np.concatenate(all_labels).reshape(-1, 1)),
            "BboxTargets": jnp.asarray(np.concatenate(all_t)),
            "BboxInsideWeights": jnp.asarray(np.concatenate(all_in)),
            "BboxOutsideWeights": jnp.asarray(np.concatenate(all_out)),
            "Rois@LOD_LEN": jnp.asarray(np.asarray(out_lens, np.int32))}
