"""MNIST CNN (parity with reference benchmark/fluid/models/mnist.py:68
get_model — conv5x5x20/pool2 + conv5x5x50/pool2 + fc10, Adam)."""

import numpy as np

import paddle_tpu.fluid as fluid


def cnn_model(data):
    conv_pool_1 = fluid.nets.simple_img_conv_pool(
        input=data, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu")
    conv_pool_2 = fluid.nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")
    SIZE = 10
    input_shape = conv_pool_2.shape
    param_shape = [int(np.prod(input_shape[1:]))] + [SIZE]
    scale = (2.0 / (param_shape[0] ** 2 * SIZE)) ** 0.5
    predict = fluid.layers.fc(
        input=conv_pool_2, size=SIZE, act="softmax",
        param_attr=fluid.ParamAttr(
            initializer=fluid.initializer.NormalInitializer(
                loc=0.0, scale=scale)))
    return predict


def get_model(batch_size=128, lr=0.001, use_adam=True):
    """Returns (main, startup, feeds, loss, acc, predict)."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        images = fluid.layers.data(name="pixel", shape=[1, 28, 28],
                                   dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        predict = cnn_model(images)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg_cost = fluid.layers.mean(cost)
        batch_acc = fluid.layers.accuracy(input=predict, label=label)
        if use_adam:
            opt = fluid.optimizer.AdamOptimizer(
                learning_rate=lr, beta1=0.9, beta2=0.999)
        else:
            opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9)
        opt.minimize(avg_cost)
    return main, startup, [images, label], avg_cost, batch_acc, predict
