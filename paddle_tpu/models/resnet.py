"""ResNet for ImageNet (50/101/152 bottleneck) and CIFAR-10 (20/32/44/56).

Parity with reference benchmark/fluid/models/resnet.py:171 (the BASELINE.json
flagship config: ResNet-50 batch 256) — conv7x7/2 + maxpool3/2 + bottleneck
stacks [3,4,6,3] + global avgpool + fc, batch-norm after every conv,
piecewise-decay Momentum training. Built from paddle_tpu layers; on TPU every
conv+bn+relu chain fuses into MXU convolutions with fused epilogues.

`layout` selects the activation layout: NCHW matches the reference feed
contract; NHWC is the TPU-native channels-last layout (channel dim lives in
the lane dimension of the (8,128) tile, so BN stat reductions and the
BN/relu/add epilogues stay lane-aligned instead of reducing across lanes).
Parameters are layout-independent (filters OIHW) — only activations and the
`data` feed change shape.
"""

import paddle_tpu.fluid as fluid


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  is_train=True, layout="NCHW"):
    conv1 = fluid.layers.conv2d(
        input=input, filter_size=filter_size, num_filters=ch_out,
        stride=stride, padding=padding, act=None, bias_attr=False,
        data_format=layout)
    return fluid.layers.batch_norm(input=conv1, act=act,
                                   is_test=not is_train, data_layout=layout)


def _channels(v, layout):
    return v.shape[1] if layout == "NCHW" else v.shape[-1]


def _tag_block_out(x, is_train):
    """Remat tag at the residual-block boundary: with
    remat_policy="block_out" the backward saves ONLY these values and
    recomputes each block's interior from its input — a ~3x
    activation-memory-capacity lever at flagship batch (ROOFLINE.md
    quantified ladder; BN-stats materialization makes it
    capacity-oriented, not traffic-oriented, for conv stacks)."""
    return fluid.layers.remat_checkpoint(x) if is_train else x


def shortcut(input, ch_out, stride, is_train=True, layout="NCHW"):
    ch_in = _channels(input, layout)
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None,
                             is_train=is_train, layout=layout)
    return input


def bottleneck_block(input, num_filters, stride, is_train=True,
                     layout="NCHW"):
    conv0 = conv_bn_layer(input, num_filters, 1, 1, 0, is_train=is_train,
                          layout=layout)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride, 1,
                          is_train=is_train, layout=layout)
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1, 1, 0, act=None,
                          is_train=is_train, layout=layout)
    short = shortcut(input, num_filters * 4, stride, is_train=is_train,
                     layout=layout)
    out = fluid.layers.elementwise_add(x=short, y=conv2, act="relu")
    return _tag_block_out(out, is_train)


def basic_block(input, num_filters, stride, is_train=True, layout="NCHW"):
    conv0 = conv_bn_layer(input, num_filters, 3, stride, 1,
                          is_train=is_train, layout=layout)
    conv1 = conv_bn_layer(conv0, num_filters, 3, 1, 1, act=None,
                          is_train=is_train, layout=layout)
    short = shortcut(input, num_filters, stride, is_train=is_train,
                     layout=layout)
    out = fluid.layers.elementwise_add(x=short, y=conv1, act="relu")
    return _tag_block_out(out, is_train)


def resnet_imagenet(input, class_dim=1000, depth=50, is_train=True,
                    layout="NCHW"):
    cfg = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}[depth]
    conv = conv_bn_layer(input, 64, 7, 2, 3, is_train=is_train,
                         layout=layout)
    pool = fluid.layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                               pool_padding=1, pool_type="max",
                               data_format=layout)
    res = pool
    for stage, count in enumerate(cfg):
        num_filters = 64 * (2 ** stage)
        for i in range(count):
            stride = 2 if i == 0 and stage > 0 else 1
            res = bottleneck_block(res, num_filters, stride,
                                   is_train=is_train, layout=layout)
    pool = fluid.layers.pool2d(input=res, pool_type="avg",
                               global_pooling=True, data_format=layout)
    out = fluid.layers.fc(input=pool, size=class_dim, act="softmax")
    return out


def resnet_cifar10(input, class_dim=10, depth=32, is_train=True,
                   layout="NCHW"):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv = conv_bn_layer(input, 16, 3, 1, 1, is_train=is_train,
                         layout=layout)
    res = conv
    for stage in range(3):
        num_filters = 16 * (2 ** stage)
        for i in range(n):
            stride = 2 if i == 0 and stage > 0 else 1
            res = basic_block(res, num_filters, stride, is_train=is_train,
                              layout=layout)
    pool = fluid.layers.pool2d(input=res, pool_type="avg",
                               global_pooling=True, data_format=layout)
    out = fluid.layers.fc(input=pool, size=class_dim, act="softmax")
    return out


def get_model(batch_size=256, class_dim=1000, depth=50, dataset="imagenet",
              lr=0.1, is_train=True, dtype="float32", layout="NCHW"):
    """(main, startup, feeds, loss, acc, predict) — mirrors the benchmark
    harness contract (fluid_benchmark.py get_model). With layout="NHWC" the
    `data` feed is channels-last ([H, W, 3])."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        if dataset == "imagenet":
            hw = 224
            model_fn = lambda im: resnet_imagenet(
                im, class_dim=class_dim, depth=depth, is_train=is_train,
                layout=layout)
        else:
            hw = 32
            model_fn = lambda im: resnet_cifar10(
                im, class_dim=class_dim, depth=depth, is_train=is_train,
                layout=layout)
        image_shape = [3, hw, hw] if layout == "NCHW" else [hw, hw, 3]
        image = fluid.layers.data(name="data", shape=image_shape,
                                  dtype=dtype)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        predict = model_fn(image)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg_cost = fluid.layers.mean(cost)
        batch_acc = fluid.layers.accuracy(input=predict, label=label)
        if is_train:
            opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9,
                                           regularization=fluid.regularizer
                                           .L2Decay(1e-4))
            opt.minimize(avg_cost)
    return main, startup, [image, label], avg_cost, batch_acc, predict
