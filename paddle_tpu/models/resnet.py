"""ResNet for ImageNet (50/101/152 bottleneck) and CIFAR-10 (20/32/44/56).

Parity with reference benchmark/fluid/models/resnet.py:171 (the BASELINE.json
flagship config: ResNet-50 batch 256) — conv7x7/2 + maxpool3/2 + bottleneck
stacks [3,4,6,3] + global avgpool + fc, batch-norm after every conv,
piecewise-decay Momentum training. Built from paddle_tpu layers; on TPU every
conv+bn+relu chain fuses into MXU convolutions with fused epilogues.
"""

import paddle_tpu.fluid as fluid


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  is_train=True):
    conv1 = fluid.layers.conv2d(
        input=input, filter_size=filter_size, num_filters=ch_out,
        stride=stride, padding=padding, act=None, bias_attr=False)
    return fluid.layers.batch_norm(input=conv1, act=act, is_test=not is_train)


def shortcut(input, ch_out, stride, is_train=True):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None,
                             is_train=is_train)
    return input


def bottleneck_block(input, num_filters, stride, is_train=True):
    conv0 = conv_bn_layer(input, num_filters, 1, 1, 0, is_train=is_train)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride, 1,
                          is_train=is_train)
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1, 1, 0, act=None,
                          is_train=is_train)
    short = shortcut(input, num_filters * 4, stride, is_train=is_train)
    return fluid.layers.elementwise_add(x=short, y=conv2, act="relu")


def basic_block(input, num_filters, stride, is_train=True):
    conv0 = conv_bn_layer(input, num_filters, 3, stride, 1,
                          is_train=is_train)
    conv1 = conv_bn_layer(conv0, num_filters, 3, 1, 1, act=None,
                          is_train=is_train)
    short = shortcut(input, num_filters, stride, is_train=is_train)
    return fluid.layers.elementwise_add(x=short, y=conv1, act="relu")


def resnet_imagenet(input, class_dim=1000, depth=50, is_train=True):
    cfg = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}[depth]
    conv = conv_bn_layer(input, 64, 7, 2, 3, is_train=is_train)
    pool = fluid.layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                               pool_padding=1, pool_type="max")
    res = pool
    for stage, count in enumerate(cfg):
        num_filters = 64 * (2 ** stage)
        for i in range(count):
            stride = 2 if i == 0 and stage > 0 else 1
            res = bottleneck_block(res, num_filters, stride,
                                   is_train=is_train)
    pool = fluid.layers.pool2d(input=res, pool_type="avg",
                               global_pooling=True)
    out = fluid.layers.fc(input=pool, size=class_dim, act="softmax")
    return out


def resnet_cifar10(input, class_dim=10, depth=32, is_train=True):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv = conv_bn_layer(input, 16, 3, 1, 1, is_train=is_train)
    res = conv
    for stage in range(3):
        num_filters = 16 * (2 ** stage)
        for i in range(n):
            stride = 2 if i == 0 and stage > 0 else 1
            res = basic_block(res, num_filters, stride, is_train=is_train)
    pool = fluid.layers.pool2d(input=res, pool_type="avg",
                               global_pooling=True)
    out = fluid.layers.fc(input=pool, size=class_dim, act="softmax")
    return out


def get_model(batch_size=256, class_dim=1000, depth=50, dataset="imagenet",
              lr=0.1, is_train=True, dtype="float32"):
    """(main, startup, feeds, loss, acc, predict) — mirrors the benchmark
    harness contract (fluid_benchmark.py get_model)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        if dataset == "imagenet":
            image_shape = [3, 224, 224]
            model_fn = lambda im: resnet_imagenet(
                im, class_dim=class_dim, depth=depth, is_train=is_train)
        else:
            image_shape = [3, 32, 32]
            model_fn = lambda im: resnet_cifar10(
                im, class_dim=class_dim, depth=depth, is_train=is_train)
        image = fluid.layers.data(name="data", shape=image_shape,
                                  dtype=dtype)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        predict = model_fn(image)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg_cost = fluid.layers.mean(cost)
        batch_acc = fluid.layers.accuracy(input=predict, label=label)
        if is_train:
            opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9,
                                           regularization=fluid.regularizer
                                           .L2Decay(1e-4))
            opt.minimize(avg_cost)
    return main, startup, [image, label], avg_cost, batch_acc, predict
