"""Seq2seq machine translation (WMT14-shaped).

Parity with reference benchmark/fluid/models/machine_translation.py
(seq_to_seq_net: bi-LSTM encoder -> attention LSTM decoder via DynamicRNN,
cross-entropy, Adam) — the BASELINE.json ragged seq2seq config. The decoder
is a DynamicRNN whose static_input closes the padded encoder states into the
lax.scan body; attention is sequence_expand + masked sequence_softmax +
sequence_pool over the ragged encoder axis.

Generation: the reference decodes with beam_search ops inside a While loop
over LoD beams; the TPU build unrolls `max_length` dense beam steps (every
source keeps exactly beam_size rows — ops/beam_ops.py) conditioned on the
encoder's final state, then beam_search_decode backtracks the stacked
parent pointers.
"""

import paddle_tpu.fluid as fluid


def lstm_step(x_t, hidden_t_prev, cell_t_prev, size, param_prefix=None):
    """One LSTM cell step from fc gates (reference lstm_step in
    benchmark/fluid/models/machine_translation.py). `param_prefix` pins the
    gate parameter names so an unrolled decode loop shares one cell's
    weights across all timesteps."""
    gate_idx = [0]

    def linear(inputs):
        if param_prefix is None:
            return fluid.layers.fc(input=inputs, size=size, bias_attr=True)
        g = gate_idx[0]
        gate_idx[0] += 1
        return fluid.layers.fc(
            input=inputs, size=size,
            param_attr=[fluid.ParamAttr(name="%s_g%d_w%d" %
                                        (param_prefix, g, i))
                        for i in range(len(inputs))],
            bias_attr=fluid.ParamAttr(name="%s_g%d_b" % (param_prefix, g)))

    forget_gate = fluid.layers.sigmoid(linear([hidden_t_prev, x_t]))
    input_gate = fluid.layers.sigmoid(linear([hidden_t_prev, x_t]))
    output_gate = fluid.layers.sigmoid(linear([hidden_t_prev, x_t]))
    cell_tilde = fluid.layers.tanh(linear([hidden_t_prev, x_t]))
    cell_t = fluid.layers.sums(input=[
        fluid.layers.elementwise_mul(x=forget_gate, y=cell_t_prev),
        fluid.layers.elementwise_mul(x=input_gate, y=cell_tilde)])
    hidden_t = fluid.layers.elementwise_mul(
        x=output_gate, y=fluid.layers.tanh(cell_t))
    return hidden_t, cell_t


def bi_lstm_encoder(input_seq, gate_size):
    fwd_proj = fluid.layers.fc(input=input_seq, size=gate_size * 4,
                               bias_attr=False)
    forward, _ = fluid.layers.dynamic_lstm(
        input=fwd_proj, size=gate_size * 4, use_peepholes=False)
    rev_proj = fluid.layers.fc(input=input_seq, size=gate_size * 4,
                               bias_attr=False)
    reversed_, _ = fluid.layers.dynamic_lstm(
        input=rev_proj, size=gate_size * 4, is_reverse=True,
        use_peepholes=False)
    return forward, reversed_


def simple_attention(encoder_vec, encoder_proj, decoder_state, decoder_size):
    """Additive attention over the ragged encoder axis. Parameter names are
    pinned ("att_state_w", "att_score_w") so the dense generation decoder
    (below) can reuse the trained weights."""
    state_proj = fluid.layers.fc(
        input=decoder_state, size=decoder_size, bias_attr=False,
        param_attr=fluid.ParamAttr(name="att_state_w"))
    state_expand = fluid.layers.sequence_expand(x=state_proj, y=encoder_proj)
    concated = fluid.layers.concat(input=[encoder_proj, state_expand], axis=1)
    weights = fluid.layers.fc(input=concated, size=1, act="tanh",
                              bias_attr=False,
                              param_attr=fluid.ParamAttr(name="att_score_w"))
    weights = fluid.layers.sequence_softmax(input=weights)
    weights = fluid.layers.reshape(x=weights, shape=[-1])
    scaled = fluid.layers.elementwise_mul(x=encoder_vec, y=weights, axis=0)
    return fluid.layers.sequence_pool(input=scaled, pool_type="sum")


def seq_to_seq_net(embedding_dim, encoder_size, decoder_size,
                   source_dict_dim, target_dict_dim, is_generating=False,
                   beam_size=3, max_length=8):
    src_word_idx = fluid.layers.data(name="source_sequence", shape=[1],
                                     dtype="int64", lod_level=1)
    src_embedding = fluid.layers.embedding(
        input=src_word_idx, size=[source_dict_dim, embedding_dim],
        dtype="float32")
    src_forward, src_reversed = bi_lstm_encoder(src_embedding, encoder_size)
    encoded_vector = fluid.layers.concat(
        input=[src_forward, src_reversed], axis=1)
    encoded_proj = fluid.layers.fc(input=encoded_vector, size=decoder_size,
                                   bias_attr=False)
    backward_first = fluid.layers.sequence_pool(input=src_reversed,
                                                pool_type="first")
    decoder_boot = fluid.layers.fc(input=backward_first, size=decoder_size,
                                   bias_attr=False, act="tanh")

    if not is_generating:
        trg_word_idx = fluid.layers.data(name="target_sequence", shape=[1],
                                         dtype="int64", lod_level=1)
        trg_embedding = fluid.layers.embedding(
            input=trg_word_idx, size=[target_dict_dim, embedding_dim],
            dtype="float32", param_attr=fluid.ParamAttr(name="trg_emb"))

        rnn = fluid.layers.DynamicRNN()
        cell_init = fluid.layers.fill_constant_batch_size_like(
            input=decoder_boot, value=0.0, shape=[-1, decoder_size],
            dtype="float32")
        cell_init.stop_gradient = False
        with rnn.block():
            current_word = rnn.step_input(trg_embedding)
            encoder_vec = rnn.static_input(encoded_vector)
            encoder_proj = rnn.static_input(encoded_proj)
            hidden_mem = rnn.memory(init=decoder_boot, need_reorder=True)
            cell_mem = rnn.memory(init=cell_init)
            context = simple_attention(encoder_vec, encoder_proj,
                                       hidden_mem, decoder_size)
            decoder_inputs = fluid.layers.concat(
                input=[context, current_word], axis=1)
            h, c = lstm_step(decoder_inputs, hidden_mem, cell_mem,
                             decoder_size, param_prefix="decoder_lstm")
            rnn.update_memory(hidden_mem, h)
            rnn.update_memory(cell_mem, c)
            # shared names with the generation decoder so trained weights
            # drive beam-search decoding
            out = fluid.layers.fc(
                input=h, size=target_dict_dim, act="softmax",
                param_attr=fluid.ParamAttr(name="decoder_out_w"),
                bias_attr=fluid.ParamAttr(name="decoder_out_b"))
            rnn.output(out)
        prediction = rnn()

        label = fluid.layers.data(name="label_sequence", shape=[1],
                                  dtype="int64", lod_level=1)
        cost = fluid.layers.cross_entropy(input=prediction, label=label)
        avg_cost = fluid.layers.mean(cost)
        feeding_list = ["source_sequence", "target_sequence",
                        "label_sequence"]
        return avg_cost, prediction, feeding_list

    # -- generation: dense beam search with per-step attention, sharing the
    # training decoder's parameters (attention + lstm gates + output fc) --
    W = beam_size
    # replicate per-source state to W beam rows: [B, D] -> [B*W, D]
    boot0 = fluid.layers.unsqueeze(decoder_boot, axes=[1])      # [B, 1, D]
    boot0 = fluid.layers.expand(boot0, expand_times=[1, W, 1])  # [B, W, D]
    boot_beam = fluid.layers.reshape(boot0, shape=[-1, decoder_size])

    # dense (padded) encoder states + validity mask, gathered per beam row
    pad0 = fluid.layers.fill_constant([1], "float32", 0.0)
    enc_pad, _ = fluid.layers.sequence_pad(encoded_vector, pad0)  # [B,T,2E]
    proj_pad, _ = fluid.layers.sequence_pad(encoded_proj, pad0)   # [B,T,D]
    ones_ragged = fluid.layers.scale(
        fluid.layers.cast(src_word_idx, "float32"), scale=0.0, bias=1.0)
    mask_pad, _ = fluid.layers.sequence_pad(ones_ragged, pad0)    # [B,T,1]
    ones_bw = fluid.layers.fill_constant_batch_size_like(
        input=boot_beam, shape=[-1, 1], value=1.0, dtype="float32")
    ramp = fluid.layers.cumsum(ones_bw, axis=0, exclusive=True)  # 0..BW-1
    src_idx = fluid.layers.cast(
        fluid.layers.floor(fluid.layers.scale(ramp, scale=1.0 / W)), "int32")
    src_idx = fluid.layers.reshape(src_idx, shape=[-1])
    enc_beam = fluid.layers.gather(enc_pad, src_idx)      # [BW, T, 2E]
    proj_beam = fluid.layers.gather(proj_pad, src_idx)    # [BW, T, D]
    mask_beam = fluid.layers.gather(mask_pad, src_idx)    # [BW, T, 1]

    # attention score weight shared with training: att_score_w [2D, 1],
    # split into the encoder-proj half and the state half
    helper = fluid.LayerHelper("gen_attention")
    att_w = helper.create_parameter(
        attr=fluid.ParamAttr(name="att_score_w"),
        shape=[2 * decoder_size, 1], dtype="float32")
    w_proj = fluid.layers.slice(att_w, axes=[0], starts=[0],
                                ends=[decoder_size])
    w_state = fluid.layers.slice(att_w, axes=[0], starts=[decoder_size],
                                 ends=[2 * decoder_size])

    def dense_attention(hidden):
        """Same math as simple_attention, on padded beam tensors:
        fc(concat([proj, state])) == proj @ w_proj + state @ w_state."""
        state_proj = fluid.layers.fc(
            input=hidden, size=decoder_size, bias_attr=False,
            param_attr=fluid.ParamAttr(name="att_state_w"))
        s_enc = fluid.layers.matmul(proj_beam, w_proj)     # [BW, T, 1]
        s_state = fluid.layers.unsqueeze(
            fluid.layers.matmul(state_proj, w_state), axes=[1])  # [BW,1,1]
        score = fluid.layers.tanh(
            fluid.layers.elementwise_add(s_enc, s_state))
        neg = fluid.layers.scale(mask_beam, scale=1e9, bias=-1e9)
        score = fluid.layers.elementwise_add(
            fluid.layers.elementwise_mul(score, mask_beam), neg)
        score = fluid.layers.squeeze(score, axes=[2])      # [BW, T]
        att = fluid.layers.softmax(score)
        att = fluid.layers.unsqueeze(att, axes=[2])        # [BW, T, 1]
        ctx = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(enc_beam, att), dim=1)
        return ctx                                          # [BW, 2E]

    start_id = 0
    end_id = 1
    pre_ids = fluid.layers.fill_constant_batch_size_like(
        input=boot_beam, shape=[-1, 1], value=start_id, dtype="int64")
    pre_scores = fluid.layers.fill_constant_batch_size_like(
        input=boot_beam, shape=[-1, 1], value=0.0, dtype="float32")

    step_ids, step_scores, step_parents = [], [], []
    hidden = boot_beam
    cell = fluid.layers.fill_constant_batch_size_like(
        input=boot_beam, shape=[-1, decoder_size], value=0.0,
        dtype="float32")
    first = True
    for t in range(max_length):
        word_emb = fluid.layers.embedding(
            input=pre_ids, size=[target_dict_dim, embedding_dim],
            dtype="float32", param_attr=fluid.ParamAttr(name="trg_emb"))
        word_emb = fluid.layers.reshape(word_emb,
                                        shape=[-1, embedding_dim])
        context = dense_attention(hidden)
        dec_in = fluid.layers.concat(input=[context, word_emb], axis=1)
        hidden, cell = lstm_step(dec_in, hidden, cell, decoder_size,
                                 param_prefix="decoder_lstm")
        probs = fluid.layers.fc(
            input=hidden, size=target_dict_dim, act="softmax",
            param_attr=fluid.ParamAttr(name="decoder_out_w"),
            bias_attr=fluid.ParamAttr(name="decoder_out_b"))
        log_probs = fluid.layers.log(probs)
        accu = fluid.layers.elementwise_add(log_probs, pre_scores, axis=0)
        if first:
            # deactivate the W-1 duplicate start beams per source so the
            # first expansion selects from one beam only (the reference
            # starts with a single LoD beam per source)
            first = False
            accu = fluid.layers.elementwise_add(
                accu, _beam_slot_mask(boot_beam, W), axis=0)
        sel_ids, sel_scores, parent_idx = fluid.layers.beam_search(
            pre_ids, pre_scores, None, accu, beam_size=W, end_id=end_id,
            return_parent_idx=True)
        step_ids.append(sel_ids)
        step_scores.append(sel_scores)
        step_parents.append(parent_idx)
        pre_ids, pre_scores = sel_ids, sel_scores
        # reorder recurrent state by parent pointers
        hidden = fluid.layers.gather(hidden, parent_idx)
        cell = fluid.layers.gather(cell, parent_idx)

    ids_arr = fluid.layers.stack([fluid.layers.reshape(i, shape=[-1])
                                  for i in step_ids], axis=0)
    scores_arr = fluid.layers.stack([fluid.layers.reshape(s, shape=[-1])
                                     for s in step_scores], axis=0)
    parents_arr = fluid.layers.stack(step_parents, axis=0)
    sent_ids, sent_scores = fluid.layers.beam_search_decode(
        ids_arr, scores_arr, beam_size=W, end_id=end_id,
        parent_idx=parents_arr)
    return sent_ids, sent_scores, ["source_sequence"]


def _beam_slot_mask(context, W):
    return fluid.layers.beam_slot_mask(context, W)


def get_model(batch_size=16, embedding_dim=512, encoder_size=512,
              decoder_size=512, dict_size=30000, lr=0.0002):
    """Training program (reference get_model: Adam, dict 30k, dim 512)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        avg_cost, prediction, feeding_list = seq_to_seq_net(
            embedding_dim, encoder_size, decoder_size, dict_size, dict_size,
            is_generating=False)
        fluid.optimizer.Adam(learning_rate=lr).minimize(avg_cost)
    return main, startup, feeding_list, avg_cost, None, prediction
