"""Stacked dynamic-LSTM text classifier.

Parity with reference benchmark/fluid/models/stacked_dynamic_lstm.py
(IMDB sentiment: embedding -> N x (fc 4H -> dynamic_lstm) -> max pools ->
fc softmax, Adam) — the BASELINE.json variable-length LoDTensor config.
Ragged sequences flow as padded [B, T, ...] + lengths; the LSTM is one
lax.scan per layer (see ops/sequence_ops.py).
"""

import paddle_tpu.fluid as fluid


def lstm_net(data, dict_dim, class_dim=2, emb_dim=512, hid_dim=512,
             stacked_num=3):
    emb = fluid.layers.embedding(input=data, size=[dict_dim, emb_dim],
                                 is_sparse=False)
    pools = []
    fc1 = fluid.layers.fc(input=emb, size=hid_dim * 4)
    lstm1, cell1 = fluid.layers.dynamic_lstm(input=fc1, size=hid_dim * 4)
    pools.append(fluid.layers.sequence_pool(lstm1, pool_type="max"))
    inputs = lstm1
    for _ in range(2, stacked_num + 1):
        fc = fluid.layers.fc(input=inputs, size=hid_dim * 4)
        lstm, cell = fluid.layers.dynamic_lstm(input=fc,
                                               size=hid_dim * 4)
        pools.append(fluid.layers.sequence_pool(lstm, pool_type="max"))
        inputs = lstm
    prediction = fluid.layers.fc(input=pools, size=class_dim, act="softmax")
    return prediction


def get_model(batch_size=64, dict_dim=5147, emb_dim=512, hid_dim=512,
              stacked_num=3, class_dim=2, lr=0.002):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                                 lod_level=1)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        prediction = lstm_net(data, dict_dim, class_dim, emb_dim, hid_dim,
                              stacked_num)
        cost = fluid.layers.cross_entropy(input=prediction, label=label)
        avg_cost = fluid.layers.mean(cost)
        acc = fluid.layers.accuracy(input=prediction, label=label)
        fluid.optimizer.Adam(learning_rate=lr).minimize(avg_cost)
    return main, startup, [data, label], avg_cost, acc, prediction
