"""Decoder-only Transformer language model, TPU-first.

Reference analogue: the era's transformer appears as the dist-training
workhorse (python/paddle/fluid/tests/unittests/dist_transformer.py, the
WMT16 encoder-decoder). This build keeps the same program-construction
style (fluid layers + append_backward) but uses the TPU-native attention
stack: the Pallas flash-attention op (ops/pallas_kernels.py) on one chip,
and — through paddle_tpu.parallel — ring attention / Ulysses for sequence
parallelism at long context.

Pre-norm blocks, learned positional embeddings, GELU MLP, causal masking;
everything static-shaped so the whole step compiles to one XLA program.
"""

import numpy as np

import paddle_tpu.fluid as fluid


def transformer_block(x, d_model, n_heads, d_ff, prefix, is_train=True):
    """Pre-norm block: x [N, S, D] -> [N, S, D]."""
    ln1 = fluid.layers.layer_norm(x, begin_norm_axis=2,
                                  param_attr=fluid.ParamAttr(
                                      name=prefix + "_ln1_w"),
                                  bias_attr=fluid.ParamAttr(
                                      name=prefix + "_ln1_b"))
    qkv = fluid.layers.fc(
        input=ln1, size=3 * d_model, num_flatten_dims=2,
        param_attr=fluid.ParamAttr(name=prefix + "_qkv_w"),
        bias_attr=fluid.ParamAttr(name=prefix + "_qkv_b"))
    q = fluid.layers.slice(qkv, axes=[2], starts=[0], ends=[d_model])
    k = fluid.layers.slice(qkv, axes=[2], starts=[d_model],
                           ends=[2 * d_model])
    v = fluid.layers.slice(qkv, axes=[2], starts=[2 * d_model],
                           ends=[3 * d_model])
    att = fluid.layers.flash_attention(q, k, v, num_heads=n_heads,
                                       causal=True)
    proj = fluid.layers.fc(
        input=att, size=d_model, num_flatten_dims=2,
        param_attr=fluid.ParamAttr(name=prefix + "_proj_w"),
        bias_attr=fluid.ParamAttr(name=prefix + "_proj_b"))
    x = fluid.layers.elementwise_add(x, proj)

    ln2 = fluid.layers.layer_norm(x, begin_norm_axis=2,
                                  param_attr=fluid.ParamAttr(
                                      name=prefix + "_ln2_w"),
                                  bias_attr=fluid.ParamAttr(
                                      name=prefix + "_ln2_b"))
    h = fluid.layers.fc(
        input=ln2, size=d_ff, num_flatten_dims=2, act="gelu",
        param_attr=fluid.ParamAttr(name=prefix + "_ff1_w"),
        bias_attr=fluid.ParamAttr(name=prefix + "_ff1_b"))
    h = fluid.layers.fc(
        input=h, size=d_model, num_flatten_dims=2,
        param_attr=fluid.ParamAttr(name=prefix + "_ff2_w"),
        bias_attr=fluid.ParamAttr(name=prefix + "_ff2_b"))
    out = fluid.layers.elementwise_add(x, h)
    # layer-boundary remat tag: remat_policy="block_out" recomputes each
    # transformer layer from its input in the backward (the standard
    # per-layer checkpointing for long-sequence training)
    return fluid.layers.remat_checkpoint(out) if is_train else out


def build(tokens, vocab_size, seq_len, d_model=512, n_heads=8, n_layers=6,
          d_ff=2048, is_train=True):
    """tokens [N, S] int64 -> logits [N, S, vocab]."""
    emb = fluid.layers.embedding(
        input=tokens, size=[vocab_size, d_model], dtype="float32",
        param_attr=fluid.ParamAttr(name="tok_emb"))
    pos_ids = fluid.layers.cumsum(
        fluid.layers.fill_constant([1, seq_len], "int64", 1), axis=1,
        exclusive=True)
    pos_emb = fluid.layers.embedding(
        input=pos_ids, size=[seq_len, d_model], dtype="float32",
        param_attr=fluid.ParamAttr(name="pos_emb"))
    x = fluid.layers.elementwise_add(emb, pos_emb)
    if is_train:
        x = fluid.layers.dropout(x, dropout_prob=0.1, is_test=not is_train)
    for i in range(n_layers):
        x = transformer_block(x, d_model, n_heads, d_ff, "blk%d" % i,
                              is_train=is_train)
    x = fluid.layers.layer_norm(x, begin_norm_axis=2,
                                param_attr=fluid.ParamAttr(name="lnf_w"),
                                bias_attr=fluid.ParamAttr(name="lnf_b"))
    logits = fluid.layers.fc(
        input=x, size=vocab_size, num_flatten_dims=2,
        param_attr=fluid.ParamAttr(name="lm_head_w"), bias_attr=False)
    return logits


def get_model(batch_size=8, seq_len=512, vocab_size=32000, d_model=512,
              n_heads=8, n_layers=6, d_ff=2048, lr=1e-3, is_train=True):
    """Training program: next-token cross entropy, Adam (the reference
    transformer's optimizer), feeds src [N,S] + tgt [N,S]."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        tokens = fluid.layers.data("tokens", shape=[seq_len], dtype="int64")
        labels = fluid.layers.data("labels", shape=[seq_len], dtype="int64")
        logits = build(tokens, vocab_size, seq_len, d_model, n_heads,
                       n_layers, d_ff, is_train=is_train)
        flat = fluid.layers.reshape(logits, [-1, vocab_size])
        flat_l = fluid.layers.reshape(labels, [-1, 1])
        loss = fluid.layers.softmax_with_cross_entropy(flat, flat_l)
        avg_loss = fluid.layers.mean(loss)
        if is_train:
            fluid.optimizer.Adam(learning_rate=lr).minimize(avg_loss)
    return main, startup, ["tokens", "labels"], avg_loss, None, logits
