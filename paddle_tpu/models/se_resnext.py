"""SE-ResNeXt (50/101/152) for ImageNet-shaped inputs.

Parity with reference python/paddle/fluid/tests/unittests/dist_se_resnext.py
(SE_ResNeXt class: grouped 3x3 convs — cardinality 32 for depths 50/101,
64 for 152 — + squeeze-excitation with reduction 16) — the reference's
multi-device convergence workhorse (test_parallel_executor_seresnext /
test_dist_se_resnext).

TPU notes: grouped convs lower to one lax.conv_general_dilated with
feature_group_count; the SE block's squeeze (global avgpool) + two fcs +
channel scale all fuse into the surrounding convolutions' epilogues.
"""

import paddle_tpu.fluid as fluid


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, is_train=True, remove_bn=False,
                  layout="NCHW"):
    conv = fluid.layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=(filter_size - 1) // 2, groups=groups,
        act=act if remove_bn else None, bias_attr=False,
        data_format=layout)
    if remove_bn:
        # reference test_parallel_executor_seresnext.py:38 `remove_bn`:
        # the Executor-vs-ParallelExecutor convergence comparison drops BN
        # because cross-replica stat reassociation makes deep BN stacks
        # numerically chaotic (the reference's FIXME(zcd) comment).
        # Deviation: the reference also drops `act` here (returning the
        # bare conv, a mostly-linear net); we KEEP the activation so the
        # parity comparison exercises a fully nonlinear model — a stricter
        # check than the reference's.
        return conv
    return fluid.layers.batch_norm(input=conv, act=act,
                                   is_test=not is_train,
                                   data_layout=layout)


def squeeze_excitation(input, num_channels, reduction_ratio,
                       layout="NCHW"):
    pool = fluid.layers.pool2d(input=input, pool_type="avg",
                               global_pooling=True, data_format=layout)
    pool = fluid.layers.reshape(pool, [-1, num_channels])
    squeeze = fluid.layers.fc(input=pool,
                              size=num_channels // reduction_ratio,
                              act="relu")
    excitation = fluid.layers.fc(input=squeeze, size=num_channels,
                                 act="sigmoid")
    bshape = ([-1, num_channels, 1, 1] if layout == "NCHW"
              else [-1, 1, 1, num_channels])
    excitation = fluid.layers.reshape(excitation, bshape)
    return fluid.layers.elementwise_mul(x=input, y=excitation)


def shortcut(input, ch_out, stride, is_train=True, remove_bn=False,
             layout="NCHW"):
    ch_in = input.shape[1] if layout == "NCHW" else input.shape[-1]
    if ch_in != ch_out or stride != 1:
        filter_size = 1
        return conv_bn_layer(input, ch_out, filter_size, stride,
                             is_train=is_train, remove_bn=remove_bn,
                             layout=layout)
    return input


def bottleneck_block(input, num_filters, stride, cardinality,
                     reduction_ratio, is_train=True, remove_bn=False,
                     layout="NCHW"):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu",
                          is_train=is_train, remove_bn=remove_bn,
                          layout=layout)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride,
                          groups=cardinality, act="relu", is_train=is_train,
                          remove_bn=remove_bn, layout=layout)
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, act=None,
                          is_train=is_train, remove_bn=remove_bn,
                          layout=layout)
    scale = squeeze_excitation(conv2, num_filters * 2, reduction_ratio,
                               layout=layout)
    short = shortcut(input, num_filters * 2, stride, is_train=is_train,
                     remove_bn=remove_bn, layout=layout)
    out = fluid.layers.elementwise_add(x=short, y=scale, act="relu")
    # block-boundary remat tag (ROOFLINE.md block_out capacity lever)
    return fluid.layers.remat_checkpoint(out) if is_train else out


def build(img, layers=50, class_dim=1000, is_train=True, remove_bn=False,
          remove_dropout=False, layout="NCHW"):
    """img [N, 3, H, W] (layout="NCHW") or [N, H, W, 3] ("NHWC")
    -> logits [N, class_dim] (pre-softmax fc)."""
    # cardinality per depth matches dist_se_resnext.py:60,:78,:96 —
    # 32 groups for SE-ResNeXt-50/101, 64 for 152
    supported = {50: ([3, 4, 6, 3], [128, 256, 512, 1024], 32),
                 101: ([3, 4, 23, 3], [128, 256, 512, 1024], 32),
                 152: ([3, 8, 36, 3], [128, 256, 512, 1024], 64)}
    depth, num_filters, cardinality = supported[layers]
    reduction_ratio = 16

    if layers == 152:
        conv = conv_bn_layer(img, 64, 3, stride=2, act="relu",
                             is_train=is_train, remove_bn=remove_bn,
                             layout=layout)
        conv = conv_bn_layer(conv, 64, 3, act="relu", is_train=is_train,
                             remove_bn=remove_bn, layout=layout)
        conv = conv_bn_layer(conv, 128, 3, act="relu", is_train=is_train,
                             remove_bn=remove_bn, layout=layout)
    else:
        conv = conv_bn_layer(img, 64, 7, stride=2, act="relu",
                             is_train=is_train, remove_bn=remove_bn,
                             layout=layout)
    conv = fluid.layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                               pool_padding=1, pool_type="max",
                               data_format=layout)
    for block in range(len(depth)):
        for i in range(depth[block]):
            conv = bottleneck_block(
                conv, num_filters[block], 2 if i == 0 and block != 0 else 1,
                cardinality, reduction_ratio, is_train=is_train,
                remove_bn=remove_bn, layout=layout)
    pool = fluid.layers.pool2d(input=conv, pool_type="avg",
                               global_pooling=True, data_format=layout)
    nch = pool.shape[1] if layout == "NCHW" else pool.shape[-1]
    pool = fluid.layers.reshape(pool, [-1, nch])
    if remove_dropout:
        # reference test_parallel_executor_seresnext.py:34 `remove_dropout`
        drop = pool
    else:
        drop = fluid.layers.dropout(pool, dropout_prob=0.2,
                                    is_test=not is_train)
    return fluid.layers.fc(input=drop, size=class_dim)


def get_model(batch_size=32, class_dim=1000, layers=50, img_size=224,
              lr=0.1, is_train=True, remove_bn=False, remove_dropout=False,
              layout="NCHW"):
    """Training program mirroring dist_se_resnext.py get_model: Momentum +
    piecewise decay + L2. remove_bn/remove_dropout mirror the reference's
    test_parallel_executor_seresnext.py globals (:34,:38) used by its
    Executor-vs-ParallelExecutor convergence comparison."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img_shape = ([3, img_size, img_size] if layout == "NCHW"
                     else [img_size, img_size, 3])
        img = fluid.layers.data("data", shape=img_shape, dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        logits = build(img, layers=layers, class_dim=class_dim,
                       is_train=is_train, remove_bn=remove_bn,
                       remove_dropout=remove_dropout, layout=layout)
        prob = fluid.layers.softmax(logits)
        loss = fluid.layers.cross_entropy(input=prob, label=label)
        avg_loss = fluid.layers.mean(loss)
        acc = fluid.layers.accuracy(input=prob, label=label)
        if is_train:
            epochs = [30, 60, 90]
            steps_per_pass = 1252
            bd = [e * steps_per_pass for e in epochs]
            lrs = [lr * (0.1 ** i) for i in range(len(bd) + 1)]
            opt = fluid.optimizer.Momentum(
                learning_rate=fluid.layers.piecewise_decay(
                    boundaries=bd, values=lrs),
                momentum=0.9,
                regularization=fluid.regularizer.L2Decay(1e-4))
            opt.minimize(avg_loss)
    return main, startup, ["data", "label"], avg_loss, acc, prob
