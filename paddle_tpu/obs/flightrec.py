"""Flight recorder: a post-mortem black box for the serving fleet.

When a lane wedges, a sentinel gives up, or an SLO burns through its
budget, the evidence — the span ring, the event ring, the metrics
timeline, which thread was stuck where — lives in process memory and
evaporates with the process.  This module dumps it to disk the moment
a trigger fires, as one atomically-committed bundle directory:

    <flight_dir>/
      flight_<utcstamp>_<pid>_<reason>/
        MANIFEST.json     # schema, reason, ts, context, per-file
                          #   {bytes, crc32} — written LAST
        spans.jsonl       # the tracing ring at dump time
        events.jsonl      # the structured event ring
        metrics.prom      # the full Prometheus exposition
        threads.txt       # all-thread stacks (sys._current_frames)
        flags.json        # every resolved FLAGS value
        <provider>.json   # registered snapshots (server stats/health,
                          #   SLO timeline, lane/slot/registry state)

Commit discipline is the checkpoint vault's (CHECKPOINT.md): every
file is written+fsynced into a ``_tmp.flight_*`` directory, the dir is
fsynced, the vault chaos hook fires at ``flight_committed``, then ONE
``os.rename`` publishes the bundle — a SIGKILL at any point leaves
prior bundles intact plus at most a stale tmp dir (swept by the next
dump), never a half-readable bundle.  Keep-N rotation bounds disk.

Triggers (``trigger(reason, **context)``): ``watchdog_fire`` (executor
step watchdog), ``sentinel_giveup`` / ``sentinel_rollback`` (training
sentinel), ``slo_breach`` (obs/slo.py), ``thread_death`` (a serving
router/lane thread dying un-handled), and the manual ``flight`` RPC
verb.  A per-reason cooldown (``FLAGS.flight_cooldown_s``) makes a
breach storm write ONE bundle, not hundreds — the 4-thread trigger
hammer in tests/test_slo.py pins exactly-one.  Triggering NEVER raises
and is a no-op while ``FLAGS.flight_dir`` is unset.

``tools/flight_inspect.py`` lists, validates (manifest CRC walk +
JSONL parse), and pretty-prints bundles; ``tools/chaos.py --scenario
slo-breach`` drives the whole loop (injected latency -> breach ->
bundle) including the SIGKILL-mid-dump crash test.
"""

import binascii
import json
import os
import sys
import threading
import time
import traceback
import warnings

__all__ = ["FlightRecorder", "configure", "get_recorder", "trigger",
           "add_provider", "remove_provider", "validate_bundle",
           "read_manifest", "list_bundles", "MANIFEST_NAME",
           "SCHEMA_VERSION", "REQUIRED_FILES"]

MANIFEST_NAME = "MANIFEST.json"
SCHEMA_VERSION = 1
_TMP_PREFIX = "_tmp.flight_"
_BUNDLE_PREFIX = "flight_"
# every bundle must carry these; providers add more
REQUIRED_FILES = ("spans.jsonl", "events.jsonl", "metrics.prom",
                  "threads.txt", "flags.json")


def _thread_stacks():
    """Human-readable stacks of EVERY live thread — the wedged-lane
    smoking gun.  ``sys._current_frames`` is a point-in-time snapshot;
    names resolve through threading.enumerate."""
    names = {t.ident: "%s%s" % (t.name, " daemon" if t.daemon else "")
             for t in threading.enumerate()}
    lines = []
    for ident, frame in sorted(sys._current_frames().items()):
        lines.append("--- thread %s (ident %d) ---"
                     % (names.get(ident, "<unknown>"), ident))
        lines.extend(l.rstrip("\n")
                     for l in traceback.format_stack(frame))
        lines.append("")
    return "\n".join(lines) + "\n"


def _flags_snapshot():
    try:
        from ..flags import FLAGS, flag_info
        return {name: getattr(FLAGS, name) for name in flag_info()}
    except Exception as e:
        return {"_error": "%s: %s" % (type(e).__name__, e)}


class FlightRecorder(object):
    """One bundle sink rooted at ``root`` with keep-N rotation and a
    per-trigger-reason cooldown."""

    def __init__(self, root, keep=8, cooldown_s=30.0):
        self.root = str(root)
        self.keep = max(int(keep), 1)
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        # serializes concurrent dumps WITHOUT holding _lock across
        # provider callbacks — a provider may legitimately read this
        # recorder back (the server's health snapshot does)
        self._dump_lock = threading.Lock()
        self._last = {}       # reason -> monotonic of last ACCEPTED
        self._seq = 0
        self._providers = {}  # name -> fn() -> json-encodable
        self._dumps = 0
        self._failures = 0

    # -- providers -----------------------------------------------------

    def add_provider(self, name, fn):
        """Register a snapshot source: ``fn()`` returns a
        json-encodable object written as ``<name>.json`` in every
        bundle.  A provider that raises at dump time is recorded as an
        error entry, never a dump failure."""
        with self._lock:
            self._providers[str(name)] = fn

    def remove_provider(self, name):
        with self._lock:
            self._providers.pop(str(name), None)

    # -- trigger -------------------------------------------------------

    def trigger(self, reason, force=False, **context):
        """Fire one trigger.  Returns the committed bundle path, or
        None when the cooldown suppressed it (or the dump failed).
        Never raises — the recorder must not take down what it
        observes."""
        reason = str(reason)
        now = time.monotonic()
        with self._lock:
            last = self._last.get(reason)
            if not force and last is not None \
                    and now - last < self.cooldown_s:
                return None
            # stamp at ACCEPT time so a concurrent trigger storm
            # collapses to one bundle even while this dump runs
            self._last[reason] = now
        try:
            return self.dump(reason, context)
        except Exception as e:
            self._failures += 1
            warnings.warn("flight recorder dump failed (%s: %s) — "
                          "continuing without a bundle"
                          % (type(e).__name__, e))
            return None

    # -- the dump ------------------------------------------------------

    def _sweep_stale_locked(self):
        try:
            for name in os.listdir(self.root):
                if name.startswith(_TMP_PREFIX):
                    import shutil
                    shutil.rmtree(os.path.join(self.root, name),
                                  ignore_errors=True)
        except OSError:
            pass

    def _rotate_locked(self):
        bundles = self.list_bundles()
        for path in bundles[:-self.keep]:
            import shutil
            shutil.rmtree(path, ignore_errors=True)

    def dump(self, reason, context=None):
        """Write one bundle unconditionally (cooldown is trigger()'s
        job) and return its committed path.  All file writes + the
        rename happen here so the whole commit is one auditable scope
        (the lint_runtime vault-write check keys on that)."""
        from ..fluid.checkpoint import _chaos, _fsync_dir
        from . import events as obs_events
        from . import registry as obs_registry
        from . import tracing as obs_tracing
        t0 = time.monotonic()
        with self._lock:
            self._seq += 1
            seq = self._seq
            providers = dict(self._providers)
        # the writes run under _dump_lock only: providers may read this
        # recorder back (stats/list), which needs _lock free
        with self._dump_lock:
            os.makedirs(self.root, exist_ok=True)
            self._sweep_stale_locked()
            # wall stamp names the bundle (operators sort by it); the
            # seq suffix keeps same-second dumps distinct
            stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
            final_name = "%s%s.%03d_%d_%s" % (
                _BUNDLE_PREFIX, stamp, seq % 1000, os.getpid(),
                reason.replace(os.sep, "_"))
            final = os.path.join(self.root, final_name)
            tmp = os.path.join(self.root,
                               _TMP_PREFIX + final_name[len(_BUNDLE_PREFIX):])
            os.makedirs(tmp)
            files = {}

            def _write(name, data):
                if isinstance(data, str):
                    data = data.encode("utf-8")
                path = os.path.join(tmp, name)
                with open(path, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                files[name] = {"bytes": len(data),
                               "crc32": binascii.crc32(data) & 0xFFFFFFFF}

            _write("spans.jsonl", "".join(
                json.dumps(s, sort_keys=True) + "\n"
                for s in obs_tracing.recent_spans()))
            _write("events.jsonl", "".join(
                json.dumps(e, sort_keys=True) + "\n"
                for e in obs_events.recent_events()))
            _write("metrics.prom",
                   obs_registry.default().prometheus_text())
            _write("threads.txt", _thread_stacks())
            _write("flags.json", json.dumps(_flags_snapshot(),
                                            indent=1, sort_keys=True,
                                            default=str))
            for name, fn in sorted(providers.items()):
                try:
                    payload = fn()
                except Exception as e:
                    payload = {"_error": "%s: %s"
                               % (type(e).__name__, e)}
                _write("%s.json" % name,
                       json.dumps(payload, indent=1, sort_keys=True,
                                  default=str))
            manifest = {
                "schema": SCHEMA_VERSION,
                "reason": reason,
                "ts": round(time.time(), 6),
                "context": {k: (v if isinstance(v, (str, int, float,
                                                    bool)) else str(v))
                            for k, v in (context or {}).items()
                            if v is not None},
                "pid": os.getpid(),
                "dump_ms": round((time.monotonic() - t0) * 1e3, 3),
                "files": files,
            }
            _write(MANIFEST_NAME,
                   json.dumps(manifest, indent=1, sort_keys=True))
            _fsync_dir(tmp)
            _chaos("flight_committed")
            os.rename(tmp, final)
            _fsync_dir(self.root)
            self._rotate_locked()
        with self._lock:
            self._dumps += 1
        obs_events.emit("flight_dumped", reason=reason,
                        bundle=os.path.basename(final))
        return final

    # -- readouts ------------------------------------------------------

    def list_bundles(self):
        """Committed bundle paths, oldest first (name-sorted — the
        stamp prefix makes that chronological)."""
        return list_bundles(self.root)

    def stats(self):
        with self._lock:
            return {"root": self.root, "keep": self.keep,
                    "cooldown_s": self.cooldown_s,
                    "dumps": self._dumps, "failures": self._failures,
                    "bundles": len(self.list_bundles())}


# ---------------------------------------------------------------------------
# process-default recorder (flag-configured) + module-level trigger
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_recorder = None
_configured = False
# providers registered before the recorder exists (or while disabled)
# are replayed onto every (re)configured recorder
_pending_providers = {}


def _flag(name, default):
    try:
        from ..flags import FLAGS
        return getattr(FLAGS, name)
    except Exception:
        return default


def configure(root=None, keep=None, cooldown_s=None):
    """(Re)build the process-default recorder from flags/overrides;
    ``root=''`` (the default flag value) disables it."""
    global _recorder, _configured
    with _lock:
        root = _flag("flight_dir", "") if root is None else root
        if not root:
            _recorder = None
        else:
            _recorder = FlightRecorder(
                root,
                keep=_flag("flight_keep", 8) if keep is None else keep,
                cooldown_s=_flag("flight_cooldown_s", 30.0)
                if cooldown_s is None else cooldown_s)
            for name, fn in _pending_providers.items():
                _recorder.add_provider(name, fn)
        _configured = True
    return _recorder


def get_recorder():
    """The process-default recorder, or None while disabled."""
    global _recorder, _configured
    if not _configured:
        configure()
    return _recorder


def add_provider(name, fn):
    """Register a snapshot provider on the default recorder — kept
    across reconfiguration, harmless while the recorder is disabled."""
    with _lock:
        _pending_providers[str(name)] = fn
    rec = get_recorder()
    if rec is not None:
        rec.add_provider(name, fn)


def remove_provider(name):
    with _lock:
        _pending_providers.pop(str(name), None)
    rec = get_recorder()
    if rec is not None:
        rec.remove_provider(name)


def trigger(reason, force=False, **context):
    """Module-level trigger into the default recorder.  The one-line
    call sites (executor watchdog, sentinel, SLO monitor, batcher
    thread guards, the `flight` RPC) must stay exception-free and
    zero-cost while disabled."""
    try:
        rec = get_recorder()
        if rec is None:
            return None
        return rec.trigger(reason, force=force, **context)
    except Exception:
        return None


# ---------------------------------------------------------------------------
# bundle inspection (tools/flight_inspect.py rides these)
# ---------------------------------------------------------------------------

def list_bundles(root):
    if not os.path.isdir(root):
        return []
    out = [os.path.join(root, name) for name in os.listdir(root)
           if name.startswith(_BUNDLE_PREFIX)
           and os.path.isdir(os.path.join(root, name))
           and os.path.exists(os.path.join(root, name, MANIFEST_NAME))]
    return sorted(out)


def read_manifest(bundle):
    with open(os.path.join(bundle, MANIFEST_NAME)) as f:
        return json.load(f)


def validate_bundle(bundle):
    """Deep-validate one committed bundle; returns a list of problem
    strings (empty == valid).  Checks: manifest parses and carries the
    known schema, every listed file exists with matching size + CRC32,
    the required files are present, and every ``*.jsonl``/``*.json``
    payload parses."""
    problems = []
    try:
        manifest = read_manifest(bundle)
    except (OSError, ValueError) as e:
        return ["manifest unreadable: %s: %s" % (type(e).__name__, e)]
    if manifest.get("schema") != SCHEMA_VERSION:
        problems.append("unknown schema %r" % (manifest.get("schema"),))
    if not manifest.get("reason"):
        problems.append("manifest missing reason")
    files = manifest.get("files") or {}
    for name in REQUIRED_FILES:
        if name not in files:
            problems.append("required file %s missing from manifest"
                            % name)
    for name, meta in sorted(files.items()):
        path = os.path.join(bundle, name)
        if not os.path.exists(path):
            problems.append("%s listed but missing on disk" % name)
            continue
        with open(path, "rb") as f:
            data = f.read()
        if len(data) != meta.get("bytes"):
            problems.append("%s size %d != manifest %s"
                            % (name, len(data), meta.get("bytes")))
        crc = binascii.crc32(data) & 0xFFFFFFFF
        if crc != meta.get("crc32"):
            problems.append("%s crc32 %d != manifest %s (corrupt)"
                            % (name, crc, meta.get("crc32")))
            continue
        if name.endswith(".jsonl"):
            for i, line in enumerate(data.splitlines()):
                if not line.strip():
                    continue
                try:
                    json.loads(line)
                except ValueError:
                    problems.append("%s line %d is not JSON"
                                    % (name, i + 1))
                    break
        elif name.endswith(".json"):
            try:
                json.loads(data.decode("utf-8"))
            except ValueError:
                problems.append("%s is not JSON" % name)
    # files on disk the manifest never heard of (a torn commit can't
    # produce this — the rename is atomic — but a tamper can)
    for name in sorted(os.listdir(bundle)):
        if name != MANIFEST_NAME and name not in files:
            problems.append("unlisted file %s in bundle" % name)
    if "threads.txt" in files and files["threads.txt"].get("bytes", 0) \
            < 10:
        problems.append("threads.txt suspiciously empty")
    return problems
