"""Structured event log: append-only JSONL of discrete lifecycle events.

Spans answer "where did the time go"; this log answers "what happened":
hot-swap flips, compile-cache hit/miss deltas, sentinel skips and
rollbacks, admission sheds (with the priority class dropped), watchdog
fires, checkpoint commits.  Each record is one JSON object per line with
a wall-clock ``ts``, a ``kind``, and whatever ids the emitter had —
``trace_id`` for serving events, ``step`` for training events — so logs,
metrics, and traces cross-reference (OBSERVABILITY.md documents the
schema).

Discipline mirrors the tracing ring's: emitting NEVER raises and never
blocks the hot path on durability.  A bounded in-memory ring always
records (``recent_events``); the file sink is opt-in via
``FLAGS.event_log`` and plain buffered appends.  Rotation follows the
checkpoint vault's commit discipline: when the file passes
``FLAGS.event_log_max_kb`` it is fsynced and atomically renamed to
``<path>.1`` (one rotated generation kept), with the vault's chaos hook
fired at ``obs_rotated`` between the fsync and the rename — the
kill-mid-rotation scenario in tools/chaos.py (--scenario trace-overflow)
proves a crash there leaves the old log intact and the emitter alive.
"""

import collections
import json
import os
import threading
import time
import warnings

__all__ = ["EventLog", "emit", "recent_events", "configure", "get_log",
           "events_total", "stats"]

_lock = threading.Lock()
_log = None          # the process-default EventLog (lazy, flag-config'd)
_configured = False


class EventLog(object):
    """One event sink: bounded memory ring + optional JSONL file."""

    def __init__(self, path=None, max_bytes=1 << 20, ring=1024):
        self.path = path or None
        self.max_bytes = int(max_bytes)
        self._mem = collections.deque(maxlen=max(int(ring), 1))
        self._total = 0
        self._dropped = 0     # ring-overflow evictions (oldest-first)
        self._rotations = 0   # committed file rotations
        self._lock = threading.Lock()
        self._f = None
        self._size = 0
        self._sink_dead = False

    # -- file sink ----------------------------------------------------

    def _open_locked(self):
        # caller holds self._lock (emit / _rotate_locked)
        if self._f is None:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._f = open(self.path, "ab")
            self._size = self._f.tell()
        return self._f

    def _rotate_locked(self):
        """Vault-discipline rotation: flush+fsync the full file, fire
        the chaos point, then one atomic rename to ``<path>.1`` (the
        previous generation is dropped).  A crash between fsync and
        rename leaves the just-synced file in place — nothing is ever
        truncated in place."""
        from ..fluid.checkpoint import _chaos, _fsync_dir
        f = self._f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        self._f = None
        _chaos("obs_rotated")
        os.replace(self.path, self.path + ".1")
        _fsync_dir(os.path.dirname(self.path) or ".")
        self._size = 0
        self._rotations += 1

    # -- emit ---------------------------------------------------------

    def emit(self, kind, **fields):
        """Record one event.  Never raises; file-sink failures warn once
        and drop to memory-only."""
        rec = {"ts": round(time.time(), 6), "kind": str(kind)}
        for k, v in fields.items():
            if v is None:
                continue
            rec[k] = v if isinstance(v, (str, int, float, bool)) \
                else str(v)
        if len(self._mem) == self._mem.maxlen:
            self._dropped += 1  # GIL-atomic bump, same as the append
        self._mem.append(rec)
        self._total += 1
        if not self.path or self._sink_dead:
            return rec
        try:
            line = (json.dumps(rec, sort_keys=True) + "\n").encode()
            with self._lock:
                f = self._open_locked()
                f.write(line)
                # flush (no fsync) per record: lifecycle events are
                # low-rate and an operator tailing the file must see
                # them live; durability is the rotation's job
                f.flush()
                self._size += len(line)
                if self._size >= self.max_bytes:
                    self._rotate_locked()
        except Exception as e:
            self._sink_dead = True
            warnings.warn("obs event log sink %r failed (%s: %s) — "
                          "continuing memory-only"
                          % (self.path, type(e).__name__, e))
        return rec

    def flush(self):
        with self._lock:
            if self._f is not None:
                try:
                    self._f.flush()
                except Exception:
                    pass

    def close(self):
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except Exception:
                    pass
                self._f = None

    def recent(self, n=None, kind=None):
        evs = list(self._mem)
        if kind:
            evs = [e for e in evs if e.get("kind") == kind]
        if n is not None and len(evs) > n:
            evs = evs[-int(n):]
        return evs

    @property
    def total(self):
        return self._total

    def stats(self):
        """Ring + sink health (the metrics surface's first-class
        event-log families, OBSERVABILITY.md)."""
        return {"events_total": self._total,
                "buffered": len(self._mem),
                "dropped": self._dropped,
                "rotations": self._rotations,
                "sink": ("none" if not self.path
                         else "dead" if self._sink_dead else "ok"),
                "sink_dead": bool(self.path and self._sink_dead)}


# ---------------------------------------------------------------------------
# process-default log (flag-configured)
# ---------------------------------------------------------------------------

def get_log():
    global _log, _configured
    if _log is None or not _configured:
        with _lock:
            if _log is None or not _configured:
                path, max_kb = None, 1024
                try:
                    from ..flags import FLAGS
                    path = FLAGS.event_log or None
                    max_kb = FLAGS.event_log_max_kb
                except Exception:
                    pass
                _log = EventLog(path=path, max_bytes=max_kb * 1024)
                _configured = True
    return _log


def _flag(name, default):
    try:
        from ..flags import FLAGS
        return getattr(FLAGS, name)
    except Exception:
        return default


def configure(path=None, max_kb=None):
    """Swap the process-default sink (flags on_change routes here).
    The memory ring starts fresh; the old file handle is closed."""
    global _log, _configured
    with _lock:
        if _log is not None:
            _log.close()
        _log = EventLog(
            path=(_flag("event_log", "") if path is None else path)
            or None,
            max_bytes=(_flag("event_log_max_kb", 1024) if max_kb is None
                       else max_kb) * 1024)
        _configured = True
    return _log


def emit(kind, **fields):
    """Module-level emit into the process-default log.  The one-line
    call sites sprinkle through serving and training; it must stay
    exception-free whatever state the sink is in."""
    try:
        return get_log().emit(kind, **fields)
    except Exception:
        return None


def recent_events(n=None, kind=None):
    return get_log().recent(n=n, kind=kind)


def events_total():
    return get_log().total


def stats():
    """Health of the process-default event log."""
    return get_log().stats()
