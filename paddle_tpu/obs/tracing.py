"""Span tracing over a fixed-size ring buffer (OBSERVABILITY.md).

The stack has five performance-critical async layers (reader prefetch ->
dispatch pipeline -> serving lanes -> micro-batcher -> compile cache) and
until now no way to say where one request's or one train step's time
went.  This module is the shared answer: a thread-safe ``Span`` record +
``trace()`` context manager writing completed spans into a bounded ring
(``FLAGS.trace_buffer_events``), cheap enough to leave on in production
(<3% on the bench smoke lanes — BENCH_r09.json pins the delta).

Design constraints, in order:

* the hot path NEVER blocks and NEVER raises: span append is one
  ``deque.append`` on a maxlen deque (GIL-atomic; old spans fall off the
  far end — overflow is silent by design and counted);
* disabled tracing is one module-global bool test: ``trace()`` returns a
  shared no-op context manager, no allocation;
* spans are plain data (name, trace_id, kind, wall start, duration,
  small attr dict), wire-encodable as dicts so the serving ``trace`` RPC
  verb can ship them to ``tools/trace_top.py`` unchanged, and
  chrome-trace convertible so ``profiler.export_chrome_tracing`` can
  merge them with the jax device timeline.

Trace ids: every serving request gets one minted at admission (or
carries one in on the wire ``"trace_id"`` field, echoed in the reply);
training spans carry a ``step`` attr instead.  A trace id groups the
request's stage spans (queue_wait / coalesce / lane_wait / compute /
scatter) into the tree ``trace_top`` prints; the stages are stamped from
contiguous timestamps, so they sum to the root span by construction.
"""

import collections
import contextlib
import random
import threading
import time

__all__ = ["Span", "trace", "span_begin", "new_trace_id", "enabled",
           "set_enabled", "configure", "recent_spans", "spans_for_trace",
           "clear", "stats", "add_span", "chrome_events"]

_lock = threading.Lock()           # guards reconfiguration only
_ring = collections.deque(maxlen=4096)
_enabled = True
_spans_total = 0                   # lifetime appends (overflow = total - len)
_rng = random.Random()
_configured = False

# one listener hook: the MetricsRegistry aggregates train/serving span
# totals without the emitters knowing about metrics at all
_on_span = None


class Span(object):
    """One completed timed region.  ``ts`` is wall-clock epoch seconds
    (chrome-trace compatible); ``dur_ms`` the measured duration;
    ``attrs`` a SMALL dict of wire-encodable values (str/int/float)."""

    __slots__ = ("name", "kind", "trace_id", "ts", "dur_ms", "attrs",
                 "thread")

    def __init__(self, name, kind="", trace_id=None, ts=None, dur_ms=0.0,
                 attrs=None, thread=None):
        self.name = name
        self.kind = kind
        self.trace_id = trace_id
        self.ts = time.time() if ts is None else ts
        self.dur_ms = dur_ms
        self.attrs = attrs or {}
        self.thread = threading.get_ident() if thread is None else thread

    def to_dict(self):
        d = {"name": self.name, "kind": self.kind, "ts": self.ts,
             "dur_ms": round(self.dur_ms, 4)}
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
        if self.attrs:
            d["attrs"] = {str(k): (v if isinstance(v, (int, float, bool))
                                   else str(v))
                          for k, v in self.attrs.items()}
        return d

    def __repr__(self):
        return "Span(%r, %.2fms, trace=%s, %s)" % (
            self.name, self.dur_ms, self.trace_id, self.attrs)


def new_trace_id():
    """16 hex chars, random.  Cheap (no uuid machinery) and long enough
    that a collision inside one ring buffer's lifetime is negligible."""
    return "%016x" % _rng.getrandbits(64)


def _flag(name, default):
    """Read a flag, tolerating a half-initialized flag registry (the
    on_change hooks can fire while flags.py itself is importing)."""
    try:
        from ..flags import FLAGS
        return getattr(FLAGS, name)
    except Exception:
        return default


def _ensure_configured():
    """Lazy first-use sync with FLAGS (flags may be set before this
    module is ever imported; on_change hooks keep us in sync after)."""
    global _configured
    if _configured:
        return
    with _lock:
        if _configured:
            return
        _apply(_flag("trace", _enabled),
               _flag("trace_buffer_events", _ring.maxlen))
        _configured = True


def _apply(enabled_, capacity):
    global _enabled, _ring
    _enabled = bool(enabled_)
    capacity = max(int(capacity), 1)
    if capacity != _ring.maxlen:
        _ring = collections.deque(_ring, maxlen=capacity)


def configure(enabled=None, capacity=None):
    """Reconfigure the tracer (flags on_change hooks route here)."""
    global _configured
    with _lock:
        _apply(_flag("trace", _enabled) if enabled is None else enabled,
               _flag("trace_buffer_events", _ring.maxlen)
               if capacity is None else capacity)
        _configured = True


def enabled():
    _ensure_configured()
    return _enabled


def set_enabled(on):
    global _enabled, _configured
    _enabled = bool(on)
    _configured = True


def set_span_listener(fn):
    """Install the single span listener (MetricsRegistry aggregation);
    None removes it.  Listener exceptions are swallowed — telemetry must
    never take down the traffic it observes."""
    global _on_span
    _on_span = fn


def add_span(span):
    """Append one completed Span.  The hot-path primitive: instrumented
    code that stamps its own timestamps (the batcher's contiguous stage
    spans) builds Spans directly and lands them here."""
    global _spans_total
    _ring.append(span)
    _spans_total += 1
    if _on_span is not None:
        try:
            _on_span(span)
        except Exception:
            pass


class _NullCtx(object):
    """Shared no-op context manager: the disabled-tracing fast path."""
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


_NULL = _NullCtx()


class _LiveSpan(object):
    """Context manager for one in-progress span; ``__exit__`` stamps the
    duration and lands it in the ring.  An exception inside the region
    still records the span (with ``error`` attr) and propagates."""

    __slots__ = ("_span", "_t0")

    def __init__(self, span):
        self._span = span
        self._t0 = time.perf_counter()

    def __enter__(self):
        return self._span

    def __exit__(self, exc_type, exc, tb):
        s = self._span
        s.dur_ms = (time.perf_counter() - self._t0) * 1e3
        if exc_type is not None:
            s.attrs = dict(s.attrs, error=exc_type.__name__)
        add_span(s)
        return False


def trace(name, kind="", trace_id=None, **attrs):
    """``with trace("serving/compute", trace_id=tid, step=3): ...`` —
    the span API everything instruments through.  Returns a no-op
    context when tracing is disabled (one bool test, no allocation)."""
    _ensure_configured()
    if not _enabled:
        return _NULL
    return _LiveSpan(Span(name, kind=kind, trace_id=trace_id,
                          attrs=attrs))


def span_begin():
    """Monotonic stamp helper for code that builds contiguous stage
    spans by hand (see ``add_span``)."""
    return time.perf_counter()


def clear():
    global _spans_total
    with _lock:
        _ring.clear()
        _spans_total = 0


def stats():
    """Ring statistics for the metrics surface."""
    return {"enabled": _enabled, "capacity": _ring.maxlen or 0,
            "buffered": len(_ring), "spans_total": _spans_total,
            "dropped": max(_spans_total - len(_ring), 0)}


def recent_spans(limit=None, kind=None, name=None):
    """Most-recent-last list of span dicts (wire-encodable).  Snapshot
    is GIL-consistent; concurrent appends during iteration are fine."""
    spans = list(_ring)
    if kind:
        spans = [s for s in spans if s.kind == kind]
    if name:
        spans = [s for s in spans if s.name == name]
    if limit is not None and len(spans) > limit:
        spans = spans[-int(limit):]
    return [s.to_dict() for s in spans]


def spans_for_trace(trace_id):
    """Every buffered span of one trace, oldest first — the span tree a
    reply-visible trace_id resolves to."""
    return [s.to_dict() for s in list(_ring) if s.trace_id == trace_id]


def chrome_events(spans=None, pid=None):
    """Convert span dicts to chrome-trace ``X`` events so they merge
    into the jax device timeline (profiler.export_chrome_tracing).
    One synthetic thread row per span kind (serving / train / obs)."""
    import os
    if spans is None:
        spans = recent_spans()
    pid = os.getpid() if pid is None else pid
    out = [{"ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": "paddle_tpu obs spans"}}]
    tids = {}
    for s in spans:
        kind = s.get("kind") or "obs"
        tid = tids.get(kind)
        if tid is None:
            tid = tids[kind] = len(tids) + 1
            out.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name",
                        "args": {"name": "obs:%s" % kind}})
        args = dict(s.get("attrs") or {})
        if s.get("trace_id"):
            args["trace_id"] = s["trace_id"]
        out.append({"ph": "X", "pid": pid, "tid": tid,
                    "name": s["name"], "ts": s["ts"] * 1e6,
                    "dur": s["dur_ms"] * 1e3, "args": args})
    return out
