"""paddle_tpu.obs — end-to-end tracing + unified telemetry core.

The observability seam shared by training and serving
(OBSERVABILITY.md):

* ``tracing`` — ``Span``/``trace()`` over a fixed ring buffer
  (``FLAGS.trace_buffer_events``); serving requests carry a ``trace_id``
  minted at admission, training spans carry ``step`` attrs;
* ``events`` — append-only structured JSONL event log (hot-swap flips,
  compile-cache deltas, sentinel skips/rollbacks, sheds, watchdog
  fires) with vault-discipline rotation;
* ``registry`` — ``MetricsRegistry``: one Prometheus-style exposition
  absorbing ServingMetrics, training counters and span aggregates,
  served by the ``metrics`` RPC verb and ``tools/metrics_dump.py``;
* ``slo`` — declared per-model SLOs with multi-window burn-rate
  evaluation and the ok/degraded/breach health state machine the
  ``health`` RPC verb renders;
* ``flightrec`` — the flight recorder: on trigger (watchdog, sentinel
  give-up, SLO breach, thread death, manual RPC) dumps spans + events
  + metrics timeline + all-thread stacks + flags as one atomically
  committed post-mortem bundle (``FLAGS.flight_dir``).

Importing this package installs the default registry as the span
ring's listener, so per-stage time aggregates accumulate from the very
first instrumented span — training-only processes included (the
registry itself is import-light; serving classes load lazily).
"""

from . import events, tracing  # noqa: F401
from .tracing import (Span, new_trace_id, recent_spans,  # noqa: F401
                      spans_for_trace, trace)
from .events import emit, recent_events  # noqa: F401
from . import registry  # noqa: F401
from .registry import MetricsRegistry  # noqa: F401
from .registry import default as default_registry  # noqa: F401
from . import flightrec, slo  # noqa: F401
from .flightrec import FlightRecorder  # noqa: F401
from .slo import SLO, SLOMonitor  # noqa: F401

__all__ = ["tracing", "events", "registry", "trace", "Span",
           "new_trace_id", "recent_spans", "spans_for_trace", "emit",
           "recent_events", "MetricsRegistry", "default_registry",
           "slo", "flightrec", "SLO", "SLOMonitor", "FlightRecorder"]

# wire the span listener now: aggregates must not depend on who asks
# for the registry first (a training run before any server boot still
# feeds paddle_tpu_span_ms_total)
default_registry()
