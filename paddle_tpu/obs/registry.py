"""Unified metrics surface: one registry across training + serving.

Before this module, telemetry was fragmented per subsystem: serving kept
``ServingMetrics`` counters/histograms behind the ``stats`` RPC,
training kept an unsynchronized profiler table, and nothing exported
either in a form a scraper could ingest.  ``MetricsRegistry`` absorbs
them all into ONE exposition:

* its own counters / gauges / histograms (training-side code registers
  here directly);
* every attached ``ServingMetrics`` (the server attaches at start,
  detaches at shutdown) — their ``snapshot()`` dicts are flattened into
  labeled metric families at render time, so there is no double
  bookkeeping and a hot swap keeps its no-counter-reset semantics;
* span aggregates: the registry listens to the tracing ring
  (tracing.set_span_listener) and keeps per-(kind, name) call counts and
  total milliseconds — the per-step prefetch_wait / dispatch / drain /
  ckpt breakdown and the per-stage serving totals fall out of the spans
  already being recorded, no extra instrumentation;
* event-log totals, compile-cache store counters, and the tracing
  ring's own health (buffered/dropped).

``prometheus_text()`` renders the whole thing Prometheus-style
(``# TYPE`` headers, ``name{label="v"} value`` samples) — served by the
new ``metrics`` RPC verb on the inference server and by
``tools/metrics_dump.py``.
"""

import threading

__all__ = ["MetricsRegistry", "default"]

_PREFIX = "paddle_tpu_"

# ServingMetrics snapshot ints rendered as labeled counters
_SERVING_COUNTERS = ("requests", "responses", "errors", "shed",
                     "deadline_expired", "dispatches",
                     # generation counters (absent for one-shot models)
                     "streams", "prefills", "decode_tokens",
                     "decode_steps",
                     # fused multi-step decode (SERVING.md): dispatches
                     # issued — tokens/dispatches is the amortization
                     "decode_dispatches",
                     # speculative decoding (absent without a draft)
                     "spec_rounds", "draft_tokens", "accepted_tokens",
                     "spec_degraded")
# ... and floats rendered as labeled gauges
_SERVING_GAUGES = ("qps_recent", "qps_lifetime", "batch_fill",
                   "bucket_fill_ratio", "queue_depth",
                   # continuous-batching decode gauges (SERVING.md)
                   "tokens_per_sec", "slot_occupancy",
                   # measured KV slot-table bytes across lanes — reads
                   # ~0.25x under kv_cache_dtype=int8 (QUANTIZE.md
                   # "Quantized KV cache")
                   "kv_cache_bytes",
                   # lifetime draft accept fraction (SERVING.md
                   # speculative decoding — the speedup dial)
                   "spec_accept_rate")
_SERVING_HISTS = ("latency_ms", "queue_wait_ms", "ttft_ms",
                  "tokens_per_dispatch")
_QUANTILES = ("p50", "p95", "p99")


def _esc(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _labels(d):
    if not d:
        return ""
    return "{%s}" % ",".join('%s="%s"' % (k, _esc(v))
                             for k, v in sorted(d.items()))


def _num(v):
    if isinstance(v, float):
        return repr(round(v, 6))
    return str(v)


class MetricsRegistry(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}    # (name, labels-tuple) -> Counter
        self._gauges = {}      # name -> callable() -> value|dict|None
        self._hists = {}       # (name, labels-tuple) -> ReservoirHistogram
        self._serving = []     # attached ServingMetrics
        self._slo = []         # attached SLOMonitors (obs/slo.py)
        self._fleet = []       # attached FleetControllers (serving/fleet)
        self._federation = []  # attached FrontendServers (federation/)
        self._span_agg = {}    # (kind, name) -> [count, total_ms]

    # -- primitive instruments ---------------------------------------

    @staticmethod
    def _key(name, labels):
        return (name, tuple(sorted((labels or {}).items())))

    def counter(self, name, labels=None):
        # Counter/ReservoirHistogram live in serving.metrics but are
        # stdlib-only; importing them lazily keeps `import
        # paddle_tpu.obs` (and therefore every instrumented training
        # module) from dragging the serving package in
        from ..serving.metrics import Counter
        key = self._key(name, labels)
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                c = self._counters[key] = Counter()
            return c

    def gauge(self, name, fn):
        """Register a live-read gauge: ``fn()`` -> number, or a dict of
        labels-tuple-free {label_value: number} rendered with one
        ``key`` label, or None to skip."""
        with self._lock:
            self._gauges[name] = fn

    def histogram(self, name, labels=None):
        from ..serving.metrics import ReservoirHistogram
        key = self._key(name, labels)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = ReservoirHistogram()
            return h

    # -- absorbed sources --------------------------------------------

    def attach_serving(self, serving_metrics):
        with self._lock:
            if serving_metrics not in self._serving:
                self._serving.append(serving_metrics)

    def detach_serving(self, serving_metrics):
        with self._lock:
            if serving_metrics in self._serving:
                self._serving.remove(serving_metrics)

    def attach_slo(self, monitor):
        """Absorb one SLOMonitor: its burn-rate / compliance / state
        gauges render as first-class families (the fleet controller's
        health scrape — OBSERVABILITY.md "SLOs & burn rates")."""
        with self._lock:
            if monitor not in self._slo:
                self._slo.append(monitor)

    def detach_slo(self, monitor):
        with self._lock:
            if monitor in self._slo:
                self._slo.remove(monitor)

    def attach_fleet(self, controller):
        """Absorb one FleetController (serving/fleet.py): its
        fleet_replicas / fleet_state / fault_in_ms gauges render as
        first-class families — the actuation-side twin of the slo_*
        judgment families."""
        with self._lock:
            if controller not in self._fleet:
                self._fleet.append(controller)

    def detach_fleet(self, controller):
        with self._lock:
            if controller in self._fleet:
                self._fleet.remove(controller)

    def attach_federation(self, frontend):
        """Absorb one federation FrontendServer (federation/frontend):
        membership-by-state, placement/spillover/shed counters, and —
        when the global tier runs — the global_fleet_* families, all
        via the same [(metric, labels, value, type)] export rows."""
        with self._lock:
            if frontend not in self._federation:
                self._federation.append(frontend)

    def detach_federation(self, frontend):
        with self._lock:
            if frontend in self._federation:
                self._federation.remove(frontend)

    def note_span(self, span):
        """Tracing-ring listener: fold one completed span into the
        per-(kind, name) totals."""
        key = (span.kind, span.name)
        with self._lock:
            rec = self._span_agg.get(key)
            if rec is None:
                self._span_agg[key] = [1, span.dur_ms]
            else:
                rec[0] += 1
                rec[1] += span.dur_ms

    def span_totals(self, kind=None):
        """{(kind, name): {"count", "total_ms"}} — the per-stage time
        budget (trace_top's aggregate view reads this via metrics)."""
        with self._lock:
            return {k: {"count": v[0], "total_ms": round(v[1], 3)}
                    for k, v in self._span_agg.items()
                    if kind is None or k[0] == kind}

    # -- exposition ---------------------------------------------------

    @staticmethod
    def _model_labels(model_key, m, **extra):
        """Label set of one serving lane: the plain model name plus a
        ``precision`` label for non-fp32 lanes (the QUANTIZE.md A/B
        axis — an int8 lane keys as 'name@int8' in the snapshot but
        scrapes as model='name', precision='int8')."""
        labels = {"model": m.get("model", model_key)}
        prec = m.get("precision")
        if prec and prec != "fp32":
            labels["precision"] = prec
        labels.update(extra)
        return labels

    def _render_serving(self, lines):
        snaps = []
        with self._lock:
            serving = list(self._serving)
        for sm in serving:
            try:
                snaps.append(sm.snapshot())
            except Exception:
                continue
        for field in _SERVING_COUNTERS:
            mname = _PREFIX + "serving_%s_total" % field
            samples = []
            for snap in snaps:
                for model, m in sorted(snap.get("models", {}).items()):
                    if field in m:
                        samples.append(
                            (mname, self._model_labels(model, m),
                             m[field]))
            _family(lines, mname, "counter", samples)
        for field in _SERVING_GAUGES:
            mname = _PREFIX + "serving_" + field
            samples = []
            for snap in snaps:
                for model, m in sorted(snap.get("models", {}).items()):
                    if field in m:
                        samples.append(
                            (mname, self._model_labels(model, m),
                             m[field]))
            _family(lines, mname, "gauge", samples)
        for hist_field in _SERVING_HISTS:
            mname = _PREFIX + "serving_" + hist_field
            samples = []
            for snap in snaps:
                for model, m in sorted(snap.get("models", {}).items()):
                    if hist_field not in m:
                        continue  # e.g. ttft_ms on a one-shot model
                    h = m.get(hist_field) or {}
                    for q in _QUANTILES:
                        if h.get(q) is not None:
                            samples.append(
                                (mname,
                                 self._model_labels(model, m,
                                                    quantile=q),
                                 h[q]))
                    samples.append((mname + "_count",
                                    self._model_labels(model, m),
                                    h.get("count", 0)))
            _family(lines, mname, "summary", samples)
        # priority-shed + per-model compile-cache attribution
        samples = []
        for snap in snaps:
            for model, m in sorted(snap.get("models", {}).items()):
                for pri, n in sorted(
                        (m.get("shed_by_priority") or {}).items()):
                    samples.append((_PREFIX + "serving_shed_by_priority_"
                                    "total",
                                    self._model_labels(model, m,
                                                       priority=pri),
                                    n))
        _family(lines, _PREFIX + "serving_shed_by_priority_total",
                "counter", samples)
        # static resource estimates (ANALYSIS.md): the placement-by-
        # cost gauges the fleet controller scrapes — per-replica peak
        # HBM estimate and one-step FLOPs, set by the admission check
        for field, mname in (("est_peak_mb",
                              _PREFIX + "model_est_peak_mb"),
                             ("est_flops",
                              _PREFIX + "model_est_flops")):
            samples = []
            for snap in snaps:
                for model, m in sorted(snap.get("models", {}).items()):
                    if field in m:
                        samples.append(
                            (mname, self._model_labels(model, m),
                             m[field]))
            _family(lines, mname, "gauge", samples)
        # mesh shape per replica lane (SERVING.md "Mesh replicas"):
        # member-device count of each lane — 1 for a plain single-chip
        # replica; a dead mesh lane keeps exporting so a scraper can
        # still see the shape it lost
        samples = []
        for snap in snaps:
            for model, m in sorted(snap.get("models", {}).items()):
                for row in m.get("replicas") or []:
                    samples.append(
                        (_PREFIX + "replica_mesh_size",
                         self._model_labels(
                             model, m,
                             replica=str(row.get("replica", "")),
                             device=str(row.get("device", ""))),
                         int(row.get("mesh", 1) or 1)))
        _family(lines, _PREFIX + "replica_mesh_size", "gauge", samples)
        samples = []
        for snap in snaps:
            for model, m in sorted(snap.get("models", {}).items()):
                cc = m.get("compile_cache") or {}
                for f in ("hits", "misses"):
                    samples.append((_PREFIX + "serving_compile_cache_%s_"
                                    "total" % f,
                                    self._model_labels(model, m),
                                    cc.get(f, 0)))
        _family(lines, _PREFIX + "serving_compile_cache_total", "counter",
                samples)

    def _render_slo(self, lines):
        """Burn-rate / compliance / state families from every attached
        SLOMonitor (obs/slo.py export rows), and the fleet families
        (fleet_replicas / fleet_state / fault_in_ms) from every
        attached FleetController — both speak the same
        [(metric, labels, value, type)] export row shape."""
        with self._lock:
            monitors = (list(self._slo) + list(self._fleet)
                        + list(self._federation))
        by_name = {}
        for mon in monitors:
            try:
                rows = mon.export()
            except Exception:
                continue
            for metric, labels, value, mtype in rows:
                by_name.setdefault((metric, mtype), []).append(
                    (_PREFIX + metric, labels, value))
        for (metric, mtype), samples in sorted(by_name.items()):
            _family(lines, _PREFIX + metric, mtype, samples)

    def prometheus_text(self):
        """The one metrics surface, Prometheus text exposition."""
        lines = []
        # span aggregates: training per-stage breakdown + serving stages
        with self._lock:
            agg = sorted((k, list(v)) for k, v in self._span_agg.items())
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._hists.items())
        _family(lines, _PREFIX + "span_ms_total", "counter",
                [(_PREFIX + "span_ms_total",
                  {"kind": k or "none", "span": n}, round(v[1], 3))
                 for (k, n), v in agg])
        _family(lines, _PREFIX + "span_count_total", "counter",
                [(_PREFIX + "span_count_total",
                  {"kind": k or "none", "span": n}, v[0])
                 for (k, n), v in agg])
        for (name, labels), c in counters:
            _family(lines, _PREFIX + name, "counter",
                    [(_PREFIX + name, dict(labels), c.value)])
        for name, fn in gauges:
            try:
                v = fn()
            except Exception:
                continue
            if v is None:
                continue
            if isinstance(v, dict):
                _family(lines, _PREFIX + name, "gauge",
                        [(_PREFIX + name, {"key": k}, x)
                         for k, x in sorted(v.items())])
            else:
                _family(lines, _PREFIX + name, "gauge",
                        [(_PREFIX + name, {}, v)])
        for (name, labels), h in hists:
            s = h.summary()
            samples = [(_PREFIX + name + "_count", dict(labels),
                        s.get("count", 0))]
            for q in _QUANTILES:
                if s.get(q) is not None:
                    samples.append((_PREFIX + name,
                                    dict(labels, quantile=q), s[q]))
            _family(lines, _PREFIX + name, "summary", samples)
        self._render_serving(lines)
        self._render_slo(lines)
        # subsystem health: tracing ring, event log, compile-cache store
        # — each a FIRST-CLASS family (span drops, event drops, sink
        # state) so a scraper can alert on telemetry loss directly
        from . import events, tracing
        ts = tracing.stats()
        _family(lines, _PREFIX + "trace_spans_total", "counter",
                [(_PREFIX + "trace_spans_total", {}, ts["spans_total"])])
        _family(lines, _PREFIX + "trace_buffered", "gauge",
                [(_PREFIX + "trace_buffered", {}, ts["buffered"])])
        _family(lines, _PREFIX + "trace_dropped_total", "counter",
                [(_PREFIX + "trace_dropped_total", {}, ts["dropped"])])
        es = events.stats()
        _family(lines, _PREFIX + "events_total", "counter",
                [(_PREFIX + "events_total", {}, es["events_total"])])
        _family(lines, _PREFIX + "events_buffered", "gauge",
                [(_PREFIX + "events_buffered", {}, es["buffered"])])
        _family(lines, _PREFIX + "events_dropped_total", "counter",
                [(_PREFIX + "events_dropped_total", {}, es["dropped"])])
        _family(lines, _PREFIX + "events_rotations_total", "counter",
                [(_PREFIX + "events_rotations_total", {},
                  es["rotations"])])
        # 1 = a configured file sink has died (memory-only fallback);
        # 0 covers both "healthy sink" and "no sink configured"
        _family(lines, _PREFIX + "events_sink_dead", "gauge",
                [(_PREFIX + "events_sink_dead", {},
                  int(es["sink_dead"]))])
        try:
            from . import flightrec
            rec = flightrec.get_recorder()
            if rec is not None:
                fs = rec.stats()
                _family(lines, _PREFIX + "flight_dumps_total", "counter",
                        [(_PREFIX + "flight_dumps_total", {},
                          fs["dumps"])])
                _family(lines, _PREFIX + "flight_bundles", "gauge",
                        [(_PREFIX + "flight_bundles", {},
                          fs["bundles"])])
        except Exception:
            pass
        try:
            from .. import compile_cache
            cc = compile_cache.stats()
            for k, v in sorted(cc.items()):
                if isinstance(v, (int, float)):
                    n = _PREFIX + "compile_cache_%s" % k
                    _family(lines, n, "counter", [(n, {}, v)])
        except Exception:
            pass
        return "\n".join(lines) + "\n"


def _family(lines, name, mtype, samples):
    if not samples:
        return
    lines.append("# TYPE %s %s" % (name, mtype))
    for sname, labels, value in samples:
        lines.append("%s%s %s" % (sname, _labels(labels), _num(value)))


_default = None
_default_lock = threading.Lock()


def default():
    """The process-wide registry; first use wires it as the tracing
    ring's span listener so train/serving stage totals accumulate."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                reg = MetricsRegistry()
                from . import tracing
                tracing.set_span_listener(reg.note_span)
                _default = reg
    return _default
