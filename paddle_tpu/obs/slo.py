"""SLO engine: declared objectives, burn-rate evaluation, health states.

PR 7 gave the stack *measurement* (spans, events, one metrics surface);
this module adds *judgment*: a declared SLO per served model, a
background monitor thread that samples the live ``ServingMetrics``
counters at a fixed interval into a bounded time-series ring, and a
Google-SRE-style multi-window burn-rate evaluation driving a per-model
health state machine (``ok`` -> ``degraded`` -> ``breach``, with
hysteresis on both edges).  Transitions emit ``slo_breach`` /
``slo_recovered`` structured events, arm the flight recorder
(obs/flightrec.py) on breach, and export burn-rate / compliance /
state gauges on the Prometheus surface — exactly the signals the
SLO-driven fleet controller (ROADMAP) will act on, and the `health`
RPC verb on the inference server renders.

Burn-rate model (OBSERVABILITY.md "SLOs & burn rates"):

* every objective reduces each sampling interval to a **bad fraction**
  in [0, 1]:
    - ``error_rate`` / ``shed_rate``: the measured rate over the
      interval's counter deltas (bad requests / requests);
    - ``p95_ms`` / ``ttft_p95_ms``: an indicator — 1.0 when the
      interval's windowed p95 exceeded the target, else 0.0;
    - ``spec_accept``: 1.0 when the interval's draft accept rate fell
      below the floor (only when drafts were offered);
* ``burn(window) = mean(bad fraction over the window) / budget`` where
  the budget is the declared rate itself for rate objectives and
  ``SLO.budget`` (the allowed fraction of violating intervals) for
  threshold objectives.  burn == 1.0 means the error budget is being
  spent exactly at the sustainable rate; burn >> 1 means it will be
  exhausted early;
* two windows: a FAST window (default 6 samples) evaluated against
  ``fast_burn`` (default 10.0) catches hard outages within a couple of
  intervals; a SLOW window (default 30 samples, only evaluated once
  full) against ``slow_burn`` (default 2.0) catches low-grade burns a
  fast window can never see.  Either rule "trips" the evaluation; the
  slow rule is additionally gated on the fast window ALSO burning at
  >= ``slow_burn`` (Google's paired-window condition — stale bad
  intervals inside the slow window must not re-trip a lane that
  already recovered).  Note threshold objectives cap their burn at
  ``1/budget`` (an all-bad window), so ``fast_burn`` must sit at or
  under that to be reachable.

State machine with hysteresis: ``breach_evals`` consecutive tripped
evaluations escalate (first trip = ``degraded``, sustained =
``breach``); ``recover_evals`` consecutive clean evaluations are
required to return to ``ok`` (one ``slo_recovered`` event per
recovery, never a flap storm).

Nothing here touches the hot path: the monitor thread reads counters
the traffic already maintains, and a declared-SLO-free model is still
sampled (its timeline feeds the flight recorder) but never evaluated.
"""

import collections
import threading
import time

__all__ = ["SLO", "SLOMonitor", "parse_slo_spec",
           "STATE_OK", "STATE_DEGRADED", "STATE_BREACH"]

STATE_OK = "ok"
STATE_DEGRADED = "degraded"
STATE_BREACH = "breach"
_STATE_CODE = {STATE_OK: 0, STATE_DEGRADED: 1, STATE_BREACH: 2}

# objective keys a spec / declare() may set (targets)
_RATE_OBJECTIVES = ("error_rate", "shed_rate")
_THRESHOLD_OBJECTIVES = ("p95_ms", "ttft_p95_ms", "spec_accept")
OBJECTIVES = _RATE_OBJECTIVES + _THRESHOLD_OBJECTIVES
# tunables riding the same spec syntax
_TUNABLES = ("budget", "fast_window", "slow_window", "fast_burn",
             "slow_burn", "breach_evals", "recover_evals")


class SLO(object):
    """One model's declared objectives + burn/hysteresis tuning.
    Unset objectives (None) are not evaluated."""

    __slots__ = ("error_rate", "shed_rate", "p95_ms", "ttft_p95_ms",
                 "spec_accept", "budget", "fast_window", "slow_window",
                 "fast_burn", "slow_burn", "breach_evals",
                 "recover_evals")

    def __init__(self, error_rate=None, shed_rate=None, p95_ms=None,
                 ttft_p95_ms=None, spec_accept=None, budget=0.1,
                 fast_window=6, slow_window=30, fast_burn=10.0,
                 slow_burn=2.0, breach_evals=2, recover_evals=3):
        self.error_rate = None if error_rate is None else float(error_rate)
        self.shed_rate = None if shed_rate is None else float(shed_rate)
        self.p95_ms = None if p95_ms is None else float(p95_ms)
        self.ttft_p95_ms = None if ttft_p95_ms is None \
            else float(ttft_p95_ms)
        self.spec_accept = None if spec_accept is None \
            else float(spec_accept)
        # the fraction of intervals a threshold objective may violate
        # before its budget burns at rate 1.0
        self.budget = max(float(budget), 1e-6)
        self.fast_window = max(int(fast_window), 2)
        self.slow_window = max(int(slow_window), self.fast_window + 1)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.breach_evals = max(int(breach_evals), 1)
        self.recover_evals = max(int(recover_evals), 1)

    def objectives(self):
        """The declared (objective, target) pairs."""
        return [(k, getattr(self, k)) for k in OBJECTIVES
                if getattr(self, k) is not None]

    def to_dict(self):
        d = {k: getattr(self, k) for k, _ in
             [(o, None) for o in OBJECTIVES]
             if getattr(self, k) is not None}
        d.update({k: getattr(self, k) for k in _TUNABLES})
        return d

    def __repr__(self):
        return "SLO(%s)" % ", ".join(
            "%s=%s" % (k, v) for k, v in sorted(self.to_dict().items()))


def parse_slo_spec(spec):
    """Parse ``FLAGS.serving_slo`` into {model_or_*: SLO}.

    Syntax: semicolon-separated declarations, each
    ``[model:]key=value,key=value,...``; a declaration with no model
    prefix (or the ``*`` prefix) is the default applied to every model
    without its own.  Keys: the objectives (p95_ms, ttft_p95_ms,
    error_rate, shed_rate, spec_accept) plus the tunables (budget,
    fast_window, slow_window, fast_burn, slow_burn, breach_evals,
    recover_evals).  Example::

        "p95_ms=250,error_rate=0.01;llm:ttft_p95_ms=400,spec_accept=0.5"
    """
    out = {}
    if not spec:
        return out
    for decl in str(spec).split(";"):
        decl = decl.strip()
        if not decl:
            continue
        model = "*"
        body = decl
        head, sep, rest = decl.partition(":")
        if sep and "=" not in head:
            model, body = (head.strip() or "*"), rest
        kwargs = {}
        for part in body.split(","):
            part = part.strip()
            if not part:
                continue
            key, eq, val = part.partition("=")
            key = key.strip()
            if not eq or key not in OBJECTIVES + _TUNABLES:
                raise ValueError(
                    "bad SLO spec entry %r (model %r) — keys are %s"
                    % (part, model, ", ".join(OBJECTIVES + _TUNABLES)))
            kwargs[key] = float(val)
        out[model] = SLO(**kwargs)
    return out


class _Sample(object):
    """One sampling instant of one model lane: cumulative counters plus
    the interval-windowed percentile reads.  ``ts`` is the wall-clock
    record stamp (timeline/bundle field); ``mono`` carries the
    interval math."""

    __slots__ = ("ts", "mono", "requests", "responses", "errors",
                 "shed", "deadline_expired", "p95_ms", "ttft_p95_ms",
                 "draft_tokens", "accepted_tokens", "bad")

    def to_dict(self):
        d = {"ts": self.ts, "requests": self.requests,
             "responses": self.responses, "errors": self.errors,
             "shed": self.shed,
             "deadline_expired": self.deadline_expired,
             "draft_tokens": self.draft_tokens,
             "accepted_tokens": self.accepted_tokens}
        if self.p95_ms is not None:
            d["p95_ms"] = round(self.p95_ms, 3)
        if self.ttft_p95_ms is not None:
            d["ttft_p95_ms"] = round(self.ttft_p95_ms, 3)
        if self.bad:
            d["bad"] = {k: round(v, 4) for k, v in self.bad.items()}
        return d


class SLOMonitor(object):
    """Samples one ``ServingMetrics`` registry on a fixed interval,
    keeps a bounded per-lane time-series ring, and evaluates declared
    SLOs into the ok/degraded/breach state machine.

    ``tick()`` is the whole evaluation pass and is public so tests (and
    synthetic-timeline drivers) can step the monitor without the
    background thread; ``start()`` runs it on a daemon thread every
    ``interval_s``."""

    def __init__(self, metrics, slos=None, interval_s=None,
                 timeline_samples=256, name="server"):
        from ..flags import FLAGS
        self.metrics = metrics
        self.name = str(name)
        self.interval_s = (float(FLAGS.slo_eval_interval_ms) / 1000.0
                           if interval_s is None else float(interval_s))
        self.interval_s = max(self.interval_s, 0.01)
        self._slos = dict(slos or {})      # model name (or '*') -> SLO
        self._timeline_cap = max(int(timeline_samples), 8)
        self._lock = threading.Lock()
        self._rings = {}     # lane key -> deque[_Sample]
        self._states = {}    # lane key -> state dict
        self._stop = threading.Event()
        self._thread = None
        self._ticks = 0

    @classmethod
    def from_flags(cls, metrics, name="server"):
        from ..flags import FLAGS
        return cls(metrics, slos=parse_slo_spec(FLAGS.serving_slo),
                   name=name)

    # -- declarations --------------------------------------------------

    def declare(self, model, slo=None, **kwargs):
        """Declare (or replace) one model's SLO; kwargs build one."""
        with self._lock:
            self._slos[str(model)] = slo if slo is not None \
                else SLO(**kwargs)

    def slo_for(self, lane_key):
        """Resolve the SLO of one metrics lane key ('m' or 'm@int8'):
        exact lane key > plain model name > '*' default > None."""
        model = lane_key.split("@", 1)[0]
        with self._lock:
            return (self._slos.get(lane_key)
                    or self._slos.get(model)
                    or self._slos.get("*"))

    # -- sampling ------------------------------------------------------

    def _read_lane(self, mm, interval_s):
        s = _Sample()
        s.ts = time.time()
        s.mono = time.monotonic()
        s.requests = mm.requests.value
        s.responses = mm.responses.value
        s.errors = mm.errors.value
        s.shed = mm.shed.value
        s.deadline_expired = mm.deadline_expired.value
        s.draft_tokens = mm.draft_tokens.value
        s.accepted_tokens = mm.accepted_tokens.value
        # windowed percentiles over roughly the sampling interval — the
        # lifetime reservoir would blur a fresh regression under hours
        # of healthy history
        window = max(interval_s * 1.5, 0.05)
        s.p95_ms = mm.recent_latency_p95(window)
        s.ttft_p95_ms = mm.recent_ttft_p95(window)
        s.bad = {}
        return s

    @staticmethod
    def _bad_fractions(prev, cur, slo):
        """Reduce one interval (prev -> cur) to per-objective bad
        fractions; objectives without traffic contribute 0.0 (no data
        is not a burn)."""
        bad = {}
        done_d = max((cur.responses - prev.responses)
                     + (cur.errors - prev.errors), 0)
        req_d = max(cur.requests - prev.requests, 0)
        shed_d = max(cur.shed - prev.shed, 0)
        if slo.error_rate is not None:
            bad["error_rate"] = ((cur.errors - prev.errors) / done_d) \
                if done_d else 0.0
        if slo.shed_rate is not None:
            offered = req_d + shed_d
            bad["shed_rate"] = (shed_d / offered) if offered else 0.0
        if slo.p95_ms is not None:
            bad["p95_ms"] = 1.0 if (cur.p95_ms is not None
                                    and cur.p95_ms > slo.p95_ms) else 0.0
        if slo.ttft_p95_ms is not None:
            bad["ttft_p95_ms"] = 1.0 if (
                cur.ttft_p95_ms is not None
                and cur.ttft_p95_ms > slo.ttft_p95_ms) else 0.0
        if slo.spec_accept is not None:
            drafts_d = max(cur.draft_tokens - prev.draft_tokens, 0)
            if drafts_d:
                rate = (cur.accepted_tokens
                        - prev.accepted_tokens) / drafts_d
                bad["spec_accept"] = 1.0 if rate < slo.spec_accept \
                    else 0.0
            else:
                bad["spec_accept"] = 0.0
        return bad

    @staticmethod
    def _budget(slo, objective):
        if objective == "error_rate":
            return max(slo.error_rate, 1e-6)
        if objective == "shed_rate":
            return max(slo.shed_rate, 1e-6)
        return slo.budget

    def _burns(self, ring, slo):
        """{objective: {"fast": burn, "slow": burn|None}} over the two
        windows.  The fast window evaluates as soon as 2 intervals
        exist (hard outages trip early); the slow window only once it
        is FULL — a low-grade burn must prove itself over the whole
        window before it trips (trips late, by design)."""
        samples = list(ring)
        intervals = [s.bad for s in samples[1:] if s.bad is not None]
        out = {}
        for objective, _target in slo.objectives():
            series = [b.get(objective, 0.0) for b in intervals]
            budget = self._budget(slo, objective)
            fast_n = min(slo.fast_window, len(series))
            fast = (sum(series[-fast_n:]) / fast_n / budget) \
                if fast_n >= 2 else None
            slow = (sum(series[-slo.slow_window:]) / slo.slow_window
                    / budget) if len(series) >= slo.slow_window else None
            out[objective] = {"fast": fast, "slow": slow}
        return out

    # -- evaluation ----------------------------------------------------

    def _evaluate_locked(self, key, slo, burns):
        st = self._states.setdefault(
            key, {"state": STATE_OK, "bad_streak": 0, "good_streak": 0,
                  "breaches": 0, "recoveries": 0, "burns": {},
                  "tripped_by": None})
        st["burns"] = burns
        tripped = None
        worst = 0.0
        for objective, b in burns.items():
            if b["fast"] is not None and b["fast"] >= slo.fast_burn \
                    and b["fast"] / slo.fast_burn >= worst:
                tripped, worst = (objective, "fast"), \
                    b["fast"] / slo.fast_burn
            # the slow rule is gated on the SHORT window also burning
            # (Google's paired-window condition): without it, stale bad
            # intervals still inside the slow window would re-trip a
            # lane that has already recovered
            if b["slow"] is not None and b["slow"] >= slo.slow_burn \
                    and b["fast"] is not None \
                    and b["fast"] >= slo.slow_burn \
                    and b["slow"] / slo.slow_burn >= worst:
                tripped, worst = (objective, "slow"), \
                    b["slow"] / slo.slow_burn
        events = []
        if tripped is not None:
            st["bad_streak"] += 1
            st["good_streak"] = 0
            st["tripped_by"] = tripped[0]
            if st["bad_streak"] >= slo.breach_evals:
                if st["state"] != STATE_BREACH:
                    st["state"] = STATE_BREACH
                    st["breaches"] += 1
                    b = burns[tripped[0]]
                    events.append(("slo_breach", {
                        "model": key, "objective": tripped[0],
                        "window": tripped[1],
                        "burn_fast": round(b["fast"], 3)
                        if b["fast"] is not None else None,
                        "burn_slow": round(b["slow"], 3)
                        if b["slow"] is not None else None}))
            elif st["state"] == STATE_OK:
                st["state"] = STATE_DEGRADED
                events.append(("slo_degraded", {
                    "model": key, "objective": tripped[0],
                    "window": tripped[1]}))
        else:
            st["good_streak"] += 1
            st["bad_streak"] = 0
            if st["state"] != STATE_OK \
                    and st["good_streak"] >= slo.recover_evals:
                st["state"] = STATE_OK
                st["recoveries"] += 1
                st["tripped_by"] = None
                events.append(("slo_recovered", {"model": key}))
        return events

    def tick(self):
        """One sample + evaluate pass over every live metrics lane.
        Returns the emitted (kind, fields) transition events."""
        from . import events as obs_events
        interval = self.interval_s
        with self.metrics._lock:
            lanes = dict(self.metrics._models)
        emitted = []
        with self._lock:
            self._ticks += 1
            # an unloaded model's lane leaves the metrics registry:
            # drop its ring/state so health() reflects what is served
            for gone in [k for k in self._rings if k not in lanes]:
                self._rings.pop(gone, None)
                self._states.pop(gone, None)
            for key, mm in lanes.items():
                ring = self._rings.get(key)
                if ring is None:
                    ring = self._rings[key] = collections.deque(
                        maxlen=self._timeline_cap)
                sample = self._read_lane(mm, interval)
                slo = (self._slos.get(key)
                       or self._slos.get(key.split("@", 1)[0])
                       or self._slos.get("*"))
                if ring and slo is not None:
                    sample.bad = self._bad_fractions(ring[-1], sample,
                                                     slo)
                ring.append(sample)
                if slo is None:
                    continue
                burns = self._burns(ring, slo)
                emitted.extend(self._evaluate_locked(key, slo, burns))
        # emit (and arm the flight recorder) OUTSIDE the lock: the
        # recorder's providers may read this monitor back
        for kind, fields in emitted:
            obs_events.emit(kind, monitor=self.name, **fields)
            if kind == "slo_breach":
                from . import flightrec
                flightrec.trigger("slo_breach", **fields)
        return emitted

    # -- thread lifecycle ----------------------------------------------

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="paddle-tpu-slo-monitor-%s" % self.name)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # judgment must never take down the serving process;
                # a broken tick retries next interval
                pass

    def stop(self, timeout=5.0):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    @property
    def running(self):
        t = self._thread
        return bool(t is not None and t.is_alive())

    # -- readouts ------------------------------------------------------

    def state(self):
        """Wire-encodable per-lane SLO readout (the `health` RPC's
        ``slo`` section)."""
        with self._lock:
            out = {}
            for key, ring in self._rings.items():
                slo = (self._slos.get(key)
                       or self._slos.get(key.split("@", 1)[0])
                       or self._slos.get("*"))
                st = self._states.get(key)
                info = {"samples": len(ring),
                        "monitored": slo is not None}
                if ring:
                    info["last_sample_age_s"] = round(
                        max(time.monotonic() - ring[-1].mono, 0.0), 3)
                if slo is not None:
                    info["slo"] = slo.to_dict()
                if st is None:
                    info["state"] = STATE_OK if slo is not None else None
                else:
                    info["state"] = st["state"]
                    info["breaches"] = st["breaches"]
                    info["recoveries"] = st["recoveries"]
                    if st["tripped_by"]:
                        info["tripped_by"] = st["tripped_by"]
                    burns = {}
                    for objective, b in (st["burns"] or {}).items():
                        burns[objective] = {
                            w: (round(v, 3) if v is not None else None)
                            for w, v in b.items()}
                    if burns:
                        info["burn"] = burns
                out[key] = info
            return out

    def timeline(self, model=None, n=None):
        """The bounded time-series ring (flight-recorder bundle's
        ``timeline`` payload): {lane key: [sample dicts, oldest
        first]}."""
        with self._lock:
            out = {}
            for key, ring in self._rings.items():
                if model is not None and key != model:
                    continue
                samples = list(ring)
                if n is not None:
                    samples = samples[-int(n):]
                out[key] = [s.to_dict() for s in samples]
            return out

    def export(self):
        """Prometheus samples for the registry render:
        [(metric, labels, value, type)].  State codes: 0 ok,
        1 degraded, 2 breach."""
        with self._lock:
            rows = []
            for key in sorted(self._rings):
                model, _, prec = key.partition("@")
                labels = {"model": model}
                if prec:
                    labels["precision"] = prec
                st = self._states.get(key)
                slo = (self._slos.get(key) or self._slos.get(model)
                       or self._slos.get("*"))
                if slo is None:
                    continue
                state = st["state"] if st else STATE_OK
                rows.append(("slo_state", dict(labels),
                             _STATE_CODE[state], "gauge"))
                for objective, b in ((st or {}).get("burns")
                                     or {}).items():
                    for window in ("fast", "slow"):
                        if b.get(window) is not None:
                            rows.append((
                                "slo_burn_rate",
                                dict(labels, objective=objective,
                                     window=window),
                                round(b[window], 4), "gauge"))
                # compliance: the fraction of recent intervals that met
                # the objective (1.0 = clean slow window)
                ring = self._rings.get(key)
                intervals = [s.bad for s in list(ring)[1:]
                             if s.bad is not None] if ring else []
                for objective, _t in slo.objectives():
                    series = [b.get(objective, 0.0) for b in
                              intervals[-slo.slow_window:]]
                    if series:
                        rows.append((
                            "slo_compliance",
                            dict(labels, objective=objective),
                            round(1.0 - sum(series) / len(series), 4),
                            "gauge"))
            return rows
