"""Py2/3 compatibility helpers (reference python/paddle/compat.py) —
python-3-only build, the API surface is kept for ported code."""

import math

__all__ = ["long_type", "to_text", "to_bytes", "round",
           "floor_division", "get_exception_message"]

int_type = int
long_type = int


def to_text(obj, encoding="utf-8", inplace=False):
    """Convert bytes (recursively through list/set/dict) to str
    (reference compat.py to_text)."""
    if obj is None:
        return obj
    if isinstance(obj, list):
        if inplace:
            for i in range(len(obj)):
                obj[i] = _to_text(obj[i], encoding)
            return obj
        return [_to_text(o, encoding) for o in obj]
    if isinstance(obj, set):
        if inplace:
            items = [_to_text(o, encoding) for o in obj]
            obj.clear()
            obj.update(items)
            return obj
        return set(_to_text(o, encoding) for o in obj)
    if isinstance(obj, dict):
        if inplace:
            for k in list(obj):
                obj[k] = _to_text(obj[k], encoding)
            return obj
        return {k: _to_text(v, encoding) for k, v in obj.items()}
    return _to_text(obj, encoding)


def _to_text(obj, encoding):
    if isinstance(obj, bytes):
        return obj.decode(encoding)
    return obj


def to_bytes(obj, encoding="utf-8", inplace=False):
    """Convert str (recursively through containers) to bytes."""
    if obj is None:
        return obj
    if isinstance(obj, list):
        if inplace:
            for i in range(len(obj)):
                obj[i] = _to_bytes(obj[i], encoding)
            return obj
        return [_to_bytes(o, encoding) for o in obj]
    if isinstance(obj, set):
        if inplace:
            items = [_to_bytes(o, encoding) for o in obj]
            obj.clear()
            obj.update(items)
            return obj
        return set(_to_bytes(o, encoding) for o in obj)
    if isinstance(obj, dict):
        if inplace:
            for k in list(obj):
                obj[k] = _to_bytes(obj[k], encoding)
            return obj
        return {k: _to_bytes(v, encoding) for k, v in obj.items()}
    return _to_bytes(obj, encoding)


def _to_bytes(obj, encoding):
    if isinstance(obj, str):
        return obj.encode(encoding)
    return obj


def round(x, d=0):
    """Python-2 semantics: round half away from zero (reference
    compat.py round)."""
    p = 10 ** d
    if x > 0:
        return float(math.floor((x * p) + math.copysign(0.5, x))) / p
    if x < 0:
        return float(math.ceil((x * p) + math.copysign(0.5, x))) / p
    return 0.0


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    assert exc is not None
    return str(exc)
