"""AOT training export: run a TRAINING step from a saved artifact with no
Program rebuild and no jax trace.

Reference analogue: the C++ train/demo
(/root/reference/paddle/fluid/train/demo/demo_trainer.cc,
test_train_recognize_digits.cc) — pure-C++ training driven from a saved
program via `framework::Executor`. TPU redesign: the whole optimizer step
(forward + backward + update) is functionalized into one pure
fn(state, feeds, step) -> (fetches, new_state), AOT-exported as versioned
StableHLO (jax.export), and the parameter/optimizer state rides the
no-pickle wire codec. A fresh process — or a C host via
native/pd_capi.h's pd_create_trainer — deserializes and trains with XLA
compiling the stored module directly.

Artifact layout (directory):
  train_step.bin    serialized jax.export module for the step fn
  train_state.bin   wire-encoded {name: ndarray} parameter/opt state
  train_meta.bin    wire-encoded feed specs, fetch names, step counter
"""

import os

import numpy as np

__all__ = ["save_aot_trainer", "load_aot_trainer", "AotTrainer"]


def save_aot_trainer(dirname, program, feed_names, fetch_names,
                     scope=None, batch_size=None, platforms=None):
    """Export `program`'s training step for batch size `batch_size`
    (default: the feed vars' static batch dim; -1 dims require an
    explicit batch_size).

    `fetch_names` are the per-step fetches (losses/metrics); the full
    persistable state is threaded and saved automatically. `platforms`
    selects the target(s): ("tpu",) cross-compiles from a CPU build
    host; ("cpu", "tpu") embeds both lowerings in one artifact (for
    Pallas-free programs — see Predictor.save_aot)."""
    import jax
    from jax import export as jax_export
    from . import functionalizer
    from .executor import global_scope
    from ..native import wire
    from . import core

    if scope is None:
        scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    gb = program.global_block()
    fetch_names = [getattr(f, "name", f) for f in fetch_names]
    # caller order is the artifact's positional-feed contract (same
    # convention as AotPredictor); the step fn internally keys feeds by
    # name so its own ordering is irrelevant
    feed_names = tuple(getattr(f, "name", f) for f in feed_names)

    feed_specs = {}
    for name in feed_names:
        v = gb._find_var_recursive(name)
        if v is None or v.shape is None:
            raise ValueError("feed var %r not found or unshaped" % name)
        shape = [int(d) for d in v.shape]
        if shape and shape[0] == -1:
            if batch_size is None:
                raise ValueError(
                    "feed %r has dynamic batch; pass batch_size" % name)
            shape[0] = int(batch_size)
        if any(d < 0 for d in shape):
            raise ValueError("feed %r has non-batch dynamic dims %s"
                             % (name, shape))
        feed_specs[name] = (tuple(shape),
                            str(np.dtype(core.convert_dtype_to_np(
                                v.dtype))))

    state_names = tuple(functionalizer.persistable_names(program))
    state = {}
    for n in state_names:
        val = scope.get(n)
        if val is not None:
            state[n] = np.asarray(val)
    step_fn = functionalizer.build_step_fn(
        program, tuple(sorted(feed_names)), tuple(fetch_names),
        tuple(state.keys()))

    state_spec = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                  for n, v in state.items()}
    feeds_spec = {n: jax.ShapeDtypeStruct(s, np.dtype(dt))
                  for n, (s, dt) in feed_specs.items()}
    if isinstance(platforms, str):
        # list("tpu") would become ['t','p','u'] and fail far away
        platforms = (platforms,)
    step_spec = jax.ShapeDtypeStruct((), np.uint32)
    from ..ops.pallas_kernels import mosaic_lowering
    with mosaic_lowering(bool(platforms) and "tpu" in platforms
                         and "cpu" not in platforms):
        # pure-TPU targets embed the real Mosaic kernels from a CPU
        # build host; cpu-including targets keep interpret emulation
        exp = jax_export.export(
            jax.jit(step_fn),
            platforms=list(platforms) if platforms else None)(
            state_spec, feeds_spec, step_spec)
    with open(os.path.join(dirname, "train_step.bin"), "wb") as f:
        f.write(exp.serialize())
    with open(os.path.join(dirname, "train_state.bin"), "wb") as f:
        f.write(wire.encode(state))
    meta = {
        "feed_names": list(feed_names),
        "fetch_names": list(fetch_names),
        "feed_specs": {n: {"shape": list(s), "dtype": d}
                       for n, (s, d) in feed_specs.items()},
        "step": 0,
        "platform": jax.default_backend(),
    }
    with open(os.path.join(dirname, "train_meta.bin"), "wb") as f:
        f.write(wire.encode(meta))
    return dirname


class AotTrainer:
    """Train from a `save_aot_trainer` artifact: step() runs the stored
    XLA module and threads the state; save() checkpoints state + step
    counter so a later process resumes exactly."""

    def __init__(self, dirname):
        from jax import export as jax_export
        from ..native import wire

        with open(os.path.join(dirname, "train_meta.bin"), "rb") as f:
            self._meta = wire.decode(f.read())
        with open(os.path.join(dirname, "train_state.bin"), "rb") as f:
            self._state = wire.decode(f.read())
        with open(os.path.join(dirname, "train_step.bin"), "rb") as f:
            self._fn = jax_export.deserialize(f.read()).call
        self._dir = dirname
        self._feed_names = list(self._meta["feed_names"])
        self._fetch_names = list(self._meta["fetch_names"])
        self._feed_specs = self._meta["feed_specs"]
        self._step = int(self._meta.get("step", 0))

    @property
    def step_count(self):
        return self._step

    def state(self, name):
        return self._state[name]

    def step(self, feed):
        """One optimizer step. `feed` is {name: array} (or a positional
        sequence in feed_names order); returns the fetch list."""
        if not isinstance(feed, dict):
            feed = {n: v for n, v in zip(self._feed_names, feed)}
        feeds = {}
        for name in self._feed_names:
            if name not in feed:
                raise KeyError("missing feed %r" % name)
            spec = self._feed_specs[name]
            arr = np.asarray(feed[name])
            want = np.dtype(spec["dtype"])
            if arr.dtype != want:
                if arr.dtype.kind in "iu" and want.kind in "iu":
                    arr = arr.astype(want)
                elif arr.dtype.kind == "f" and want.kind == "f":
                    arr = arr.astype(want)
                else:
                    raise TypeError(
                        "feed %r dtype %s, artifact expects %s"
                        % (name, arr.dtype, want))
            if tuple(arr.shape) != tuple(spec["shape"]):
                raise ValueError(
                    "feed %r shape %s, artifact expects %s"
                    % (name, arr.shape, tuple(spec["shape"])))
            feeds[name] = arr
        fetches, self._state = self._fn(self._state, feeds,
                                        np.uint32(self._step))
        self._step += 1
        return [np.asarray(f) for f in fetches]

    def save(self, dirname):
        """Checkpoint into `dirname` (may be the source artifact dir):
        the step module is copied if absent, state and step counter are
        rewritten."""
        import shutil
        from ..native import wire

        os.makedirs(dirname, exist_ok=True)
        dst_mod = os.path.join(dirname, "train_step.bin")
        src_mod = os.path.join(self._dir, "train_step.bin")
        # always overwrite: a stale module from an earlier export in the
        # target dir would silently resume the OLD program on new state
        if os.path.abspath(dst_mod) != os.path.abspath(src_mod):
            shutil.copy(src_mod, dst_mod)
        with open(os.path.join(dirname, "train_state.bin"), "wb") as f:
            f.write(wire.encode({n: np.asarray(v)
                                 for n, v in self._state.items()}))
        with open(os.path.join(dirname, "train_meta.bin"), "wb") as f:
            f.write(wire.encode(dict(self._meta, step=self._step)))
        return dirname


def load_aot_trainer(dirname):
    return AotTrainer(dirname)
