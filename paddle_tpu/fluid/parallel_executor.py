"""ParallelExecutor — multi-chip data-parallel training.

Reference analogue: python/paddle/fluid/parallel_executor.py:32 wrapping C++
ParallelExecutor (parallel_executor.cc:69): per-device scopes, NCCLContextMap,
multi_devices_pass cloning ops per device + inserting ncclAllReduce handles
(details/all_reduce_op_handle.cc:48), ThreadedSSAGraphExecutor.

TPU redesign (SURVEY.md §2.10 row 1): the multi-device SSA graph is replaced
by ONE jitted step over a jax.sharding.Mesh — feeds are sharded on the batch
axis, parameters are replicated, and XLA's SPMD partitioner inserts the grad
all-reduce over ICI exactly where the reference's multi_devices_pass inserted
NCCL op handles. BuildStrategy/ExecutionStrategy are kept as first-class
config objects (pybind.cc:685,:772) — most knobs are advisory because the
compiler owns scheduling, but reduce-strategy and num-threads map to
sharding/compiler choices.

Param broadcast at construction (BCastParamsToDevices, parallel_executor.cc
:200) becomes re-device_put of scope arrays with a replicated sharding.
"""

import os

import numpy as np

from . import core
from .executor import global_scope, as_numpy, _fetch_name
from .pipeline import FetchFuture
from .framework import default_main_program
from . import functionalizer
from ..parallel.mesh import data_parallel_mesh, DATA_AXIS

__all__ = ["ParallelExecutor", "ExecutionStrategy", "BuildStrategy"]


class ExecutionStrategy:
    """reference details/execution_strategy.h. Scheduling is XLA's job; these
    knobs are accepted for API parity and used where meaningful."""

    def __init__(self):
        self.num_threads = 0
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 100
        self.use_experimental_executor = False


class BuildStrategy:
    """reference details/build_strategy.h:95."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""
        self.enable_data_balance = False
        self.memory_optimize = False
        self.fuse_elewise_add_act_ops = False  # XLA fuses anyway


class ParallelExecutor:
    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None, mesh=None):
        import jax
        self._main_program = main_program if main_program is not None \
            else default_main_program()
        self._scope = scope if scope is not None else global_scope()
        if share_vars_from is not None:
            self._scope = share_vars_from._scope
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._build_strategy = build_strategy or BuildStrategy()
        self._loss_name = loss_name
        # multi-host ("nccl2") data parallelism: after the startup
        # program's gen_collective_id has run jax.distributed.initialize,
        # jax.devices() spans every trainer process and the mesh below is
        # the cross-node NCCLContextMap analogue (nccl_helper.h:82,
        # parallel_executor.cc:113). Feeds stay process-local; run()
        # assembles them into global arrays.
        self._num_trainers = int(num_trainers or 1)
        self._trainer_id = int(trainer_id or 0)
        if self._num_trainers > 1:
            if jax.process_count() != self._num_trainers:
                raise RuntimeError(
                    "ParallelExecutor(num_trainers=%d) but the collective "
                    "world has %d processes — run gen_collective_id (the "
                    "collective-mode transpiler emits it into the startup "
                    "program) or set PADDLE_COORDINATOR before first "
                    "device use" % (self._num_trainers,
                                    jax.process_count()))
            if jax.process_index() != self._trainer_id:
                raise RuntimeError(
                    "trainer_id=%d does not match collective process "
                    "index %d" % (self._trainer_id, jax.process_index()))
            if mesh is None:
                from ..parallel.mesh import make_mesh
                devs = jax.devices()
                mesh = make_mesh({DATA_AXIS: len(devs)}, devs)
        self._mesh = mesh if mesh is not None else \
            data_parallel_mesh(use_cuda=use_cuda)
        self._num_devices = int(np.prod(list(self._mesh.shape.values())))
        self._cache = {}
        self._host_ops_flag = {}  # program version -> has host ops
        self._step = 0
        # BuildStrategy pass pipeline (reference build_strategy.cc:27
        # ParallelExecutorPassBuilder chains passes before graph build)
        from . import ir_passes
        if self._build_strategy.fuse_elewise_add_act_ops:
            ir_passes.get_pass("fuse_elewise_add_act_pass").apply(
                self._main_program)
        self._apply_gradient_scale_strategy()
        if self._build_strategy.debug_graphviz_path:
            ir_passes.get_pass(
                "graph_viz_pass",
                graph_viz_path=self._build_strategy.debug_graphviz_path
            ).apply(self._main_program)
        # BCastParamsToDevices analogue: replicate existing scope arrays
        self._replicate_state()

    def _apply_gradient_scale_strategy(self):
        """reference details/build_strategy.h:55 GradientScaleStrategy +
        scale_loss_grad_op_handle: how the loss-gradient seed relates to
        the device count.

        - CoeffNumDevice (default): each device seeds 1/num_devices and
          grads SUM-reduce — identical to this build's global formulation
          (one SPMD step over the global batch, loss already a global
          mean), so nothing changes.
        - One: each device seeds 1.0 and grads sum — net effect is grads
          num_devices x larger; encoded by rewriting the backward
          fill_constant seed (backward.py appends fill_constant(1) for
          <loss>@GRAD) to num_devices.
        - Customized: per-device user-supplied seeds have no analogue in
          the single-global-computation design — rejected explicitly.
        """
        strat = self._build_strategy.gradient_scale_strategy
        if strat == BuildStrategy.GradientScaleStrategy.CoeffNumDevice:
            return
        if strat == BuildStrategy.GradientScaleStrategy.Customized:
            raise NotImplementedError(
                "GradientScaleStrategy.Customized: supply a custom loss "
                "scale by scaling the loss itself (the SPMD step is one "
                "global computation; there is no per-device seed to feed)")
        if self._loss_name is None:
            return
        from .framework import grad_var_name
        target = grad_var_name(self._loss_name)
        for op in self._main_program.global_block().ops:
            if op.type == "fill_constant" and \
                    op.outputs.get("Out", [None])[0] == target:
                if not op.attrs.get("@grad_scale_applied"):
                    op.attrs["value"] = float(op.attrs.get("value", 1.0)) \
                        * self._num_devices
                    op.attrs["@grad_scale_applied"] = True
                    self._main_program._bump_version()
                break

    @property
    def device_count(self):
        return self._num_devices

    @property
    def mesh(self):
        """The jax.sharding.Mesh this executor shards over — handed to
        reader.prefetch_to_device(mesh=...) so the prefetch thread
        commits pre-sharded feeds (the sharded-prefetch pipeline mode,
        PIPELINE.md)."""
        return self._mesh

    def _replicated_sharding(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self._mesh, P())

    def _batch_sharding(self, ndim):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self._mesh,
                             P(DATA_AXIS, *([None] * (ndim - 1))))

    def _put(self, arr, sharding):
        """Place a process-local array under `sharding`. Across processes
        this is the BCast/split analogue: every process contributes its
        addressable shards (full array when replicated, the local batch
        shard when batch-sharded)."""
        import jax
        if self._num_trainers > 1:
            return jax.make_array_from_process_local_data(sharding, arr)
        return jax.device_put(arr, sharding)

    def _replicate_state(self):
        rep = self._replicated_sharding()
        for name in functionalizer.persistable_names(self._main_program):
            val = self._scope.get(name)
            if val is not None:
                self._scope.set(name, self._put(np.asarray(val), rep))

    def _get_jitted(self, feed_key, fetch_names, state_names):
        import jax
        from ..ops.registry import amp_enabled
        wga, remat = functionalizer.flags_ad_config()
        key = (feed_key, fetch_names, tuple(state_names),
               self._main_program._version, amp_enabled(), wga, remat)
        fn = self._cache.get(key)
        if fn is not None:
            return fn
        step_fn = functionalizer.build_step_fn(
            self._main_program, feed_key, fetch_names, state_names,
            mesh=self._mesh, whole_graph_ad=wga, remat_policy=remat)
        rep = self._replicated_sharding()

        def wrapped(state, feeds, step):
            return step_fn(state, feeds, step)

        donate = (0,) if any(d.platform == "tpu"
                             for d in self._mesh.devices.flat) else ()
        fn = jax.jit(wrapped, donate_argnums=donate,
                     out_shardings=None)
        self._cache[key] = fn
        return fn

    def _prepare_feeds(self, feed, feed_dict=None):
        """Merge per-device feed lists, then run the Executor's shared
        feed preparation (dtype casts; LoDTensor -> padded dense +
        @LOD_LEN companions) and shard every batch-dim array on the
        mesh's data axis."""
        import jax.numpy as jnp
        from .executor import prepare_feeds
        if feed is None:
            feed = feed_dict
        if feed is None:
            feed = {}
        if isinstance(feed, (list, tuple)):
            from .lod import LoDTensor
            merged = {}
            for k in feed[0]:
                vals = [d[k] for d in feed]
                if any(isinstance(v, LoDTensor) and v.lod() for v in vals):
                    # merge data AND lod — np.concatenate alone would
                    # strip the ragged structure via __array__: per
                    # level, sequence lengths concatenate (each level's
                    # offsets index rows of the next, and concatenation
                    # preserves that nesting)
                    if not all(isinstance(v, LoDTensor) and v.lod()
                               for v in vals):
                        raise ValueError(
                            "feed '%s': mixed LoDTensor and dense "
                            "entries across devices" % k)
                    depth = len(vals[0].lod())
                    if any(len(v.lod()) != depth for v in vals):
                        raise ValueError(
                            "feed '%s': inconsistent LoD depth across "
                            "devices" % k)
                    t = LoDTensor(np.concatenate(
                        [v.numpy() for v in vals], axis=0))
                    t.set_recursive_sequence_lengths(
                        [sum((v.recursive_sequence_lengths()[lv]
                              for v in vals), [])
                         for lv in range(depth)])
                    merged[k] = t
                else:
                    merged[k] = np.concatenate(
                        [np.asarray(v) for v in vals], axis=0)
            feed = merged
        import jax
        dense = prepare_feeds(self._main_program, feed, device_put=False)
        feeds = {}
        for name, arr in dense.items():
            if arr.ndim == 0:
                feeds[name] = jnp.asarray(arr)
                continue
            # @LOD_LEN/@LOD_SEG companions are batch-dim vectors and
            # shard with their payload. jax.Array feeds (PyReader
            # double-buffer) go straight to the sharded device_put —
            # no host round-trip — except in multi-trainer mode, where
            # make_array_from_process_local_data wants host data.
            target = self._batch_sharding(arr.ndim)
            if isinstance(arr, jax.Array):
                if arr.sharding == target:
                    # sharded prefetch (prefetch_to_device mesh mode)
                    # already committed this array on the mesh — the
                    # whole point is skipping the per-dispatch commit
                    feeds[name] = arr
                    continue
                if self._num_trainers > 1:
                    arr = np.asarray(arr)
            feeds[name] = self._put(arr, target)
        return feeds

    def run_loop(self, fetch_list, feed=None, steps=1, return_numpy=True):
        """`steps` SPMD training steps as ONE device computation — the
        multi-chip analogue of Executor.run_loop: lax.fori_loop over the
        mesh-sharded jitted step with a constant sharded feed, one
        dispatch per `steps` steps. Gradient all-reduces stay inside the
        single XLA computation, so a pod iterates without any host
        involvement between steps."""
        import jax
        import jax.numpy as jnp
        steps = int(steps)
        if steps < 1:
            raise ValueError("run_loop: steps must be >= 1")
        from ..flags import FLAGS
        if FLAGS.check_nan_inf:
            raise RuntimeError(
                "run_loop: FLAGS.check_nan_inf needs per-op attribution, "
                "which requires per-step execution — use "
                "ParallelExecutor.run")
        if FLAGS.verify_program:
            from ..analysis import verify_program_cached
            verify_program_cached(
                self._main_program,
                feeds=sorted(feed) if isinstance(feed, dict) else None,
                fetches=[_fetch_name(f) for f in fetch_list],
                what="parallel executor run_loop program")
        hkey = self._main_program._version
        if self._host_ops_flag.get(hkey) is None:
            self._host_ops_flag[hkey] = \
                functionalizer.contains_host_ops(self._main_program)
        if self._host_ops_flag[hkey]:
            raise RuntimeError(
                "run_loop: the program contains host ops and cannot run "
                "as one device computation — use ParallelExecutor.run")
        fetch_names = tuple(_fetch_name(f) for f in fetch_list)
        feeds = self._prepare_feeds(feed)
        feed_key = tuple(sorted(feeds.keys()))
        persistables = tuple(
            functionalizer.persistable_names(self._main_program))
        from ..ops.registry import amp_enabled
        wga, remat = functionalizer.flags_ad_config()
        key = ("loop", feed_key, fetch_names, persistables,
               self._main_program._version, amp_enabled(), wga, remat)
        fn = self._cache.get(key)
        if fn is None:
            step_fn = functionalizer.build_step_fn(
                self._main_program, feed_key, fetch_names, persistables,
                mesh=self._mesh, whole_graph_ad=wga, remat_policy=remat)
            fn = functionalizer.jit_loop(
                step_fn, any(d.platform == "tpu"
                             for d in self._mesh.devices.flat))
            self._cache[key] = fn
        state_in = {n: self._scope.get(n) for n in persistables
                    if self._scope.get(n) is not None}
        fetches, new_state = fn(state_in, feeds,
                                np.uint32(self._step), np.int32(steps))
        self._step += steps
        for n, val in new_state.items():
            self._scope.set(n, val)
        if return_numpy:
            # one batched device->host copy for the whole fetch list —
            # a per-item np.asarray loop would serialize the transfers
            import jax
            return jax.device_get(list(fetches))
        return list(fetches)

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True,
            as_future=False):
        """reference parallel_executor.py:169. `feed` may be one dict (full
        global batch, split across devices — the reference's split path) or a
        list of per-device dicts (concatenated here, then sharded). In
        nccl2 multi-trainer mode each array is this trainer's LOCAL
        batch; the global array spans num_trainers x local (the
        reference's per-trainer reader semantics).

        `as_future=True` dispatches the SPMD step without resolving:
        the FetchFuture keeps the fetches as live (sharded) device
        arrays and the host sync is deferred to `.result()` — same
        in-flight contract as Executor.run (PIPELINE.md)."""
        fetch_names = tuple(_fetch_name(f) for f in fetch_list)
        from ..flags import FLAGS
        if FLAGS.verify_program:
            from ..analysis import verify_program_cached
            verify_program_cached(
                self._main_program,
                feeds=sorted(feed) if isinstance(feed, dict) else None,
                fetches=fetch_names, what="parallel executor program")
        feeds = self._prepare_feeds(feed, feed_dict)
        feed_key = tuple(sorted(feeds.keys()))

        persistables = tuple(
            functionalizer.persistable_names(self._main_program))
        fn = self._get_jitted(feed_key, fetch_names, persistables)
        state_in = {n: self._scope.get(n) for n in persistables
                    if self._scope.get(n) is not None}
        fetches, new_state = fn(state_in, feeds, np.uint32(self._step))
        self._step += 1
        for n, val in new_state.items():
            self._scope.set(n, val)
        if as_future:
            return FetchFuture(fetches, return_numpy=return_numpy,
                               what="parallel executor step drain")
        if return_numpy:
            # one batched device->host copy for the whole fetch list —
            # per-item np.asarray would serialize the gathers
            import jax
            return jax.device_get(list(fetches))
        return list(fetches)
