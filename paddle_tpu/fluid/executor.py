"""Executor + Scope.

Reference analogues: python/paddle/fluid/executor.py:256 (Executor: program
cache, feed/fetch, as_numpy :66, scope_guard :47) over C++
framework/executor.cc:183 (Executor::Run) and scope.h:41 (Scope).

TPU redesign: `run(program, feed, fetch_list)` functionalizes the block
(functionalizer.py), jits it once per (program version, feed signature,
fetch list) and replays the compiled XLA computation per step — the analogue
of the reference's ExecutorPrepareContext cache (executor.py:207) where the
cached object is a compiled HLO module instead of an op list. Parameters and
other persistable variables live in the Scope as jax Arrays and are threaded
through the jitted step functionally; on TPU the state buffers are donated so
updates are in-place at the XLA level.
"""

import threading
import warnings

import numpy as np

from . import core
from .framework import Program, Variable, default_main_program
from . import functionalizer
from .pipeline import FetchFuture

__all__ = ["Executor", "Scope", "global_scope", "scope_guard", "as_numpy",
           "StepWatchdogTimeout", "FetchFuture"]


class StepWatchdogTimeout(TimeoutError):
    """An executor step exceeded FLAGS.step_watchdog_secs of wall clock.
    The backend may be wedged (the r03 TPU transport outage blocked jax
    inside C forever); the hung dispatch keeps its worker thread, but the
    train loop gets an exception it can act on instead of hanging."""


def _watchdog_call(call, timeout, what="executor step"):
    """Run `call` on a worker thread and give up after `timeout` seconds
    — the in-process generalization of bench.py's subprocess wedge-probe
    (a hung XLA dispatch cannot be interrupted from Python, but it CAN be
    abandoned).  Zero overhead path is the caller's: only invoked when
    the watchdog flag is set."""
    box = {}
    done = threading.Event()

    def _worker():
        try:
            box["value"] = call()
        except BaseException as e:
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=_worker, daemon=True,
                         name="paddle-tpu-step-watchdog")
    t.start()
    if not done.wait(timeout):
        from ..obs import events as _obs_events
        from ..obs import flightrec as _obs_flightrec
        _obs_events.emit("watchdog_fire", what=str(what),
                         budget_s=round(float(timeout), 3))
        # a wedged backend is exactly what the flight recorder exists
        # for: the bundle's thread stacks show WHERE the abandoned
        # dispatch thread is stuck (no-op while FLAGS.flight_dir unset)
        _obs_flightrec.trigger("watchdog_fire", what=str(what),
                               budget_s=round(float(timeout), 3))
        raise StepWatchdogTimeout(
            "%s still running after %.1fs (FLAGS.step_watchdog_secs) — "
            "backend wedged or step pathologically slow; the dispatch "
            "thread is abandoned" % (what, timeout))
    if "error" in box:
        raise box["error"]
    return box.get("value")


class _TensorView:
    """Mimics fluid's `scope.find_var(name).get_tensor()` protocol."""

    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def get_tensor(self):
        return self._scope._vars[self._name]

    def set(self, value, place=None):
        import jax.numpy as jnp
        self._scope._vars[self._name] = jnp.asarray(value)


class Scope:
    """name -> device array map (reference scope.h:41). Mostly flat — the
    reference's parent-scope chain existed for per-op temporary locals,
    which the functional executor doesn't materialize — but `new_scope`
    keeps the kid-scope contract: reads fall through to the parent,
    writes stay local (scope.cc Scope::NewScope + parent lookup)."""

    def __init__(self):
        self._vars = {}
        self._parent = None
        self._kids = []

    def new_scope(self):
        """Create a kid scope (reference pybind Scope.new_scope —
        API.spec:412)."""
        kid = Scope()
        kid._parent = self
        self._kids.append(kid)
        return kid

    def var(self, name):
        if name not in self._vars:
            self._vars[name] = None
        return _TensorView(self, name)

    def find_var(self, name):
        if name in self._vars:
            return _TensorView(self, name)
        if self._parent is not None:
            return self._parent.find_var(name)
        return None

    def has(self, name):
        return name in self._vars or \
            (self._parent is not None and self._parent.has(name))

    def get(self, name):
        if name in self._vars:
            return self._vars[name]
        if self._parent is not None:
            return self._parent.get(name)
        return None

    def set(self, name, value):
        self._vars[name] = value

    def drop_kids(self):
        self._kids = []

    def keys(self):
        return self._vars.keys()


_global_scope = Scope()


class _ScopeStack(threading.local):
    """Per-thread scope stack rooted at the process-wide global scope —
    concurrent executors (pserver thread + trainer threads, reference
    test_dist_base style) must not see each other's scope_guard pushes."""

    def __init__(self):
        self.stack = [_global_scope]


_scope_stack_tls = _ScopeStack()


def global_scope():
    return _scope_stack_tls.stack[-1]


class scope_guard:
    def __init__(self, scope):
        self._scope = scope

    def __enter__(self):
        _scope_stack_tls.stack.append(self._scope)
        return self._scope

    def __exit__(self, *args):
        _scope_stack_tls.stack.pop()


def as_numpy(tensor):
    """reference executor.py:66"""
    if isinstance(tensor, (list, tuple)):
        return [as_numpy(t) for t in tensor]
    return np.asarray(tensor)


def _fetch_name(f):
    if isinstance(f, Variable):
        return f.name
    if isinstance(f, str):
        return f
    raise TypeError("bad fetch entry: %r" % (f,))


def _check_nan_inf(fetch_names, fetches, new_state):
    """FLAGS.check_nan_inf step-boundary check (reference operator.cc:29
    per-op check; eagerly-run host-op programs get per-op attribution in
    functionalizer._run_forward_op instead)."""
    bad = []
    for name, val in list(zip(fetch_names, fetches)) + \
            sorted(new_state.items()):
        if val is None:
            continue
        arr = np.asarray(val)
        if arr.dtype.kind == "f" and not np.all(np.isfinite(arr)):
            bad.append(name)
    if bad:
        raise FloatingPointError(
            "check_nan_inf: non-finite values in: %s (enable "
            "jax_debug_nans or run the program eagerly for per-op "
            "attribution)" % ", ".join(bad))


def prepare_feeds(program, feed, device_put=True):
    """numpy -> device arrays with var dtype; LoDTensor (ragged) feeds
    become padded [B, T, ...] + <name>@LOD_LEN lengths, with T bucketed
    to a power of two to bound recompiles. Shared by Executor and
    ParallelExecutor; the latter passes device_put=False so values stay
    host-side (or on their original device for jax.Array feeds) and the
    ONLY transfer is the sharded device_put over the mesh — committing
    a pod-global batch to device 0 first could OOM it."""
    import jax
    import jax.numpy as jnp
    put = jnp.asarray if device_put else np.asarray
    gb = program.global_block()
    feeds = {}
    for name, value in feed.items():
        v = gb._find_var_recursive(name)
        from .lod import LoDTensor, pad_lod_feed
        if isinstance(value, LoDTensor) and value.lod():
            padded, lengths, seg = pad_lod_feed(value)
            if v is not None and v.dtype is not None:
                want = core.convert_dtype_to_np(v.dtype)
                if padded.dtype != want and not (
                        padded.dtype.kind in "iu" and want.kind in "iu"):
                    padded = padded.astype(want)
            feeds[name] = put(padded)
            feeds[name + functionalizer.LOD_LEN_SUFFIX] = put(lengths)
            if seg is not None:
                feeds[name + functionalizer.LOD_SEG_SUFFIX] = put(seg)
            continue
        if isinstance(value, jax.Array):
            # already on device (PyReader double-buffer path) — do NOT
            # round-trip through numpy, that would force D2H + H2D
            arr = value
            if v is not None and v.dtype is not None:
                want = core.convert_dtype_to_np(v.dtype)
                if arr.dtype != want and not (
                        np.dtype(arr.dtype).kind in "iu"
                        and want.kind in "iu"):
                    arr = arr.astype(want)
            feeds[name] = arr
            continue
        arr = np.asarray(value)
        if v is not None and v.dtype is not None:
            want = core.convert_dtype_to_np(v.dtype)
            if arr.dtype != want and not (
                    arr.dtype.kind in "iu" and want.kind in "iu"):
                arr = arr.astype(want)
        feeds[name] = put(arr)
    return feeds


class Executor:
    """reference executor.py:256. `place` selects the jax backend; under jit
    there is no per-op placement, so CPUPlace/TPUPlace only choose where the
    compiled computation and the Scope arrays live."""

    def __init__(self, place=None):
        self.place = place if place is not None else core.TPUPlace(0)
        self._cache = {}  # key -> jitted (or eager host-path) fn
        self._step_counters = {}  # program cache id -> step
        self._host_op_cache = {}  # (id, version) -> program has host ops

    def _device(self):
        try:
            return self.place.jax_device()
        except Exception:
            return None

    def close(self):
        # reference: notifies pservers a trainer is leaving; collective-DP
        # TPU path has no pserver connection to close by default.
        self._cache.clear()

    def _get_jitted(self, program, feed_names, fetch_names, state_names):
        import jax
        from ..ops.registry import amp_enabled
        wga, remat = functionalizer.flags_ad_config()
        key = (id(program), program._version, feed_names, fetch_names,
               tuple(state_names), amp_enabled(), wga, remat)
        fn = self._cache.get(key)
        if fn is None:
            step_fn = functionalizer.build_step_fn(
                program, feed_names, fetch_names, state_names,
                whole_graph_ad=wga, remat_policy=remat)
            donate = ()
            dev = self._device()
            if dev is not None and dev.platform == "tpu":
                donate = (0,)
            fn = jax.jit(step_fn, donate_argnums=donate)
            self._cache[key] = fn
        return fn

    def _aot_cache_eligible(self, program):
        """True when the program is inference-shaped — single block, no
        *_grad ops, no optimizer ops (host ops are excluded by the
        caller's branch) — so its executable is a pure function of the
        Program content and safe to reuse from the persistent compile
        cache (COMPILE_CACHE.md; gated by FLAGS.executor_compile_cache).
        Memoized per (program identity, version)."""
        key = ("aot_ok", id(program), program._version)
        cached = self._host_op_cache.get(key)
        if cached is None:
            cached = len(program.blocks) == 1
            if cached:
                from ..ops.optimizer_ops import MERGEABLE_OPT_OPS
                opt = frozenset(MERGEABLE_OPT_OPS)
                for op in program.blocks[0].ops:
                    if op.type.endswith("_grad") or op.type in opt:
                        cached = False
                        break
            self._host_op_cache[key] = cached
        return cached

    def _get_aot_cached(self, program, feed_key, fetch_ext, persistables,
                        state_in, feeds):
        """Persistent-cache resolution for the jitted executor step:
        fingerprint the Program content + feed/state specs, deserialize
        a stored executable on a hit, export+commit on a miss.  Returns
        the step fn or None (caller falls back to _get_jitted) — the
        cache can only ever cost a recompile, never a failure."""
        import time as _time
        import jax
        from jax import export as jax_export
        from paddle_tpu import compile_cache as cc
        from ..ops.registry import amp_enabled
        if not cc.cache_enabled() or not self._aot_cache_eligible(program):
            return None
        dev = self._device()
        if dev is not None and dev.platform != jax.default_backend():
            return None
        wga, remat = functionalizer.flags_ad_config()
        sig = tuple((n, np.shape(v), str(np.asarray(v).dtype))
                    for n, v in sorted(feeds.items()))
        ssig = tuple((n, np.shape(v), str(v.dtype))
                     for n, v in sorted(state_in.items()))
        mkey = ("aotcc", id(program), program._version, sig, ssig,
                fetch_ext, persistables, amp_enabled(), wga, remat)
        fn = self._cache.get(mkey)
        if fn is False:
            return None
        if fn is not None:
            return fn
        try:
            fp = {
                "kind": "executor_step",
                "program": cc.program_fingerprint(program),
                "feeds": [[n, list(s), d] for n, s, d in sig],
                "state": [[n, list(s), d] for n, s, d in ssig],
                "fetches": list(fetch_ext),
                "persistables": list(persistables),
                "amp": bool(amp_enabled()),
                "wga": bool(wga),
                "remat": remat or "",
                "env": cc.environment_fingerprint(dev),
            }
            cache = cc.default_cache()
            blob = cache.get(fp) if cache is not None else None
            if blob is not None:
                try:
                    t0 = _time.monotonic()
                    fn = jax.jit(jax_export.deserialize(blob).call)
                    cc.note_deserialize_ms(
                        (_time.monotonic() - t0) * 1000.0)
                except Exception:
                    blob = None
            if blob is None:
                t0 = _time.monotonic()
                step_fn = functionalizer.build_step_fn(
                    program, feed_key, fetch_ext, persistables,
                    whole_graph_ad=wga, remat_policy=remat)
                f_spec = {n: jax.ShapeDtypeStruct(np.shape(v),
                                                  np.asarray(v).dtype)
                          for n, v in feeds.items()}
                s_spec = {n: jax.ShapeDtypeStruct(np.shape(v), v.dtype)
                          for n, v in state_in.items()}
                exp = jax_export.export(jax.jit(step_fn))(
                    s_spec, f_spec,
                    jax.ShapeDtypeStruct((), np.uint32))
                cc.note_compile_ms((_time.monotonic() - t0) * 1000.0)
                if cache is not None:
                    cache.put(fp, exp.serialize())
                fn = jax.jit(exp.call)
        except Exception:
            # ineligible in practice (host callback, exotic lowering):
            # remember per signature and fall back silently
            self._cache[mkey] = False
            return None
        self._cache[mkey] = fn
        return fn

    def _host_ops_cached(self, program):
        """(contains_host_ops, has_subblock_host_ops) memoized per
        (program identity, version)."""
        hkey = (id(program), program._version)
        cached = self._host_op_cache.get(hkey)
        if cached is None:
            cached = (functionalizer.contains_host_ops(program),
                      functionalizer.has_subblock_host_ops(program))
            self._host_op_cache[hkey] = cached
        return cached

    def _prepare_feeds(self, program, feed):
        return prepare_feeds(program, feed)

    @staticmethod
    def _dispatch(call, watchdog_secs, what="executor step"):
        """Run one device dispatch, under the wall-clock watchdog when
        FLAGS.step_watchdog_secs is set.  The watchdog forces a
        block_until_ready inside the watched call — async dispatch would
        otherwise return before the hang."""
        if watchdog_secs and watchdog_secs > 0:
            def _synced():
                import jax
                out = call()
                jax.block_until_ready(out)
                return out
            return _watchdog_call(_synced, watchdog_secs, what)
        return call()


    def run_loop(self, program=None, feed=None, fetch_list=None,
                 steps=1, scope=None, return_numpy=True):
        """Run `steps` training steps as ONE device computation — a
        lax.fori_loop over the jitted step body with a constant feed —
        and return the LAST step's fetches. The TPU-idiomatic device-side
        loop: one host->device dispatch per `steps` steps instead of per
        step, so throughput is not bounded by host/relay round-trips
        (reference analogue: the while_op + reader-op training loops that
        kept the GPU busy without per-step feeds, fluid_benchmark.py
        --use_reader_op).

        The per-op RNG streams still fold the step counter, so dropout
        masks differ across iterations exactly as under run(). Programs
        containing host ops cannot run as one computation and are
        rejected loudly.
        """
        import jax
        import jax.numpy as jnp
        if program is None:
            program = default_main_program()
        if feed is None:
            feed = {}
        if fetch_list is None:
            fetch_list = []
        if scope is None:
            scope = global_scope()
        steps = int(steps)
        if steps < 1:
            raise ValueError("run_loop: steps must be >= 1")
        from ..flags import FLAGS
        if FLAGS.verify_program:
            from ..analysis import verify_program_cached
            verify_program_cached(
                program, feeds=sorted(feed),
                fetches=[_fetch_name(f) for f in fetch_list],
                what="executor run_loop program")
        if FLAGS.check_nan_inf:
            raise RuntimeError(
                "run_loop: FLAGS.check_nan_inf needs per-op attribution, "
                "which requires per-step execution — use Executor.run")
        if self._host_ops_cached(program)[0]:
            raise RuntimeError(
                "run_loop: the program contains host ops (RPC/IO/python "
                "callbacks) and cannot run as one device computation — "
                "use Executor.run per step")

        fetch_names = tuple(_fetch_name(f) for f in fetch_list)
        feeds = self._prepare_feeds(program, feed)
        feed_key = tuple(sorted(feeds.keys()))
        lod_fetch = tuple(n + functionalizer.LOD_LEN_SUFFIX
                          for n in fetch_names)
        seg_fetch = tuple(n + functionalizer.LOD_SEG_SUFFIX
                          for n in fetch_names)
        fetch_ext = fetch_names + lod_fetch + seg_fetch
        persistables = tuple(functionalizer.persistable_names(program))
        state_in = {n: scope.get(n) for n in persistables
                    if scope.has(n) and scope.get(n) is not None}
        step0 = self._step_counters.get(id(program), 0)

        from ..ops.registry import amp_enabled
        wga, remat = functionalizer.flags_ad_config()
        key = ("loop", id(program), program._version, feed_key, fetch_ext,
               persistables, amp_enabled(), wga, remat)
        fn = self._cache.get(key)
        if fn is None:
            step_fn = functionalizer.build_step_fn(
                program, feed_key, fetch_ext, persistables,
                whole_graph_ad=wga, remat_policy=remat)
            dev = self._device()
            fn = functionalizer.jit_loop(
                step_fn, dev is not None and dev.platform == "tpu")
            self._cache[key] = fn
        # watchdog budget scales with the loop length: wd secs per step
        fetches, new_state = self._dispatch(
            lambda: fn(state_in, feeds, np.uint32(step0), np.int32(steps)),
            FLAGS.step_watchdog_secs * steps,
            "run_loop dispatch (%d steps)" % steps)
        # only a successful dispatch advances the counter — a build or
        # compile failure must not skew the RNG step fold for later runs
        self._step_counters[id(program)] = step0 + steps
        if FLAGS.benchmark:
            jax.block_until_ready((fetches, new_state))
        for n, val in new_state.items():
            scope.set(n, val)
        return self._post_fetches(fetch_names, lod_fetch, seg_fetch,
                                  fetches, return_numpy)

    def run(self, program=None, feed=None, fetch_list=None,
            feed_var_name="feed", fetch_var_name="fetch", scope=None,
            return_numpy=True, use_program_cache=True, as_future=False):
        """One training/eval step.  With `as_future=True` the step is
        DISPATCHED but not resolved: the return value is a FetchFuture
        holding the fetches as live device arrays, and the host sync
        (one batched jax.device_get) happens when the caller drains it
        via `.result()` — the in-flight dispatch mode of the async
        training pipeline (PIPELINE.md).  State updates land in the
        scope immediately as (unresolved) device arrays, so back-to-back
        dispatches chain on device without host round-trips.  Paths
        that are inherently synchronous (FLAGS.check_nan_inf, host-op
        programs, FLAGS.benchmark) still honor the contract by
        returning an already-resolved future."""
        if program is None:
            program = default_main_program()
        if feed is None:
            feed = {}
        if fetch_list is None:
            fetch_list = []
        if scope is None:
            scope = global_scope()

        fetch_names = tuple(_fetch_name(f) for f in fetch_list)

        feeds = self._prepare_feeds(program, feed)
        feed_key = tuple(sorted(feeds.keys()))

        # for ragged fetches, also fetch the companion lengths (present in
        # env only when the value is actually ragged; None otherwise)
        lod_fetch = tuple(n + functionalizer.LOD_LEN_SUFFIX
                          for n in fetch_names)
        seg_fetch = tuple(n + functionalizer.LOD_SEG_SUFFIX
                          for n in fetch_names)
        fetch_ext = fetch_names + lod_fetch + seg_fetch

        # output state covers ALL persistables (startup programs create
        # params that are not yet in the scope); input state is whatever
        # already exists. The jit signature keys on the input dict structure.
        persistables = tuple(functionalizer.persistable_names(program))
        has_host, has_sub_host = self._host_ops_cached(program)
        hkey = (id(program), program._version)
        from ..flags import FLAGS
        if FLAGS.verify_program:
            # opt-in pre-run verification (ANALYSIS.md): memoized per
            # (program version, feeds, fetches) — the analysis runs at
            # build time, every later step costs one dict hit
            from ..analysis import verify_program_cached
            verify_program_cached(program, feeds=sorted(feed),
                                  fetches=fetch_names,
                                  what="executor program")
        state_in = {n: scope.get(n) for n in persistables
                    if scope.has(n) and scope.get(n) is not None}
        step = self._step_counters.get(id(program), 0)
        self._step_counters[id(program)] = step + 1

        if FLAGS.check_nan_inf or (has_host and has_sub_host):
            # Fully-eager interpretation, two cases:
            # (a) check_nan_inf debugging mode: every op's output is
            #     concrete so the first non-finite op is NAMED (reference
            #     FLAGS_check_nan_inf, operator.cc:29, per-op-sync cost);
            # (b) host ops buried in control-flow sub-blocks — they cannot
            #     be partitioned out at block-0 boundaries, so the whole
            #     block is interpreted (host ops see concrete values).
            ekey = ("eager", hkey, feed_key, fetch_ext, persistables)
            fn = self._cache.get(ekey)
            if fn is None:
                fn = functionalizer.build_step_fn(
                    program, feed_key, fetch_ext, persistables)
                self._cache[ekey] = fn
            fetches, new_state = self._dispatch(
                lambda: fn(state_in, feeds, np.uint32(step)),
                FLAGS.step_watchdog_secs, "eager executor step")
        elif has_host:
            # RPC / IO host ops do side effects, but the compute BETWEEN
            # them still runs from the XLA jit cache: the segmented runner
            # partitions the block at HOST_OPS boundaries (SURVEY §7 step
            # 3), jits each compute segment, and interprets host ops
            # eagerly in order (reference: ListenAndServOp/save_op kernels
            # ran on CPU between device kernels).
            runner = self._cache.get(("seg", hkey))
            if runner is None:
                runner = functionalizer.SegmentedProgramRunner(program)
                self._cache[("seg", hkey)] = runner
            env = {}
            env.update(state_in)
            env.update(feeds)
            self._dispatch(
                lambda: runner.run(env, np.uint32(step),
                                   fetch_names=fetch_ext),
                FLAGS.step_watchdog_secs, "segmented executor step")
            fetches = [env.get(n) for n in fetch_ext]
            new_state = {n: env[n] for n in persistables if n in env}
        else:
            fn = None
            if FLAGS.executor_compile_cache:
                # inference-side persistent compile cache (opt-in): a
                # program whose fingerprint derives from its content
                # rides a stored executable across processes
                fn = self._get_aot_cached(program, feed_key, fetch_ext,
                                          persistables, state_in, feeds)
            if fn is None:
                fn = self._get_jitted(program, feed_key, fetch_ext,
                                      persistables)
            # in-flight mode: the dispatch is non-blocking by design and
            # the watchdog wraps the DRAIN (FetchFuture.result) instead
            # of forcing a block_until_ready inside every dispatch
            wd = 0 if as_future else FLAGS.step_watchdog_secs
            fetches, new_state = self._dispatch(
                lambda: fn(state_in, feeds, np.uint32(step)),
                wd, "jitted executor step")
        if FLAGS.benchmark:
            # reference FLAGS_benchmark: force device sync per step so
            # wall-clock timing around run() is honest (scope.cc:25)
            import jax as _jax
            _jax.block_until_ready((fetches, new_state))
        if FLAGS.check_nan_inf:
            _check_nan_inf(fetch_names, fetches, new_state)
        for n, val in new_state.items():
            scope.set(n, val)
        if as_future:
            post = (lambda vals, rn: self._post_fetches(
                fetch_names, lod_fetch, seg_fetch, vals, rn))
            fut = FetchFuture(fetches, post=post,
                              return_numpy=return_numpy,
                              what="executor step drain")
            if FLAGS.benchmark or FLAGS.check_nan_inf:
                # these modes already forced per-step sync semantics —
                # hand back a resolved future so the caller's drain is
                # a no-op rather than a second conversion site
                fut.result()
            return fut
        return self._post_fetches(fetch_names, lod_fetch, seg_fetch,
                                  fetches, return_numpy)

    @staticmethod
    def _post_fetches(fetch_names, lod_fetch, seg_fetch, fetches,
                      return_numpy):
        """Reassemble fetched values; ragged ones (with @LOD_LEN
        companions) become LoDTensors, nested levels from @LOD_SEG.
        The device->host copy is ONE batched jax.device_get over every
        fetch of the step, not a per-item np.asarray loop — serial
        transfers cost a host round-trip each."""
        if return_numpy and any(f is not None for f in fetches):
            import jax
            fetches = jax.device_get(list(fetches))
        n_names = len(fetch_names)
        lens_by_name = dict(zip(lod_fetch,
                                fetches[n_names:n_names + len(lod_fetch)]))
        segs_by_name = dict(zip(seg_fetch,
                                fetches[n_names + len(lod_fetch):]))
        out = []
        for i, n in enumerate(fetch_names):
            val = fetches[i]
            lens = lens_by_name.get(n + functionalizer.LOD_LEN_SUFFIX)
            if lens is not None and val is not None:
                from .lod import unpad_to_lod_tensor
                t = unpad_to_lod_tensor(np.asarray(val), np.asarray(lens))
                seg = segs_by_name.get(n + functionalizer.LOD_SEG_SUFFIX)
                if seg is not None:
                    # nested: prepend the outer level — the companion IS
                    # the per-group inner-sequence counts
                    outer = [int(c) for c in np.asarray(seg)]
                    t.set_recursive_sequence_lengths(
                        [outer] + t.recursive_sequence_lengths())
                out.append(t)
            elif return_numpy:
                out.append(np.asarray(val))
            else:
                out.append(val)
        return out

    def segmented_runner(self, program):
        """The SegmentedProgramRunner used for `program` (None if the
        program has no host ops or hasn't run yet). Exposes cache_hits /
        cache_misses / num_compute_segments for observability + tests."""
        return self._cache.get(("seg", (id(program), program._version)))

    # ---- parity shims used by reference scripts ----
    def _run_startup(self, startup_program, scope=None):
        self.run(program=startup_program, scope=scope)
