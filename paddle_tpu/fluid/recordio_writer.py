"""RecordIO dataset conversion (reference python/paddle/fluid/
recordio_writer.py: convert_reader_to_recordio_file) over the C++
recordio/tensor-serde layer (native/recordio.cc, native/tensor_serde.cc)."""

import struct

import numpy as np

from ..native import (RecordIOWriter, RecordIOScanner, serialize_tensor,
                      deserialize_tensor)

__all__ = ["convert_reader_to_recordio_file",
           "convert_reader_to_recordio_files", "recordio_reader"]


def _serialize_sample(sample):
    """One record = one sample = count prefix + per-field length-framed
    tensor records. The single place that defines the record layout —
    recordio_reader inverts it."""
    if not isinstance(sample, (tuple, list)):
        sample = (sample,)
    parts = [struct.pack("<I", len(sample))]
    for field in sample:
        t = serialize_tensor(np.asarray(field))
        parts.append(struct.pack("<Q", len(t)))
        parts.append(t)
    return b"".join(parts)


def convert_reader_to_recordio_file(filename, reader_creator, feeder=None,
                                    compressor=None, max_num_records=1000,
                                    feed_order=None):
    """Serialize every sample (tuple of arrays) from the reader into one
    recordio file. Returns number of records written."""
    count = 0
    with RecordIOWriter(filename, max_chunk_records=max_num_records) as w:
        for sample in reader_creator():
            w.write(_serialize_sample(sample))
            count += 1
    return count


def convert_reader_to_recordio_files(filename, batch_per_file,
                                     reader_creator, feeder=None,
                                     compressor=None, max_num_records=1000,
                                     feed_order=None):
    """Shard a reader across many recordio files, at most
    `batch_per_file` records each (reference recordio_writer.py:91:
    '<stem>-00000<ext>', '<stem>-00001<ext>', ...). Returns the list of
    files written."""
    import os
    stem, ext = os.path.splitext(filename)
    files = []
    writer = None
    in_file = 0
    idx = 0
    try:
        for sample in reader_creator():
            if writer is None or in_file >= batch_per_file:
                if writer is not None:
                    writer.close()
                path = "%s-%05d%s" % (stem, idx, ext)
                writer = RecordIOWriter(path,
                                        max_chunk_records=max_num_records)
                files.append(path)
                idx += 1
                in_file = 0
            writer.write(_serialize_sample(sample))
            in_file += 1
    finally:
        if writer is not None:
            writer.close()
    return files


def recordio_reader(filename):
    """Reader creator over a recordio file (reference open_files /
    recordio reader ops, operators/reader/)."""

    def reader():
        with RecordIOScanner(filename) as s:
            for rec in s:
                (n,) = struct.unpack_from("<I", rec, 0)
                off = 4
                fields = []
                for _ in range(n):
                    (ln,) = struct.unpack_from("<Q", rec, off)
                    off += 8
                    arr, _lod = deserialize_tensor(rec[off:off + ln])
                    off += ln
                    fields.append(arr)
                yield tuple(fields) if len(fields) > 1 else fields[0]

    return reader
