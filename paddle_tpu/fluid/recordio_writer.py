"""RecordIO dataset conversion (reference python/paddle/fluid/
recordio_writer.py: convert_reader_to_recordio_file) over the C++
recordio/tensor-serde layer (native/recordio.cc, native/tensor_serde.cc)."""

import numpy as np

from ..native import (RecordIOWriter, RecordIOScanner, serialize_tensor,
                      deserialize_tensor)

__all__ = ["convert_reader_to_recordio_file", "recordio_reader"]


def convert_reader_to_recordio_file(filename, reader_creator, feeder=None,
                                    compressor=None, max_num_records=1000,
                                    feed_order=None):
    """Serialize every sample (tuple of arrays) from the reader into one
    recordio file; one record = one sample = concatenated tensor records
    with a count prefix. Returns number of records written."""
    import struct
    count = 0
    with RecordIOWriter(filename, max_chunk_records=max_num_records) as w:
        for sample in reader_creator():
            if not isinstance(sample, (tuple, list)):
                sample = (sample,)
            parts = [struct.pack("<I", len(sample))]
            for field in sample:
                arr = np.asarray(field)
                t = serialize_tensor(arr)
                parts.append(struct.pack("<Q", len(t)))
                parts.append(t)
            w.write(b"".join(parts))
            count += 1
    return count


def recordio_reader(filename):
    """Reader creator over a recordio file (reference open_files /
    recordio reader ops, operators/reader/)."""
    import struct

    def reader():
        with RecordIOScanner(filename) as s:
            for rec in s:
                (n,) = struct.unpack_from("<I", rec, 0)
                off = 4
                fields = []
                for _ in range(n):
                    (ln,) = struct.unpack_from("<Q", rec, off)
                    off += 8
                    arr, _lod = deserialize_tensor(rec[off:off + ln])
                    off += ln
                    fields.append(arr)
                yield tuple(fields) if len(fields) > 1 else fields[0]

    return reader
