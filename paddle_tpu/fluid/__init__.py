"""paddle_tpu.fluid — the Fluid-compatible user API, TPU-native underneath.

Mirrors python/paddle/fluid/__init__.py of the reference: Program/Executor/
layers/optimizer/backward/io surface, with execution via XLA jit instead of
per-op kernel dispatch. See SURVEY.md §7 for the design stance.
"""

from . import core
from . import framework
from .framework import (  # noqa: F401
    Program, Operator, Variable, Parameter, default_startup_program,
    default_main_program, program_guard, name_scope, in_dygraph_mode,
)
from . import executor
from .executor import Executor, global_scope, scope_guard, Scope  # noqa: F401
from . import pipeline  # noqa: F401  (async step pipeline, PIPELINE.md)
from .pipeline import FetchFuture, DispatchPipeline  # noqa: F401
from . import layers
from . import initializer
from . import optimizer
from . import backward
from .backward import append_backward, gradients  # noqa: F401
from . import regularizer
from . import clip
from .clip import (  # noqa: F401
    ErrorClipByValue, GradientClipByValue, GradientClipByNorm,
    GradientClipByGlobalNorm,
)
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from .layer_helper import LayerHelper  # noqa: F401
from . import unique_name
from .core import CPUPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace  # noqa: F401
from .initializer import Constant, Normal, Uniform, Xavier, MSRA  # noqa: F401

# populated by later milestones; imported lazily to keep import cheap
from . import lod  # noqa: F401
from .lod import (LoDTensor, create_lod_tensor,  # noqa: F401
                  create_random_int_lodtensor)
from . import recordio_writer  # noqa: F401
from .layers import learning_rate_scheduler as learning_rate_decay  # noqa: F401,E501
from . import io
from .io import (  # noqa: F401
    save_vars, save_params, save_persistables, load_vars, load_params,
    load_persistables, save_inference_model, load_inference_model,
)
from . import parallel_executor
from .parallel_executor import (  # noqa: F401
    ParallelExecutor, ExecutionStrategy, BuildStrategy,
)
from . import data_feeder
from .data_feeder import DataFeeder  # noqa: F401
from . import transpiler
from .transpiler import (  # noqa: F401
    DistributeTranspiler, DistributeTranspilerConfig, memory_optimize,
    release_memory, InferenceTranspiler,
)
from . import metrics
from . import profiler
from . import nets
from ..ops.registry import set_amp, amp_enabled  # noqa: F401  (bf16 AMP)
from .. import flags  # noqa: F401  (typed runtime flags, env-ingested)
from ..flags import set_flags, get_flags, FLAGS  # noqa: F401
from . import ir_passes
from . import average
from . import evaluator
from . import debugger
from . import contrib
from . import checkpoint  # noqa: F401  (atomic CRC checkpoint vault)
from . import sentinel    # noqa: F401  (NaN/Inf anomaly sentinel)
from .. import analysis   # noqa: F401  (registers the verify_* passes
#                                        on the ir_passes substrate)

__all__ = [
    "Program", "Operator", "Variable", "Parameter",
    "default_startup_program", "default_main_program", "program_guard",
    "name_scope", "Executor", "global_scope", "scope_guard", "Scope",
    "layers", "initializer", "optimizer", "backward", "regularizer", "clip",
    "append_backward", "gradients", "ParamAttr", "WeightNormParamAttr",
    "LayerHelper", "unique_name", "CPUPlace", "TPUPlace", "CUDAPlace",
    "CUDAPinnedPlace", "core", "io", "save_inference_model",
    "load_inference_model", "ParallelExecutor", "ExecutionStrategy",
    "BuildStrategy", "DataFeeder", "metrics", "profiler", "nets",
    "LoDTensor", "create_lod_tensor", "transpiler", "DistributeTranspiler",
    "DistributeTranspilerConfig", "memory_optimize", "release_memory",
    "InferenceTranspiler", "average", "evaluator", "debugger", "contrib",
    "set_amp", "amp_enabled", "ir_passes",
    "flags", "set_flags", "get_flags", "FLAGS",
    "concurrency", "Go", "make_channel", "channel_send", "channel_recv",
    "channel_close", "LoDTensorArray", "Tensor", "recordio_writer",
    "learning_rate_decay", "create_random_int_lodtensor", "Trainer",
    "Inferencer", "checkpoint", "sentinel",
]

# reference top-level aliases: the fluid package re-exported the contrib
# Trainer/Inferencer and the core tensor types at its root
Tensor = LoDTensor                        # reference: Tensor aliases the
                                          # LoD-carrying dense tensor
LoDTensorArray = list                     # LOD_TENSOR_ARRAY: python list
Trainer = contrib.Trainer
Inferencer = contrib.Inferencer
from . import concurrency  # noqa: E402
from .concurrency import (  # noqa: F401,E402
    Go, make_channel, channel_send, channel_recv, channel_close)
