"""Checkpoint / model save-load.

Reference analogue: python/paddle/fluid/io.py — save/load_vars/params/
persistables (:89-:505) driving in-graph save/load ops (operators/save_op.cc),
save_inference_model (:544 prune + feed/fetch + serialize),
load_inference_model (:674).

TPU redesign: variables live in the Scope as jax Arrays; save/load is a host
round-trip to .npz shards plus the serialized Program, which keeps the
reference's directory layout (one file per var, or a single combined file
with save_combine semantics). Orbax-style sharded checkpointing for the
multi-chip path lands with the parallel milestone.
"""

import json
import os

import numpy as np

from .framework import Program, Parameter, default_main_program, Variable
from .executor import global_scope

__all__ = [
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "save_checkpoint", "load_checkpoint",
]


def _var_list(main_program, predicate):
    return [v for v in main_program.global_block().vars.values()
            if predicate(v)]


def is_persistable(var):
    return bool(var.persistable)


def is_parameter(var):
    return isinstance(var, Parameter)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """reference io.py:89. One .npy per var, or a single .npz when
    `filename` is given (save_combine semantics)."""
    if main_program is None:
        main_program = default_main_program()
    if vars is None:
        vars = _var_list(main_program, predicate or is_persistable)
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    if filename is not None:
        arrays = {}
        for v in vars:
            val = scope.get(v.name if isinstance(v, Variable) else v)
            if val is not None:
                arrays[v.name] = np.asarray(val)
        # write through a file handle so np.savez cannot append '.npz' —
        # the exact given filename must round-trip through load_vars
        with open(os.path.join(dirname, filename), "wb") as f:
            np.savez(f, **arrays)
        return
    for v in vars:
        name = v.name if isinstance(v, Variable) else v
        val = scope.get(name)
        if val is None:
            continue
        np.save(os.path.join(dirname, name.replace("/", "__") + ".npy"),
                np.asarray(val))


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=is_parameter, filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=is_persistable, filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    if main_program is None:
        main_program = default_main_program()
    if vars is None:
        vars = _var_list(main_program, predicate or is_persistable)
    scope = global_scope()
    import jax.numpy as jnp
    if filename is not None:
        data = np.load(os.path.join(dirname, filename))
        for v in vars:
            if v.name in data:
                scope.set(v.name, jnp.asarray(data[v.name]))
        return
    for v in vars:
        name = v.name if isinstance(v, Variable) else v
        path = os.path.join(dirname, name.replace("/", "__") + ".npy")
        if os.path.exists(path):
            scope.set(name, jnp.asarray(np.load(path)))


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program,
                     predicate=is_parameter, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program,
                     predicate=is_persistable, filename=filename)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True):
    """reference io.py:544: prune program to the inference subgraph, save
    program + params."""
    if main_program is None:
        main_program = default_main_program()
    if isinstance(target_vars, Variable):
        target_vars = [target_vars]
    os.makedirs(dirname, exist_ok=True)
    pruned = main_program.clone(for_test=True)
    pruned = pruned._prune(feeded_var_names,
                           [v.name for v in target_vars])
    # the artifact boundary verifies unconditionally (ANALYSIS.md): a
    # broken graph must fail HERE, at build time, not in whatever server
    # loads the artifact later — error findings raise, warnings warn.
    # Memoized on the serialized content: re-saving identical bytes
    # (bench loops, registry round-trips) costs one dict hit.
    serialized = pruned.serialize_to_string()
    from ..analysis import check_serialized_cached
    check_serialized_cached(pruned, serialized,
                            feeds=feeded_var_names,
                            fetches=[v.name for v in target_vars],
                            what="save_inference_model(%r)" % dirname)
    meta = {
        "program": serialized,
        "feed_names": list(feeded_var_names),
        "fetch_names": [v.name for v in target_vars],
    }
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename), "w") as f:
        json.dump(meta, f)
    # persistables of the PRUNED program, not just Parameters: BN moving
    # statistics must ship with the model, while optimizer accumulators
    # (pruned away) must not (reference io.py:544)
    save_persistables(executor, dirname, pruned,
                      filename=params_filename)
    return [v.name for v in target_vars]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    """reference io.py:674 -> (program, feed_names, fetch_vars)."""
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename)) as f:
        meta = json.load(f)
    program = Program.parse_from_string(meta["program"])
    # verify unconditionally at the load boundary: an artifact edited,
    # truncated, or produced by an older/divergent builder must be
    # rejected with block/op/var diagnostics before any compile is paid.
    # Content-memoized: a hot-swap flip / replica build re-loading the
    # same artifact bytes verifies once, every repeat is a dict hit.
    from ..analysis import check_serialized_cached
    check_serialized_cached(program, meta["program"],
                            feeds=meta["feed_names"],
                            fetches=meta["fetch_names"],
                            what="load_inference_model(%r)" % dirname)
    # quantized artifact (QUANTIZE.md): the int8 payloads and scale
    # tables CRC-verify against quant_meta.bin BEFORE any weight loads
    # — a tampered payload is rejected naming the corrupt file, the
    # same at-load discipline the verifier gives the Program half
    if os.path.exists(os.path.join(dirname, "quant_meta.bin")):
        from ..inference.quantize import check_quantized_dir
        check_quantized_dir(dirname)
    # load params into scope under the program's var names
    vars = [v for v in program.global_block().vars.values()
            if isinstance(v, Parameter) or v.persistable]
    load_vars(executor, dirname, program, vars=vars,
              filename=params_filename)
    fetch_vars = [program.global_block().var(n)
                  for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars


def _persistable_arrays(main_program, scope):
    if main_program is None:
        main_program = default_main_program()
    arrays = {}
    for v in _var_list(main_program, is_persistable):
        val = scope.get(v.name)
        if val is not None:
            arrays[v.name] = val
    return arrays


def save_checkpoint(executor, dirname, main_program=None, step=None,
                    epoch=None, epoch_step=None, max_num_checkpoints=None,
                    async_save=False):
    """Atomic CRC-manifest checkpoint into `dirname` (the vault root):
    `checkpoint_<step>/` + `latest` pointer + keep-N rotation — see
    fluid/checkpoint.py for the commit protocol (reference
    CheckpointConfig auto-save, contrib trainer.py:100; Go pserver CRC
    checkpoint go/pserver/service.go:119).

    `step` may be the canonical int global step, or a legacy
    ``{"epoch", "step"}`` dict (normalized); `epoch`/`epoch_step`
    override/extend the meta.  With `async_save`, the commit happens on
    the background saver thread (checkpoint.wait_for_async_saves joins).
    Returns the meta dict actually written."""
    from . import checkpoint as ckpt
    meta = ckpt.normalize_meta(step)
    if epoch is not None:
        meta["epoch"] = int(epoch)
    if epoch_step is not None:
        meta["epoch_step"] = int(epoch_step)
    arrays = _persistable_arrays(main_program, global_scope())
    if async_save:
        ckpt.async_saver().submit(dirname, arrays, meta,
                                  max_num_checkpoints=max_num_checkpoints)
    else:
        ckpt.save_checkpoint_dir(dirname, arrays, meta,
                                 max_num_checkpoints=max_num_checkpoints)
    return meta


def load_checkpoint(executor, dirname, main_program=None):
    """Load the newest committed checkpoint under `dirname` (or `dirname`
    itself when it is a single checkpoint_<n> dir, or a legacy flat
    `__checkpoint__.npz` layout), CRC-verifying every shard.  Returns the
    normalized ``{"epoch", "step", ...}`` meta dict.  Raises
    FileNotFoundError when nothing is there and
    CheckpointCorruptionError when a shard fails verification."""
    from . import checkpoint as ckpt
    import jax.numpy as jnp
    if main_program is None:
        main_program = default_main_program()
    target = None
    if os.path.exists(os.path.join(dirname, ckpt.MANIFEST_NAME)):
        target = dirname
    else:
        target = ckpt.latest_checkpoint(dirname)
    if target is None:
        # legacy flat layout (pre-vault saves)
        legacy = os.path.join(dirname, "__checkpoint__.npz")
        if not os.path.exists(legacy):
            raise FileNotFoundError(
                "no checkpoint under %s (no 'latest' pointer, no "
                "checkpoint_<step>/ dir, no legacy __checkpoint__.npz)"
                % dirname)
        load_persistables(executor, dirname, main_program,
                          filename="__checkpoint__.npz")
        meta_path = os.path.join(dirname, "__meta__.json")
        raw = None
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                raw = json.load(f).get("step")
        return ckpt.normalize_meta(raw)
    scope = global_scope()
    wanted = frozenset(
        v.name for v in _var_list(main_program, is_persistable))
    arrays, meta = ckpt.load_checkpoint_dir(target, names=wanted)
    for name, arr in arrays.items():
        scope.set(name, jnp.asarray(arr))
    return meta
