"""DistributeTranspiler — rewrite a local training Program for distributed
roles.

Reference analogue: python/paddle/fluid/transpiler/distribute_transpiler.py —
`transpile` (:239) slices params/grads into blocks (slice_variable :80),
inserts send/recv/barrier ops into the trainer program, builds per-pserver
programs (`get_pserver_program` :592) whose optimizer ops run inside a
listen_and_serv event loop; nccl2 mode (`_transpile_nccl2` :212) only inserts
gen_nccl_id for collective bootstrap.

TPU redesign:
- **Collective mode is primary** (config.mode == "collective" / "nccl2"):
  the rewrite inserts one `gen_collective_id` bootstrap op (lowered to
  jax.distributed.initialize — the gen_nccl_id analogue, SURVEY.md §2.3) and
  tags the program with (num_trainers, trainer_id) so ParallelExecutor builds
  a global device mesh; gradients are then reduced by XLA AllReduce over
  ICI/DCN exactly where the reference used NCCL rings.
- **PServer mode** performs the same structural split as the reference so
  sparse/lookup-table workloads and the test strategy (test_dist_transpiler)
  carry over. The produced programs contain host-side RPC ops (send/recv/
  listen_and_serv) executed by the eager executor path over a TCP variable
  server (paddle_tpu/distributed/rpc.py).
"""

import math

from ..framework import Program, Parameter, default_main_program, Variable
from .ps_dispatcher import RoundRobin, PSDispatcher

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "slice_variable"]

# op types whose Param/Grad slots define the param<->grad pairing
OPTIMIZER_OP_TYPES = frozenset([
    "sgd", "momentum", "lars_momentum", "adam", "adamax", "adagrad",
    "decayed_adagrad", "adadelta", "rmsprop", "ftrl", "proximal_gd",
])

RPC_OP_ROLE_ATTR = "op_role"
RPC_OP_ROLE_VALUE = "RPC"


class DistributeTranspilerConfig:
    """reference distribute_transpiler.py:126.

    slice_var_up: split large variables into blocks spread over pservers.
    split_method: PSDispatcher subclass.
    min_block_size: smallest slice, in elements (reference: 8192).
    mode: "pserver" | "collective" ("nccl2" accepted as an alias).
    """

    slice_var_up = True
    split_method = RoundRobin
    min_block_size = 8192
    mode = "pserver"
    sync_mode = True
    # delay-compensated async SGD (reference :140 enable_dc_asgd +
    # listen_and_serv_op.cc:342 dc_asgd handlers): only meaningful with
    # sync_mode=False
    enable_dc_asgd = False


class VarBlock:
    def __init__(self, varname, offset, size):
        self.varname = varname
        self.offset = offset
        self.size = size

    def name(self):
        return "%s:%d:%d" % (self.varname, self.offset, self.size)

    def __repr__(self):
        return self.name()


def slice_variable(var_list, slice_count, min_block_size):
    """Split each var into at most `slice_count` flat blocks of at least
    `min_block_size` elements (reference distribute_transpiler.py:80).
    Returns a list of lists of VarBlock."""
    blocks = []
    for var in var_list:
        var_numel = 1
        for d in var.shape:
            var_numel *= max(int(d), 1)
        max_pserver_count = min(slice_count,
                                int(math.floor(var_numel / min_block_size)))
        max_pserver_count = max(max_pserver_count, 1)
        block_size = int(math.ceil(var_numel / float(max_pserver_count)))
        if len(var.shape) >= 2:
            # align by the fastest-varying dimension so each block holds
            # whole rows (the reference's dim1 alignment)
            dim1 = 1
            for d in var.shape[1:]:
                dim1 *= int(d)
            remains = block_size % dim1
            if remains != 0:
                block_size += dim1 - remains
        split_count = int(math.ceil(var_numel / float(block_size)))
        var_blocks = []
        for block_id in range(split_count):
            curr_size = min(block_size, var_numel - block_id * block_size)
            var_blocks.append(VarBlock(var.name, block_id * block_size,
                                       curr_size))
        blocks.append(var_blocks)
    return blocks


def _find_optimize_ops(program):
    """(op, param_name, grad_name) for every optimizer op in the program."""
    found = []
    for op in program.global_block().ops:
        if op.type in OPTIMIZER_OP_TYPES:
            found.append((op, op.input("Param")[0], op.input("Grad")[0]))
    return found


def _is_lr_or_opt_support_op(op, opt_outputs):
    """Ops whose outputs are consumed only by optimizer ops. Heuristic: op
    writes only vars consumed by optimizer ops and not by forward/backward
    compute."""
    outs = set(op.output_arg_names)
    return bool(outs) and outs <= opt_outputs


def _find_lr_ops(program, opt_infos):
    """The LR-schedule chain (reference _get_lr_ops): every op transitively
    producing the optimizer ops' LearningRate inputs (decay math + the
    @LR_DECAY_COUNTER@ increment). These move to the pserver, which runs
    them once per global step — the reference ran them in a dedicated
    lr_decay block inside listen_and_serv."""
    gb = program.global_block()
    needed = set()
    for op, _p, _g in opt_infos:
        needed.update(op.input("LearningRate"))
    lr_ops = []
    changed = True
    seen = set()
    while changed:
        changed = False
        for op in gb.ops:
            if id(op) in seen or op.type in OPTIMIZER_OP_TYPES:
                continue
            if set(op.output_arg_names) & needed:
                # stop if the op reads data/compute vars (LR must be a pure
                # function of persistable state)
                reads_data = any(
                    (v := gb._find_var_recursive(n)) is not None and v.is_data
                    for n in op.input_arg_names)
                if reads_data:
                    continue
                seen.add(id(op))
                lr_ops.append(op)
                needed.update(op.input_arg_names)
                changed = True
    # preserve original program order
    order = {id(op): i for i, op in enumerate(gb.ops)}
    lr_ops.sort(key=lambda op: order[id(op)])
    return lr_ops


class DistributeTranspiler:
    """reference distribute_transpiler.py:239."""

    def __init__(self, config=None):
        self.config = config if config is not None \
            else DistributeTranspilerConfig()

    # ------------------------------------------------------------------
    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, startup_program=None,
                  current_endpoint=""):
        if program is None:
            program = default_main_program()
        self.origin_program = program
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode and self.config.sync_mode

        if self.config.mode in ("collective", "nccl2"):
            self._transpile_collective(trainer_id, program, trainers,
                                       startup_program)
            return

        self.pserver_endpoints = [e for e in pservers.split(",") if e]
        self._transpile_pserver(trainer_id, program, startup_program,
                                current_endpoint)

    # ---- collective ("nccl2") mode -----------------------------------
    def _transpile_collective(self, trainer_id, program, trainers,
                              startup_program):
        """reference _transpile_nccl2 (distribute_transpiler.py:212): the
        only graph change is bootstrap — gen_collective_id lowers to
        jax.distributed.initialize (SURVEY §2.3 TPU row); gradient reduction
        itself comes from running under a global mesh."""
        if startup_program is not None:
            gb = startup_program.global_block()
            gb.create_var(name="CollectiveId", shape=(1,), dtype="int64",
                          persistable=True)
            # PREPENDED: jax.distributed.initialize must run before any op
            # touches the backend (param initializers included), or the
            # process joins the collective world after its devices are
            # already pinned local-only
            gb._prepend_op(
                type="gen_collective_id",
                inputs={}, outputs={"Out": ["CollectiveId"]},
                attrs={"trainer_id": trainer_id,
                       "num_trainers": trainers,
                       RPC_OP_ROLE_ATTR: RPC_OP_ROLE_VALUE})
        program._num_trainers = trainers
        program._trainer_id = trainer_id
        self.trainer_program = program

    # ---- pserver mode -------------------------------------------------
    def _transpile_pserver(self, trainer_id, program, startup_program,
                           current_endpoint):
        eps = self.pserver_endpoints
        opt_infos = _find_optimize_ops(program)
        if not opt_infos:
            raise ValueError("no optimizer ops found; call minimize() "
                             "before transpile()")
        self.param_grad_ep_mapping = {ep: {"params": [], "grads": []}
                                      for ep in eps}
        self._opt_ops_by_param = {}
        gb = program.global_block()

        params, grads = [], []
        for op, pname, gname in opt_infos:
            params.append(gb.var(pname))
            grads.append(gb._find_var_recursive(gname))
            self._opt_ops_by_param[pname] = op
        self._lr_ops = _find_lr_ops(program, opt_infos)
        self._lr_op_uids = {op.uid for op in self._lr_ops}

        # endpoint placement (whole-var granularity; slice metadata is
        # published for API parity and used by the rpc layer for striping)
        dispatcher = self.config.split_method(eps)
        slice_count = len(eps) if self.config.slice_var_up else 1
        grad_blocks = slice_variable(grads, slice_count,
                                     self.config.min_block_size)
        param_blocks = slice_variable(params, slice_count,
                                      self.config.min_block_size)
        self.grad_blocks = [b for bs in grad_blocks for b in bs]
        self.param_blocks = [b for bs in param_blocks for b in bs]

        # endpoint placement is whole-var granularity, so every param/grad
        # crosses the wire as ONE frame; a var bigger than the RPC frame
        # cap would fail deep in the socket layer at step time — fail here
        # instead, naming the variable and the env var that raises the cap
        from ...distributed.rpc import _MAX_FRAME
        for var in params + grads:
            if var is None or var.shape is None:
                continue
            numel = 1
            for d in var.shape:
                numel *= max(int(d), 1)
            frame = numel * var.np_dtype.itemsize + 1024  # wire header
            if frame > _MAX_FRAME:
                raise ValueError(
                    "variable %r needs a ~%d-byte wire frame, above the "
                    "RPC frame cap of %d; export "
                    "PADDLE_TPU_MAX_RPC_FRAME=%d (in every trainer AND "
                    "pserver process) to send it unsliced"
                    % (var.name, frame, _MAX_FRAME,
                       1 << frame.bit_length()))

        self._ep_by_param = {}
        eplist = dispatcher.dispatch(
            [bs[0] for bs in param_blocks])  # one ep per var (first block)
        for (p, g, ep) in zip(params, grads, eplist):
            self._ep_by_param[p.name] = ep
            self.param_grad_ep_mapping[ep]["params"].append(p)
            self.param_grad_ep_mapping[ep]["grads"].append(g)

        # ---- trainer program: strip optimizer (+ its support ops), insert
        # send/barriers/recv
        self.trainer_program = self._build_trainer_program(program)
        if startup_program is not None:
            self.startup_program = startup_program

    def _build_trainer_program(self, program):
        t = Program.parse_from_string(program.serialize_to_string())
        t.random_seed = program.random_seed
        gb = t.global_block()
        opt_ops = [op for op in gb.ops if op.type in OPTIMIZER_OP_TYPES]
        opt_outputs = set()
        for op in opt_ops:
            opt_outputs.update(op.output_arg_names)
            opt_outputs.update(op.input("Param"))
        keep = []
        lr_uids = getattr(self, "_lr_op_uids", set())
        for op in gb.ops:
            if op.type in OPTIMIZER_OP_TYPES:
                continue
            if op.uid in lr_uids:  # LR schedule runs on the pserver
                continue
            if _is_lr_or_opt_support_op(op, opt_outputs):
                continue
            keep.append(op)
        gb.ops = keep

        eps = self.pserver_endpoints
        epmap = self._ep_by_param
        # send each grad to its endpoint
        send_inputs = []
        send_eps = []
        for pname, ep in epmap.items():
            op = self._opt_ops_by_param[pname]
            gname = op.input("Grad")[0]
            send_inputs.append(gname)
            send_eps.append(ep)
        gb.append_op(
            type="send", inputs={"X": send_inputs}, outputs={},
            attrs={"epmap": send_eps, "endpoints": eps,
                   "sync_mode": self.sync_mode,
                   "trainer_id": self.trainer_id,
                   RPC_OP_ROLE_ATTR: RPC_OP_ROLE_VALUE},
            infer_shape=False)
        if self.sync_mode:
            gb.append_op(
                type="send_barrier", inputs={}, outputs={},
                attrs={"endpoints": eps, "trainer_id": self.trainer_id,
                       RPC_OP_ROLE_ATTR: RPC_OP_ROLE_VALUE},
                infer_shape=False)
        # recv updated params back
        recv_outputs = list(epmap.keys())
        gb.append_op(
            type="recv", inputs={},
            outputs={"Out": recv_outputs},
            attrs={"epmap": [epmap[p] for p in recv_outputs],
                   "endpoints": eps, "trainer_id": self.trainer_id,
                   RPC_OP_ROLE_ATTR: RPC_OP_ROLE_VALUE},
            infer_shape=False)
        gb.append_op(
            type="fetch_barrier", inputs={}, outputs={},
            attrs={"endpoints": eps, "trainer_id": self.trainer_id,
                   RPC_OP_ROLE_ATTR: RPC_OP_ROLE_VALUE},
            infer_shape=False)
        return t

    def get_trainer_program(self):
        """reference distribute_transpiler.py:473."""
        return self.trainer_program

    # ------------------------------------------------------------------
    def get_pserver_program(self, endpoint):
        """reference distribute_transpiler.py:592: per-endpoint program whose
        root block holds listen_and_serv; each assigned param's optimizer op
        lives in its own sub-block run on grad arrival."""
        assigned = self.param_grad_ep_mapping[endpoint]["params"]
        pserver_program = Program()
        pgb = pserver_program.global_block()

        origin_gb = self.origin_program.global_block()

        # LR-schedule block: runs ONCE per global step, before the param
        # optimize blocks (the reference's lr_decay block in
        # listen_and_serv)
        lr_block_id = -1
        if getattr(self, "_lr_ops", None):
            with pserver_program._block_guard() as lr_blk:
                for op in self._lr_ops:
                    for name in (op.input_arg_names + op.output_arg_names):
                        src = origin_gb._find_var_recursive(name)
                        if src is not None and name not in pgb.vars:
                            pgb.create_var(name=name, shape=src.shape,
                                           dtype=src.dtype, persistable=True)
                    new_op = lr_blk.append_op(
                        type=op.type, inputs=dict(op.inputs),
                        outputs=dict(op.outputs), attrs=dict(op.attrs),
                        infer_shape=False)
                    new_op.uid = op.uid
                    pserver_program._op_uid = max(
                        pserver_program._op_uid, op.uid)
                lr_block_id = lr_blk.idx

        opt_block_ids = []
        param_names = []
        for p in assigned:
            opt_op = self._opt_ops_by_param[p.name]
            # recreate vars referenced by the optimizer op in the root block
            with pserver_program._block_guard() as blk:
                for name in (opt_op.input_arg_names +
                             opt_op.output_arg_names):
                    src = origin_gb._find_var_recursive(name)
                    if src is None:
                        continue
                    pgb.create_var(
                        name=name, shape=src.shape, dtype=src.dtype,
                        persistable=True)
                blk.append_op(type=opt_op.type, inputs=dict(opt_op.inputs),
                              outputs=dict(opt_op.outputs),
                              attrs=dict(opt_op.attrs), infer_shape=False)
                opt_block_ids.append(blk.idx)
                param_names.append(p.name)

        pgb.append_op(
            type="listen_and_serv", inputs={}, outputs={},
            attrs={"endpoint": endpoint,
                   "optimize_blocks": opt_block_ids,
                   "lr_decay_block_id": lr_block_id,
                   "param_names": param_names,
                   "grad_names": [
                       self._opt_ops_by_param[p].input("Grad")[0]
                       for p in param_names],
                   "Fanin": self.trainer_num,
                   "sync_mode": self.sync_mode,
                   "dc_asgd": bool(self.config.enable_dc_asgd),
                   RPC_OP_ROLE_ATTR: RPC_OP_ROLE_VALUE},
            infer_shape=False)
        return pserver_program

    def get_pserver_programs(self, endpoint):
        """(main_program, startup_program) for one pserver endpoint in a
        single call (reference distribute_transpiler.py:838)."""
        pserver_prog = self.get_pserver_program(endpoint)
        pserver_startup = self.get_startup_program(
            endpoint, pserver_program=pserver_prog)
        return pserver_prog, pserver_startup

    def get_startup_program(self, endpoint, pserver_program=None,
                            startup_program=None):
        """Startup program creating + initializing this endpoint's params
        (reference distribute_transpiler.py get_startup_program)."""
        if startup_program is None:
            # prefer the startup handed to transpile(): programs are often
            # built under their own program_guard, where the process-global
            # default startup is empty
            startup_program = getattr(self, "startup_program", None)
        if startup_program is None:
            from ..framework import default_startup_program
            startup_program = default_startup_program()
        assigned = {p.name for p in
                    self.param_grad_ep_mapping[endpoint]["params"]}
        # also bring optimizer state (moments etc.) for assigned params and
        # the LR-schedule chain's vars (counter init etc.)
        aux = set()
        for pname in assigned:
            op = self._opt_ops_by_param[pname]
            for n in op.input_arg_names + op.output_arg_names:
                aux.add(n)
        for op in getattr(self, "_lr_ops", []):
            aux.update(op.input_arg_names)
            aux.update(op.output_arg_names)
        s = Program()
        s.random_seed = startup_program.random_seed
        sgb = s.global_block()
        src_gb = startup_program.global_block()
        for op in src_gb.ops:
            outs = set(op.output_arg_names)
            if not outs & (assigned | aux):
                continue
            for name in op.output_arg_names + op.input_arg_names:
                v = src_gb._find_var_recursive(name)
                if v is not None and name not in sgb.vars:
                    sgb.create_var(name=name, shape=v.shape, dtype=v.dtype,
                                   persistable=True)
            new_op = sgb.append_op(type=op.type, inputs=dict(op.inputs),
                                   outputs=dict(op.outputs),
                                   attrs=dict(op.attrs), infer_shape=False)
            # keep the source op's uid so random initializers draw the SAME
            # values the trainers drew (per-op rng folds in op.uid) — the
            # reference guaranteed this because pservers ran the original
            # OpDescs; advance the counter so later appends can't collide
            new_op.uid = op.uid
            s._op_uid = max(s._op_uid, op.uid)
        return s
