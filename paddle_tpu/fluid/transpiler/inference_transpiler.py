"""Inference-time graph rewrites.

Reference analogue: transpiler/inference_transpiler.py (:24) — folds
batch_norm into the preceding conv2d/fc (fuse_batch_norm), removes dropout,
and flips is_test attrs, so the saved inference program runs the fused math.

On TPU, XLA would fuse the scale/shift into the conv epilogue anyway, but
folding *removes the BN statistics reads entirely* and shrinks the program,
so the rewrite is still real work — it rewrites conv weights/bias using the
frozen BN statistics at transpile time (constant folding into parameters).
"""

import numpy as np

__all__ = ["InferenceTranspiler"]


class InferenceTranspiler:
    def transpile(self, program, place=None, scope=None):
        """Apply inference rewrites in place (reference :24)."""
        if scope is None:
            from ..executor import global_scope
            scope = global_scope()
        self._remove_dropout(program)
        self._fuse_batch_norm(program, scope)
        # NHWC residual blocks collapse onto the VMEM-resident Pallas
        # kernel (ir_passes.FuseBottleneckPass); NCHW programs are left
        # to XLA's per-conv fusion
        from ..ir_passes import apply_passes
        apply_passes(program, ["fuse_bottleneck_pass"])
        self._set_is_test(program)
        return program

    # ------------------------------------------------------------------
    def _set_is_test(self, program):
        for block in program.blocks:
            for op in block.ops:
                if op.type in ("dropout", "batch_norm", "lrn"):
                    op.attrs["is_test"] = True

    def _remove_dropout(self, program):
        """dropout(is_test) is identity (upscale_in_train) or a fixed scale;
        replace with scale op to keep downstream names intact."""
        for block in program.blocks:
            new_ops = []
            for op in block.ops:
                if op.type != "dropout":
                    new_ops.append(op)
                    continue
                impl = op.attrs.get("dropout_implementation",
                                    "downgrade_in_infer")
                scale = 1.0 if impl == "upscale_in_train" else \
                    1.0 - float(op.attrs.get("dropout_prob", 0.5))
                sop = block.program  # keep handle for clarity
                del sop
                from ..framework import Operator
                new_ops.append(Operator(
                    block, "scale",
                    inputs={"X": op.input("X")},
                    outputs={"Out": op.output("Out")},
                    attrs={"scale": scale, "bias": 0.0,
                           "bias_after_scale": True}))
            block.ops = new_ops

    def _fuse_batch_norm(self, program, scope):
        """conv2d (no act) -> batch_norm  ==>  conv2d with folded W', b'.

        W' = W * gamma / sqrt(var + eps)   (per output channel)
        b' = (b - mean) * gamma / sqrt(var + eps) + beta
        """
        for block in program.blocks:
            producers = {}
            for op in block.ops:
                for name in op.output_arg_names:
                    producers[name] = op
            old_ops = list(block.ops)
            result = []
            for op in old_ops:
                if op.type == "batch_norm":
                    x = op.input("X")[0]
                    prev = producers.get(x)
                    if prev is not None and prev.type == "conv2d" and \
                            self._only_consumer(old_ops, x, op):
                        replacement = self._fold(block, scope, prev, op)
                        if replacement is not None:
                            result.append(replacement)
                            continue
                result.append(op)
            block.ops = result

    def _only_consumer(self, ops, name, consumer):
        uses = 0
        for op in ops:
            if name in op.input_arg_names:
                uses += 1
        return uses == 1

    def _fold(self, block, scope, conv_op, bn_op):
        w_name = conv_op.input("Filter")[0]
        w = scope.get(w_name)
        scale = scope.get(bn_op.input("Scale")[0])
        bias = scope.get(bn_op.input("Bias")[0])
        mean = scope.get(bn_op.input("Mean")[0])
        var = scope.get(bn_op.input("Variance")[0])
        if any(v is None for v in (w, scale, bias, mean, var)):
            return None
        conv_bias = None
        if conv_op.inputs.get("Bias"):
            # BN(conv + b) = inv_std*conv + (beta + (b - mean)*inv_std):
            # the inline bias folds into the new per-channel add and the
            # conv's Bias input is dropped. Without its value the fold
            # would change numerics — decline instead.
            conv_bias = scope.get(conv_op.input("Bias")[0])
            if conv_bias is None:
                return None
        import jax.numpy as jnp
        eps = float(bn_op.attrs.get("epsilon", 1e-5))
        w = jnp.asarray(w)
        inv_std = jnp.asarray(scale) / jnp.sqrt(jnp.asarray(var) + eps)
        # conv filter layout OIHW: fold per output channel O
        scope.set(w_name, w * inv_std.reshape(-1, 1, 1, 1))
        shift = jnp.asarray(mean) if conv_bias is None else \
            jnp.asarray(mean) - jnp.asarray(conv_bias).reshape(-1)
        new_bias = jnp.asarray(bias) - shift * inv_std
        if conv_bias is not None:
            conv_op.inputs.pop("Bias", None)   # absorbed into new_bias
        bias_name = w_name + "@bn_folded_bias"
        block.create_var(
            name=bias_name, shape=tuple(new_bias.shape), dtype="float32",
            persistable=True)
        scope.set(bias_name, new_bias)
        # BN becomes a per-channel bias add on the conv's raw output;
        # the broadcast axis follows the conv's activation layout (the
        # channel dim is 1 for NCHW, trailing for NHWC)
        from ..framework import Operator
        conv_out = conv_op.output("Output")[0]
        bn_out = bn_op.output("Y")[0]
        axis = 1 if conv_op.attrs.get("data_format", "NCHW") == "NCHW" \
            else -1
        return Operator(
            block, "elementwise_add",
            inputs={"X": [conv_out], "Y": [bias_name]},
            outputs={"Out": [bn_out]},
            attrs={"axis": axis})
