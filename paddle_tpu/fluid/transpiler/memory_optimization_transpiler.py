"""Liveness-based variable reuse planning.

Reference analogue: transpiler/memory_optimization_transpiler.py —
`ControlFlowGraph` (:112) computes per-op live-in/live-out sets by iterating
dataflow equations, `memory_optimize` (:456) renames dead vars to reuse their
buffers, `release_memory` (:494) inserts delete ops.

TPU redesign: XLA's buffer assignment already performs in-place reuse inside
a compiled step, so rewriting names buys nothing at runtime. The transpiler
keeps the analysis (it feeds the debugger/memory estimator and preserves the
public API): it computes liveness over the Program, returns the reuse plan,
and records it on the program as `_memory_reuse_plan`. `release_memory`
marks non-persistable fetch-dead vars so the eager host path can drop them
early (the reference's eager-deletion GC, executor.cc:392)."""

from collections import defaultdict

__all__ = ["memory_optimize", "release_memory", "ControlFlowGraph"]

_SKIP_OPS = frozenset(["feed", "fetch", "while", "conditional_block",
                       "recurrent"])


class ControlFlowGraph:
    """Straight-line liveness over one block (reference :112). Successor of
    op i is op i+1 — control-flow sub-blocks are analyzed independently."""

    def __init__(self, block, skip_names=()):
        self.block = block
        self.skip = set(skip_names)
        self.uses = []     # per op: vars read
        self.defs = []     # per op: vars written
        self.live_in = []
        self.live_out = []
        for op in block.ops:
            self.uses.append(set(op.input_arg_names) - self.skip)
            self.defs.append(set(op.output_arg_names) - self.skip)
            self.live_in.append(set())
            self.live_out.append(set())

    def analyze(self):
        n = len(self.block.ops)
        changed = True
        while changed:
            changed = False
            for i in reversed(range(n)):
                out = set(self.live_in[i + 1]) if i + 1 < n else set()
                inn = self.uses[i] | (out - self.defs[i])
                if out != self.live_out[i] or inn != self.live_in[i]:
                    self.live_out[i] = out
                    self.live_in[i] = inn
                    changed = True
        return self

    def dead_after(self, i):
        """Vars whose last use is op i (not live after it)."""
        return (self.uses[i] | self.defs[i]) - self.live_out[i]


def _reusable(var):
    if var is None:
        return False
    if var.persistable or var.is_data:
        return False
    if var.shape is None or any(d is None or int(d) < 0
                                for d in var.shape):
        return False
    return True


def _nbytes(var):
    n = 1
    for d in var.shape:
        n *= int(d)
    return n * var.np_dtype.itemsize


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0):
    """Compute the buffer-reuse plan (reference :456). Returns a list of
    (new_var, reused_var) pairs and stamps `_memory_reuse_plan` on the
    program. The XLA executor treats the plan as advisory."""
    skip = set(skip_opt_set or ())
    plan = []
    for block in input_program.blocks:
        cfg = ControlFlowGraph(block, skip).analyze()
        free_pool = []  # (nbytes, name) of dead buffers
        mapped = set()
        for i, op in enumerate(block.ops):
            if op.type in _SKIP_OPS:
                continue
            for out_name in op.output_arg_names:
                if out_name in skip or out_name in mapped:
                    continue
                var = block._find_var_recursive(out_name)
                if not _reusable(var):
                    continue
                want = _nbytes(var)
                for j, (sz, cand) in enumerate(free_pool):
                    cv = block._find_var_recursive(cand)
                    if cv is not None and sz == want and \
                            cv.np_dtype == var.np_dtype:
                        plan.append((out_name, cand))
                        mapped.add(out_name)
                        free_pool.pop(j)
                        break
            for dead in cfg.dead_after(i):
                var = block._find_var_recursive(dead)
                if _reusable(var) and dead not in mapped:
                    free_pool.append((_nbytes(var), dead))
        if print_log:
            for new, old in plan:
                print("memory_optimize: reuse %s <- %s" % (new, old))
    input_program._memory_reuse_plan = plan
    return plan


def release_memory(input_program, skip_opt_set=None):
    """Mark early-droppable vars (reference :494). Stamps
    `_early_delete_vars`: op index -> [var names dead after it]."""
    skip = set(skip_opt_set or ())
    drop = defaultdict(list)
    for block in input_program.blocks:
        cfg = ControlFlowGraph(block, skip).analyze()
        for i, op in enumerate(block.ops):
            if op.type in _SKIP_OPS:
                continue
            for dead in cfg.dead_after(i):
                var = block._find_var_recursive(dead)
                if _reusable(var):
                    drop[(block.idx, i)].append(dead)
    input_program._early_delete_vars = dict(drop)
    return input_program._early_delete_vars
