"""Program->program rewrite layer (reference python/paddle/fluid/transpiler/).

The transpilers keep the reference's contract — take a built Program, return
rewritten Program(s) for a deployment role — while the execution substrate is
XLA: collective ("nccl2") mode is the primary TPU path (grads reduced by XLA
collectives over ICI/DCN under pjit), and the parameter-server mode performs
the same structural split (trainer program with send/recv, pserver program
with listen_and_serv + optimize blocks) executed by the eager host path.
"""

from .distribute_transpiler import (
    DistributeTranspiler, DistributeTranspilerConfig, slice_variable)
from .ps_dispatcher import PSDispatcher, RoundRobin, HashName
from .memory_optimization_transpiler import memory_optimize, release_memory
from .inference_transpiler import InferenceTranspiler

__all__ = [
    "DistributeTranspiler", "DistributeTranspilerConfig", "slice_variable",
    "PSDispatcher", "RoundRobin", "HashName", "memory_optimize",
    "release_memory", "InferenceTranspiler",
]
