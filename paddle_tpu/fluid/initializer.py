"""Initializers emitted as startup-program ops.

Reference analogue: python/paddle/fluid/initializer.py:121-532 — Constant,
Uniform, Normal, TruncatedNormal, Xavier, MSRA, Bilinear, NumpyArray. Each
initializer appends an op (fill_constant / uniform_random / gaussian_random /
assign_value) to the startup program; the RNG ops lower to deterministic
threefry draws keyed by (seed, op uid) — see ops/tensor_ops.py.
"""

import numpy as np

from .framework import default_startup_program

__all__ = [
    "Constant", "Uniform", "Normal", "TruncatedNormal", "Xavier", "MSRA",
    "Bilinear", "NumpyArrayInitializer", "force_init_on_cpu",
    "init_on_cpu", "ConstantInitializer", "UniformInitializer",
    "NormalInitializer", "TruncatedNormalInitializer",
    "XavierInitializer", "MSRAInitializer", "BilinearInitializer",
]


def force_init_on_cpu():
    # On TPU there is no init-on-GPU-vs-CPU distinction: startup programs are
    # jitted like everything else. Kept for API parity.
    return False


class init_on_cpu:
    def __enter__(self):
        return self

    def __exit__(self, *args):
        pass


class Initializer:
    def __init__(self):
        pass

    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        super().__init__()
        self._value = value

    def __call__(self, var, block):
        return block.append_op(
            type="fill_constant", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "value": float(self._value)},
            infer_shape=False)


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        super().__init__()
        self._low, self._high, self._seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            type="uniform_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "min": self._low, "max": self._high, "seed": self._seed},
            infer_shape=False)


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        super().__init__()
        self._mean, self._std, self._seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="gaussian_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self._mean, "std": self._std, "seed": self._seed},
            infer_shape=False)


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        super().__init__()
        self._mean, self._std, self._seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="truncated_gaussian_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self._mean, "std": self._std, "seed": self._seed},
            infer_shape=False)


def _fan_in_out(var):
    shape = var.shape
    if not shape:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class XavierInitializer(Initializer):
    """Glorot. Matches reference initializer.py:276 fan computation."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        super().__init__()
        self._uniform, self._seed = uniform, seed
        self._fan_in, self._fan_out = fan_in, fan_out

    def __call__(self, var, block):
        f_in, f_out = _fan_in_out(var)
        fan_in = f_in if self._fan_in is None else self._fan_in
        fan_out = f_out if self._fan_out is None else self._fan_out
        if self._uniform:
            limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
            return UniformInitializer(-limit, limit, self._seed)(var, block)
        std = float(np.sqrt(2.0 / (fan_in + fan_out)))
        return NormalInitializer(0.0, std, self._seed)(var, block)


class MSRAInitializer(Initializer):
    """Kaiming He init (reference initializer.py:364)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        super().__init__()
        self._uniform, self._seed, self._fan_in = uniform, seed, fan_in

    def __call__(self, var, block):
        f_in, _ = _fan_in_out(var)
        fan_in = f_in if self._fan_in is None else self._fan_in
        if self._uniform:
            limit = float(np.sqrt(6.0 / fan_in))
            return UniformInitializer(-limit, limit, self._seed)(var, block)
        std = float(np.sqrt(2.0 / fan_in))
        return NormalInitializer(0.0, std, self._seed)(var, block)


class BilinearInitializer(Initializer):
    """For conv-transpose upsampling kernels (reference initializer.py:459)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("bilinear init needs rank-4 var")
        weight = np.zeros(shape, dtype=np.float32)
        size = shape[3]
        factor = (size + 1) // 2
        center = factor - 1 if size % 2 == 1 else factor - 0.5
        og = np.ogrid[:size, :size]
        filt = (1 - abs(og[0] - center) / factor) * \
               (1 - abs(og[1] - center) / factor)
        weight[range(shape[0]), range(shape[1]), :, :] = filt
        return NumpyArrayInitializer(weight)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        super().__init__()
        self._value = np.asarray(value)

    def __call__(self, var, block):
        v = self._value.astype(np.float32)
        return block.append_op(
            type="assign_value", outputs={"Out": var},
            attrs={"shape": list(v.shape), "dtype": var.dtype,
                   "fp32_values": [float(x) for x in v.flatten()]},
            infer_shape=False)


# fluid aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def _global_weight_initializer():
    return XavierInitializer()


def _global_bias_initializer():
    return ConstantInitializer(0.0)
