"""Asynchronous step pipeline: in-flight dispatch + deferred host sync.

Reference analogue: the Fluid stack kept accelerators busy by decoupling
the feed path from the step loop — `double_buffer` / `py_reader` reader
ops fed the device while the previous batch computed
(operators/reader/buffered_reader.cc), and the C++ executor's fetch ops
only forced a device->host copy when the train loop actually read the
LoDTensor.  TF's input pipelining (arXiv:1605.08695 §4.4) and the MLPerf
TPU-v3 scaling work (arXiv:1909.09756) both identify host->device infeed
overlap and async dispatch as first-order throughput levers.

TPU redesign: jax dispatch is already asynchronous — the jitted step
returns *future-backed* device arrays immediately and the host only
blocks when it converts one to numpy.  What the runtime was missing is
the discipline to EXPLOIT that: `Executor.run(..., as_future=True)`
returns a `FetchFuture` (the fetched values as live device arrays) and
the train loop keeps up to `FLAGS.async_dispatch_depth` of them in
flight, resolving each at the pipeline tail with ONE batched
`jax.device_get` instead of a per-item `np.asarray` loop.  The step
watchdog wraps the *drain* (`FetchFuture.result`), not the dispatch, so
hang detection no longer forces a per-step device sync.

The pieces:

* `FetchFuture` — one dispatched step's fetches; `result()` resolves
  them (once, cached) with a single batched transfer, optionally under
  the wall-clock watchdog.
* `DispatchPipeline` — a bounded in-flight window: `submit` enqueues a
  future (plus caller metadata), and once more than `depth` steps are
  live the oldest is drained — backpressure that bounds device-side
  queueing and host staleness alike.

PIPELINE.md documents the prefetch -> dispatch -> drain stages end to
end, including the Trainer's sentinel-lag semantics.
"""

import collections

import numpy as np

__all__ = ["FetchFuture", "DispatchPipeline"]

_UNSET = object()


class FetchFuture:
    """One dispatched step's fetched values, kept as live device arrays
    until `result()` resolves them to host.  Resolution happens at most
    once (the value is cached); it performs ONE `jax.device_get` over
    every fetch — the batched replacement for per-item `np.asarray`
    device->host round-trips — and then runs the caller's `post` hook
    (LoD reassembly, numpy conversion).

    When `FLAGS.step_watchdog_secs` is set the watchdog wraps the
    resolve: a wedged backend raises `StepWatchdogTimeout` out of the
    drain instead of blocking the train loop forever.  `watchdog_scale`
    lets the caller scale the budget by how many steps the drain is
    actually waiting on (resolving the oldest of N in-flight steps may
    legitimately take N steps of wall clock)."""

    def __init__(self, fetches, post=None, return_numpy=True,
                 what="pipeline drain"):
        self._fetches = list(fetches)
        self._post = post
        self._return_numpy = return_numpy
        self._what = what
        self._value = _UNSET

    @classmethod
    def resolved(cls, value):
        """A future that is already resolved (sync execution paths that
        must still honor the `as_future=True` return contract)."""
        fut = cls(())
        fut._value = value
        return fut

    def done(self):
        """True once `result()` has resolved (no device query)."""
        return self._value is not _UNSET

    def ready(self):
        """True when every fetched device array has its value ready —
        i.e. `result()` would not block.  Non-array fetches (eager-path
        numpy, None LoD companions) are always ready."""
        if self._value is not _UNSET:
            return True
        for f in self._fetches:
            is_ready = getattr(f, "is_ready", None)
            if is_ready is not None and not is_ready():
                return False
        return True

    def _resolve(self):
        if self._post is not None:
            # the post hook owns the (batched) device->host transfer —
            # Executor._post_fetches issues one jax.device_get for the
            # whole step
            return self._post(self._fetches, self._return_numpy)
        if self._return_numpy:
            import jax
            # ONE batched transfer for every fetch of the step (None
            # LoD companions pass through untouched)
            vals = jax.device_get(self._fetches)
            return [None if v is None else np.asarray(v) for v in vals]
        return list(self._fetches)

    def result(self, watchdog_scale=1, step=None):
        """Resolve (host sync) and return the step's fetches.  This is
        the pipeline's ONLY mandatory host<->device synchronization
        point; the watchdog — when enabled — wraps exactly this, and the
        obs `train/drain` span measures exactly this (the per-step drain
        milliseconds of PIPELINE.md's breakdown).  `step` labels the
        span with the dispatch-order step id when the caller knows it."""
        if self._value is not _UNSET:
            return self._value
        from ..flags import FLAGS
        from ..obs import tracing as obs_tracing
        wd = FLAGS.step_watchdog_secs
        with obs_tracing.trace("train/drain", kind="train",
                               **({} if step is None else
                                  {"step": step})):
            if wd and wd > 0:
                from .executor import _watchdog_call
                self._value = _watchdog_call(
                    self._resolve, wd * max(int(watchdog_scale), 1),
                    self._what)
            else:
                self._value = self._resolve()
        return self._value


class DispatchPipeline:
    """Bounded window of in-flight steps.  `submit(future, **meta)`
    enqueues; once more than `depth` steps are live the OLDEST is
    resolved and returned — backpressure, so the host never runs more
    than `depth` steps ahead of the device and fetch buffers cannot
    accumulate without bound.  `depth=0` degenerates to fully
    synchronous execution (every submit drains immediately): the flag
    default keeps today's behavior."""

    def __init__(self, depth):
        self.depth = max(int(depth), 0)
        self._inflight = collections.deque()

    def __len__(self):
        return len(self._inflight)

    def submit(self, future, **meta):
        """Enqueue one dispatched step; returns the list of (result,
        meta) pairs drained to honor the depth bound (empty, or one)."""
        self._inflight.append((future, meta))
        drained = []
        while len(self._inflight) > self.depth:
            drained.append(self.drain())
        return drained

    def drain(self):
        """Resolve and return the oldest in-flight step as (result,
        meta); None when nothing is in flight."""
        if not self._inflight:
            return None
        future, meta = self._inflight.popleft()
        # the oldest of N queued steps may need N steps of wall clock
        return future.result(watchdog_scale=len(self._inflight) + 1,
                             step=meta.get("step")), meta

    def drain_all(self):
        """Flush the window: resolve everything in flight, oldest
        first.  The pipeline's sync boundary (epoch end, checkpoint,
        shutdown)."""
        out = []
        while self._inflight:
            out.append(self.drain())
        return out

    def discard_inflight(self):
        """Drop every in-flight step WITHOUT resolving it and return
        the (future, meta) pairs — the sentinel's recovery path: steps
        dispatched downstream of a reverted step were computed from
        poisoned state and their results must never be observed."""
        dropped = list(self._inflight)
        self._inflight.clear()
        return dropped
