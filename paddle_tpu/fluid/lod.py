"""LoD (Level-of-Detail) ragged-sequence tensors.

Reference analogue: paddle/fluid/framework/lod_tensor.h:58 (LoD =
vector<Vector<size_t>>) and :110 (class LoDTensor) — the reference's signature
capability: variable-length sequences carried without padding, consumed by
the sequence_ops/ family.

TPU-native encoding (SURVEY.md §5 long-context note): XLA requires static
shapes, so a LoDTensor here is a *dense* array plus host-side LoD metadata.
Sequence ops lower to segment-id reductions / masked ops over the dense
rows (see ops/sequence_ops.py): rows of all sequences are concatenated along
axis 0 exactly like the reference's packed layout, and `sequence lengths`
become a segment-id vector fed alongside the data. This keeps the packed
(no-padding) memory layout while every op remains a fixed-shape XLA program.
"""

import numpy as np

__all__ = ["LoDTensor", "create_lod_tensor", "lod_to_segment_ids",
           "recursive_seq_lens_to_lod"]


def recursive_seq_lens_to_lod(recursive_seq_lens):
    """[[2,3],[1,2,1,2,2]] -> offsets [[0,2,5],[0,1,3,4,6,8]]"""
    lod = []
    for lens in recursive_seq_lens:
        offsets = [0]
        for l in lens:
            offsets.append(offsets[-1] + l)
        lod.append(offsets)
    return lod


def lod_to_recursive_seq_lens(lod):
    return [[offsets[i + 1] - offsets[i] for i in range(len(offsets) - 1)]
            for offsets in lod]


def lod_to_segment_ids(lod_level_offsets, total_rows):
    """offsets [0,2,5] -> segment ids [0,0,1,1,1] (int32 np array)."""
    seg = np.zeros(total_rows, dtype=np.int32)
    for i in range(len(lod_level_offsets) - 1):
        seg[lod_level_offsets[i]:lod_level_offsets[i + 1]] = i
    return seg


class LoDTensor:
    """Dense ndarray + LoD offsets. Quacks like the pybind LoDTensor
    (set/lod/recursive_sequence_lengths/shape/numpy)."""

    def __init__(self, data=None, lod=None):
        self._data = np.asarray(data) if data is not None else None
        self._lod = lod or []

    # -- fluid pybind API --
    def set(self, data, place=None):
        self._data = np.asarray(data)

    def set_lod(self, lod):
        self._lod = [list(l) for l in lod]

    def lod(self):
        return self._lod

    def set_recursive_sequence_lengths(self, seq_lens):
        self._lod = recursive_seq_lens_to_lod(seq_lens)

    def recursive_sequence_lengths(self):
        return lod_to_recursive_seq_lens(self._lod)

    def has_valid_recursive_sequence_lengths(self):
        if not self._lod:
            return True
        if self._lod[-1][-1] != (self._data.shape[0] if self._data is not None
                                 else 0):
            return False
        return True

    def shape(self):
        return list(self._data.shape)

    def numpy(self):
        return self._data

    def __array__(self, dtype=None):
        return np.asarray(self._data, dtype=dtype)

    def segment_ids(self, level=-1):
        """dense segment-id encoding of the chosen LoD level."""
        offsets = self._lod[level]
        return lod_to_segment_ids(offsets, self._data.shape[0])

    def __repr__(self):
        return "LoDTensor(shape=%s, lod=%s)" % (
            None if self._data is None else self._data.shape, self._lod)


def _bucket_len(n, minimum=16):
    """next power of two >= n (bounded recompile count per program)."""
    b = minimum
    while b < n:
        b *= 2
    return b


def pad_lod_feed(lod_tensor, bucket=True):
    """packed LoDTensor -> (padded [B, T, ...], lengths int32 [B], seg).
    T is bucketed to a power of two so changing batch raggedness reuses
    compiled programs (SURVEY.md §7 'segment ids + maxlen bucketing').
    For a 2-level (nested) LoD, B counts INNER sequences and `seg` is
    the int32 [B_outer] COUNT of inner sequences per outer group
    (functionalizer.LOD_SEG_SUFFIX); seg is None for single-level
    inputs."""
    data = np.asarray(lod_tensor)
    lod = lod_tensor.lod()
    # the pybind convention is OFFSETS ([0, 6, 12]), not lengths — a
    # lengths list ([6, 6]) silently selects wrong rows, so enforce
    # validity at EVERY level like the reference's CheckLoD
    # (lod_tensor.cc): each level starts at 0 and is monotone; level i's
    # last offset indexes level i+1's sequence count; the last level's
    # last offset is the row count.
    for li, level in enumerate(lod):
        level = list(level)
        end = (data.shape[0] if li == len(lod) - 1
               else len(lod[li + 1]) - 1)
        if (len(level) == 0 or level[0] != 0 or level[-1] != end
                or any(level[i] > level[i + 1]
                       for i in range(len(level) - 1))):
            raise ValueError(
                "invalid LoD level %d %r (expected offsets 0..%d): "
                "lod() carries OFFSETS, not lengths (use "
                "set_recursive_sequence_lengths for lengths)"
                % (li, lod, end))
    offsets = lod[-1]
    lens = np.array([offsets[i + 1] - offsets[i]
                     for i in range(len(offsets) - 1)], dtype=np.int32)
    B = len(lens)
    T = int(lens.max()) if B else 0
    if bucket:
        T = _bucket_len(max(T, 1))
    padded = np.zeros((B, T) + data.shape[1:], dtype=data.dtype)
    for i in range(B):
        padded[i, :lens[i]] = data[offsets[i]:offsets[i + 1]]
    seg = None
    if len(lod) >= 2:
        # outer level groups inner sequences: carry the per-group inner
        # COUNTS [B_outer] (not per-inner ids — counts preserve trailing
        # empty groups); lod[-2] offsets index the inner-sequence axis
        off = lod[-2]
        seg = np.array([off[i + 1] - off[i]
                        for i in range(len(off) - 1)], dtype=np.int32)
    return padded, lens, seg


def unpad_to_lod_tensor(padded, lens):
    """(padded [B, T, ...], lengths [B]) -> packed LoDTensor."""
    rows = [padded[i, :int(l)] for i, l in enumerate(lens)]
    packed = np.concatenate(rows, axis=0) if rows else padded[:0, 0]
    t = LoDTensor(packed)
    t.set_recursive_sequence_lengths([[int(l) for l in lens]])
    return t


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """reference python/paddle/fluid/lod_tensor.py create_lod_tensor."""
    if isinstance(data, list):
        # list of per-sequence row arrays -> concatenate
        flat = np.concatenate([np.asarray(x).reshape(len(x), -1)
                               for x in data], axis=0)
        t = LoDTensor(flat)
    else:
        t = LoDTensor(np.asarray(data))
    t.set_recursive_sequence_lengths(recursive_seq_lens)
    assert t.has_valid_recursive_sequence_lengths()
    return t


def nested_samples_to_lod_tensor(col, dtype):
    """Batch of nested samples (each a list of inner sequences) -> 2-level
    LoDTensor. The single conversion both feeders share."""
    outer = [len(s) for s in col]
    inners = [np.asarray(inner, dtype=dtype).reshape(len(inner), -1)
              for s in col for inner in s]
    return create_lod_tensor(inners, [outer, [len(i) for i in inners]])


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place,
                                low, high):
    """reference lod_tensor.py create_random_int_lodtensor: random int64
    ragged tensor with the given per-sequence lengths."""
    total = sum(recursive_seq_lens[-1])
    data = np.random.randint(low, high + 1,
                             size=[total] + list(base_shape)).astype("int64")
    return create_lod_tensor(data, recursive_seq_lens, place)
