"""SelectedRows — sparse row-set gradients for embeddings.

Reference analogue: paddle/fluid/framework/selected_rows.h (rows + value
tensor + height), produced by lookup_table_grad's sparse kernel when
is_sparse=True and consumed by the sparse paths of sgd/adam/adagrad
(operators/optimizers/*, SelectedRows overloads).

TPU design: a SelectedRowsValue is a jax pytree (rows int32 [K], values
[K, D], static height), so it flows through the jitted step like any other
value; optimizer lowerings detect it and perform row-wise scatter updates —
the update cost scales with the touched rows, not the table height, exactly
the property the reference's sparse kernels provide.
"""

import numpy as np

__all__ = ["SelectedRowsValue"]


class SelectedRowsValue:
    """rows [K] int32, values [K, D], height = table size (static)."""

    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height):
        self.rows = rows
        self.values = values
        self.height = int(height)

    # -- reference SelectedRows API (selected_rows.h) --
    def get_rows(self):
        return self.rows

    def get_tensor(self):
        return self.values

    def get_height(self):
        return self.height

    def to_dense(self):
        """Densify: [height, D] with rows scattered (get_tensor_from_
        selected_rows op semantics)."""
        import jax.numpy as jnp
        dense = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                          self.values.dtype)
        # mode="drop": a merged() SelectedRows pads rows with the
        # out-of-range id `height`, which must not land anywhere.
        return dense.at[self.rows].add(self.values, mode="drop")

    def merged(self):
        """Deduplicate rows, summing their values (merge_selected_rows
        op / MergeAdd functor). Rows stay fixed-capacity so shapes are
        static under jit; padding positions carry the out-of-range id
        ``height`` so downstream scatters (mode="drop") never touch a real
        row — an in-range pad id would clobber that row's moments/params
        when the batch contains duplicate ids."""
        import jax.numpy as jnp
        uniq, inv = jnp.unique(self.rows, return_inverse=True,
                               size=self.rows.shape[0],
                               fill_value=self.height)
        summed = jnp.zeros_like(self.values).at[inv].add(self.values)
        return SelectedRowsValue(uniq.astype(jnp.int32), summed,
                                 self.height)

    def __repr__(self):
        return "SelectedRows(rows=%s, values=%s, height=%d)" % (
            getattr(self.rows, "shape", None),
            getattr(self.values, "shape", None), self.height)


def _flatten(sr):
    return (sr.rows, sr.values), sr.height


def _unflatten(height, children):
    rows, values = children
    return SelectedRowsValue(rows, values, height)


def _register():
    import jax
    jax.tree_util.register_pytree_node(SelectedRowsValue, _flatten,
                                       _unflatten)


_register()
