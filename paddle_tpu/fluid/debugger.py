"""Program visualization / pretty-printing.

Reference analogue: python/paddle/fluid/debugger.py (+ graphviz.py,
net_drawer.py, and the C++ graph_viz_pass ir/graph_viz_pass.cc) — renders a
Program's op/var graph to graphviz dot text and pretty-prints program code.

Analyzer integration (ANALYSIS.md): both renderers accept the
``diagnostics`` list paddle_tpu.analysis.verify_program returns and
annotate the output instead of printing the bare program — dead ops are
dimmed, shape/dtype-mismatch sites highlighted, and every other finding
lands as a ``!`` / colored marker on its op or var, so "why does the
verifier hate my program" is answerable by looking at the graph.

Resource integration: both renderers also accept ``costs=`` — a
paddle_tpu.analysis.ResourceReport (or its ``ops`` row list) — and
grow a per-op ``est_bytes``/``est_flops`` column on the same
indexing machinery, so "where do the bytes go" reads off the printed
program the way the findings do.
"""

__all__ = ["pprint_program_codes", "pprint_block_codes",
           "draw_block_graphviz"]

# checks rendered as "dead" (dimmed) vs "broken" (highlighted)
_DEAD_CHECKS = frozenset(["dead-op", "unused-var"])
_ERROR_STYLE_CHECKS = frozenset([
    "shape-mismatch", "dtype-mismatch", "use-before-def",
    "undefined-var", "unregistered-op", "unknown-fetch",
    "unreachable-fetch"])


def _index_diags(block, diagnostics):
    """(by_op_index, by_var) for the diagnostics landing in `block`."""
    by_op, by_var = {}, {}
    for d in diagnostics or ():
        if d.block is not None and d.block != block.idx:
            continue
        if d.op_index is not None:
            by_op.setdefault(d.op_index, []).append(d)
        elif d.var:
            by_var.setdefault(d.var, []).append(d)
    return by_op, by_var


def _index_costs(block, costs):
    """op_index -> (est_flops, est_bytes) for `block` from a
    ResourceReport (or its .ops row list); {} without costs."""
    if costs is None:
        return {}
    rows = getattr(costs, "ops", costs)
    out = {}
    for row in rows:
        if row.get("block") == block.idx:
            out[row["index"]] = (row.get("est_flops", 0),
                                 row.get("est_bytes", 0))
    return out


def _fmt_units(n, unit):
    for scale, suffix in ((1e9, "G"), (1e6, "M"), (1e3, "K")):
        if n >= scale:
            return "%.1f%s%s" % (n / scale, suffix, unit)
    return "%d%s" % (n, unit)


def pprint_program_codes(program, diagnostics=None, costs=None):
    return "\n".join(pprint_block_codes(b, diagnostics=diagnostics,
                                        costs=costs)
                     for b in program.blocks)


def pprint_block_codes(block, diagnostics=None, costs=None):
    by_op, by_var = _index_diags(block, diagnostics)
    by_cost = _index_costs(block, costs)
    lines = ["# block %d (parent %d)" % (block.idx, block.parent_idx)]
    for var in block.vars.values():
        line = "var %s : %s shape=%s%s" % (
            var.name, var.dtype, var.shape,
            " persistable" if var.persistable else "")
        for d in by_var.get(var.name, ()):
            line += "   # %s[%s] %s" % (d.severity, d.check, d.message)
        lines.append(line)
    for i, op in enumerate(block.ops):
        ins = ", ".join("%s=%s" % (k, v) for k, v in op.inputs.items())
        outs = ", ".join("%s=%s" % (k, v) for k, v in op.outputs.items())
        line = "%s(%s) -> %s" % (op.type, ins, outs)
        marks = by_op.get(i, ())
        if any(d.check in _DEAD_CHECKS for d in marks):
            line = "# [dead] " + line       # dimmed: commented out
        cost = by_cost.get(i)
        if cost is not None:
            line += "   # est_flops=%s est_bytes=%s" % (
                _fmt_units(cost[0], "F"), _fmt_units(cost[1], "B"))
        for d in marks:
            if d.check not in _DEAD_CHECKS:
                line += "   # !%s[%s] %s" % (d.severity, d.check,
                                             d.message)
        lines.append(line)
    return "\n".join(lines)


def draw_block_graphviz(block, highlights=None, path="./temp.dot",
                        diagnostics=None, costs=None):
    """Write the op/var graph of `block` as graphviz dot (reference
    debugger.py draw_block_graphviz; C++ analogue graph_viz_pass).

    With `diagnostics`, analyzer findings restyle the graph: dead ops
    render dimmed (gray, dashed), shape/dtype-mismatch and other error
    sites render highlighted (red) with the finding in the tooltip, and
    flagged vars (unused/undefined) pick up the same treatment.  With
    `costs` (a ResourceReport), each op node's label carries its
    est_flops/est_bytes line."""
    highlights = set(highlights or [])
    by_op, by_var = _index_diags(block, diagnostics)
    by_cost = _index_costs(block, costs)
    lines = ["digraph G {", "  rankdir=TB;"]
    var_ids = {}

    def _esc(s):
        return str(s).replace("\\", "\\\\").replace('"', '\\"')

    def vid(name):
        if name not in var_ids:
            var_ids[name] = "var_%d" % len(var_ids)
            style = ', style=filled, fillcolor="lightblue"' \
                if name in highlights else ""
            diags = by_var.get(name, ())
            if any(d.check in _DEAD_CHECKS for d in diags):
                style = (', style="filled,dashed", fillcolor="gray90", '
                         'fontcolor="gray50"')
            elif diags:
                style = ', style=filled, fillcolor="lightcoral"'
            if diags:
                style += ', tooltip="%s"' % _esc(
                    "; ".join(str(d) for d in diags))
            lines.append('  %s [label="%s", shape=oval%s];' %
                         (var_ids[name], name, style))
        return var_ids[name]

    for i, op in enumerate(block.ops):
        op_id = "op_%d" % i
        diags = by_op.get(i, ())
        fill, extra = "lightgray", ""
        if any(d.check in _DEAD_CHECKS for d in diags):
            # dead op: dimmed out of the dataflow picture
            fill, extra = "gray90", ', fontcolor="gray50", style="filled,dashed"'
        elif any(d.check in _ERROR_STYLE_CHECKS or d.is_error
                 for d in diags):
            # mismatch/error site: highlighted
            fill, extra = "lightcoral", ', color="red", penwidth=2'
        if diags:
            extra += ', tooltip="%s"' % _esc(
                "; ".join(str(d) for d in diags))
        style = 'style=filled, fillcolor="%s"%s' % (fill, extra) \
            if "style" not in extra else 'fillcolor="%s"%s' % (fill, extra)
        label = op.type
        cost = by_cost.get(i)
        if cost is not None:
            label += "\\n%s %s" % (_fmt_units(cost[0], "F"),
                                   _fmt_units(cost[1], "B"))
        lines.append('  %s [label="%s", shape=box, %s];'
                     % (op_id, label, style))
        err_edges = any(d.check in ("shape-mismatch", "dtype-mismatch")
                        for d in diags)
        for names in op.inputs.values():
            for n in names:
                if n:
                    # a shape/dtype mismatch is a property of the edge
                    # between the recorded var and the op — paint it
                    lines.append("  %s -> %s%s;" % (
                        vid(n), op_id,
                        ' [color="red", penwidth=2]' if err_edges
                        else ""))
        for names in op.outputs.values():
            for n in names:
                if n:
                    lines.append("  %s -> %s%s;" % (
                        op_id, vid(n),
                        ' [color="red", penwidth=2]' if err_edges
                        else ""))
    lines.append("}")
    dot = "\n".join(lines)
    with open(path, "w") as f:
        f.write(dot)
    return dot
