"""Program visualization / pretty-printing.

Reference analogue: python/paddle/fluid/debugger.py (+ graphviz.py,
net_drawer.py, and the C++ graph_viz_pass ir/graph_viz_pass.cc) — renders a
Program's op/var graph to graphviz dot text and pretty-prints program code.
"""

__all__ = ["pprint_program_codes", "pprint_block_codes",
           "draw_block_graphviz"]


def pprint_program_codes(program):
    return "\n".join(pprint_block_codes(b) for b in program.blocks)


def pprint_block_codes(block):
    lines = ["# block %d (parent %d)" % (block.idx, block.parent_idx)]
    for var in block.vars.values():
        lines.append("var %s : %s shape=%s%s" % (
            var.name, var.dtype, var.shape,
            " persistable" if var.persistable else ""))
    for op in block.ops:
        ins = ", ".join("%s=%s" % (k, v) for k, v in op.inputs.items())
        outs = ", ".join("%s=%s" % (k, v) for k, v in op.outputs.items())
        lines.append("%s(%s) -> %s" % (op.type, ins, outs))
    return "\n".join(lines)


def draw_block_graphviz(block, highlights=None, path="./temp.dot"):
    """Write the op/var graph of `block` as graphviz dot (reference
    debugger.py draw_block_graphviz; C++ analogue graph_viz_pass)."""
    highlights = set(highlights or [])
    lines = ["digraph G {", "  rankdir=TB;"]
    var_ids = {}

    def vid(name):
        if name not in var_ids:
            var_ids[name] = "var_%d" % len(var_ids)
            color = ', style=filled, fillcolor="lightblue"' \
                if name in highlights else ""
            lines.append('  %s [label="%s", shape=oval%s];' %
                         (var_ids[name], name, color))
        return var_ids[name]

    for i, op in enumerate(block.ops):
        op_id = "op_%d" % i
        lines.append('  %s [label="%s", shape=box, style=filled, '
                     'fillcolor="lightgray"];' % (op_id, op.type))
        for names in op.inputs.values():
            for n in names:
                if n:
                    lines.append("  %s -> %s;" % (vid(n), op_id))
        for names in op.outputs.values():
            for n in names:
                if n:
                    lines.append("  %s -> %s;" % (op_id, vid(n)))
    lines.append("}")
    dot = "\n".join(lines)
    with open(path, "w") as f:
        f.write(dot)
    return dot
