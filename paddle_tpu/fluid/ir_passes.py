"""Program-level pass framework.

Reference analogue: paddle/fluid/framework/ir/ — ir::Graph (graph.h:63),
Pass/PassRegistry (pass.h:32), GraphPatternDetector, and the fusion pass
suite chained by BuildStrategy (details/build_strategy.cc:27).

TPU redesign: most reference passes exist to pre-fuse kernels (fc_fuse,
conv_bn, fuse_elewise_add_act) — XLA's fusion subsumes them, so the fusion
passes here are *structural parity* rewrites kept for program inspection and
op-count parity, while graph_viz / is_test / memory passes carry real
behavior. The pass substrate works on the Program in place (the Program IS
the graph: ops + var def/use edges), mirroring ir::Pass::ApplyImpl.
"""

from .framework import Program

__all__ = ["Pass", "register_pass", "get_pass", "apply_passes",
           "registered_passes"]

_PASS_REGISTRY = {}


class Pass:
    """reference ir/pass.h:32. Subclasses implement apply_impl(program)."""

    name = None

    def __init__(self, **attrs):
        self.attrs = dict(attrs)

    def set(self, key, value):
        self.attrs[key] = value
        return self

    def get(self, key, default=None):
        return self.attrs.get(key, default)

    def apply(self, program):
        out = self.apply_impl(program)
        program._bump_version()
        return out if out is not None else program

    def apply_impl(self, program):
        raise NotImplementedError


def register_pass(cls):
    assert cls.name, "pass needs a name"
    _PASS_REGISTRY[cls.name] = cls
    return cls


def get_pass(name, **attrs):
    return _PASS_REGISTRY[name](**attrs)


def registered_passes():
    return sorted(_PASS_REGISTRY)


def apply_passes(program, names, **attrs):
    for n in names:
        program = get_pass(n, **attrs).apply(program)
    return program


# ---------------------------------------------------------------------------
# concrete passes
# ---------------------------------------------------------------------------

@register_pass
class GraphVizPass(Pass):
    """ir/graph_viz_pass.cc: dump the op/var graph as graphviz dot."""

    name = "graph_viz_pass"

    def apply_impl(self, program):
        from .debugger import draw_block_graphviz
        path = self.get("graph_viz_path", "./program.dot")
        draw_block_graphviz(program.global_block(), path=path)
        return program


@register_pass
class IsTestPass(Pass):
    """ir/is_test_pass.cc: flip is_test on inference-sensitive ops."""

    name = "is_test_pass"

    _OPS = ("dropout", "batch_norm", "lrn", "layer_norm")

    def apply_impl(self, program):
        for blk in program.blocks:
            for op in blk.ops:
                if op.type in self._OPS:
                    op.attrs["is_test"] = True
        return program


@register_pass
class FuseElewiseAddActPass(Pass):
    """ir/fuse_elewise_add_act_pass.cc: elementwise_add + activation ->
    fused_elemwise_activation. XLA fuses these anyway; the rewrite keeps
    op-count/structure parity and exercises the pattern machinery."""

    name = "fuse_elewise_add_act_pass"

    _ACTS = ("relu", "sigmoid", "tanh", "gelu")

    def apply_impl(self, program):
        blk = program.global_block()
        i = 0
        while i < len(blk.ops) - 1:
            add_op = blk.ops[i]
            act_op = blk.ops[i + 1]
            if (add_op.type == "elementwise_add" and
                    act_op.type in self._ACTS and
                    act_op.inputs.get("X", [None])[0] ==
                    add_op.outputs["Out"][0] and
                    self._single_use(blk, add_op.outputs["Out"][0])):
                fused = blk.ops[i]
                fused.type = "fused_elemwise_activation"
                fused.attrs["functor_list"] = [
                    "elementwise_add", act_op.type]
                fused.attrs["axis"] = add_op.attrs.get("axis", -1)
                fused.outputs = {"Out": list(act_op.outputs["Out"])}
                del blk.ops[i + 1]
            i += 1
        return program

    @staticmethod
    def _single_use(blk, name):
        return sum(1 for o in blk.ops
                   for ns in o.inputs.values() for n in ns
                   if n == name) == 1


@register_pass
class FCFusePass(Pass):
    """ir/fc_fuse_pass.cc: mul + elementwise_add(bias) -> fc op."""

    name = "fc_fuse_pass"

    def apply_impl(self, program):
        blk = program.global_block()
        i = 0
        while i < len(blk.ops) - 1:
            mul_op = blk.ops[i]
            add_op = blk.ops[i + 1]
            if (mul_op.type == "mul" and
                    add_op.type == "elementwise_add" and
                    add_op.inputs.get("X", [None])[0] ==
                    mul_op.outputs["Out"][0] and
                    FuseElewiseAddActPass._single_use(
                        blk, mul_op.outputs["Out"][0])):
                fused = blk.ops[i]
                fused.type = "fc"
                fused.inputs = {"Input": list(mul_op.inputs["X"]),
                                "W": list(mul_op.inputs["Y"]),
                                "Bias": list(add_op.inputs["Y"])}
                fused.attrs = {"in_num_col_dims":
                               mul_op.attrs.get("x_num_col_dims", 1)}
                fused.outputs = {"Out": list(add_op.outputs["Out"])}
                del blk.ops[i + 1]
            i += 1
        return program


@register_pass
class MultiBatchMergePass(Pass):
    """ir/multi_batch_merge_pass.cc (+ test_dist_mnist_batch_merge):
    gradient accumulation — run N micro-batches, apply ONE optimizer
    update from the averaged accumulated gradient.

    The reference rewrote the SSA graph to repeat the fwd/bwd subgraph N
    times per iteration; the TPU-idiomatic encoding keeps one jitted step
    and gates the optimizer ops instead (ops/optimizer_ops._merge_gated):
    this pass creates a persistable accumulation buffer per gradient,
    wires it into each optimizer op, and annotates `merge_n` so the gated
    lowering accumulates on micro-steps and applies+resets every Nth
    step. LR-decay counter increments are gated to count applied updates.

    Usage: get_pass("multi_batch_merge_pass", n=4).apply(main_program)
    """

    name = "multi_batch_merge_pass"

    def apply_impl(self, program):
        from ..ops.optimizer_ops import MERGEABLE_OPT_OPS
        from .layers.learning_rate_scheduler import LR_COUNTER_NAME
        n = int(self.get("n", 1))
        if n <= 1:
            return program
        blk = program.global_block()
        # adam/adamax advance their beta-pow accumulators with separate
        # in-place `scale` ops (optimizer.py _finish_update, mirroring the
        # reference) — those must gate with the optimizer update
        pow_names = set()
        for op in blk.ops:
            if op.type in MERGEABLE_OPT_OPS:
                for slot in ("Beta1Pow", "Beta2Pow"):
                    for nm in op.inputs.get(slot, []):
                        if nm:
                            pow_names.add(nm)
        for op in blk.ops:
            if op.type in MERGEABLE_OPT_OPS:
                gname = op.inputs.get("Grad", [None])[0]
                if not gname:
                    continue
                gvar = blk._find_var_recursive(gname)
                acc_name = gname + "@MERGE_ACC"
                if blk._find_var_recursive(acc_name) is None:
                    blk.create_var(
                        name=acc_name,
                        dtype=gvar.dtype if gvar is not None else "float32",
                        shape=gvar.shape if gvar is not None else None,
                        persistable=True, stop_gradient=True)
                op.inputs["GradAcc"] = [acc_name]
                op.outputs["GradAccOut"] = [acc_name]
                op.attrs["merge_n"] = n
            elif op.type == "increment":
                xn = op.inputs.get("X", [None])[0]
                if xn == LR_COUNTER_NAME:
                    op.attrs["merge_n"] = n
            elif op.type == "scale":
                xn = op.inputs.get("X", [None])[0]
                on = op.outputs.get("Out", [None])[0]
                if xn and xn == on and xn in pow_names:
                    op.attrs["merge_n"] = n
        return program
