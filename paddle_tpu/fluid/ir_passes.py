"""Program-level pass framework.

Reference analogue: paddle/fluid/framework/ir/ — ir::Graph (graph.h:63),
Pass/PassRegistry (pass.h:32), GraphPatternDetector, and the fusion pass
suite chained by BuildStrategy (details/build_strategy.cc:27).

TPU redesign: most reference passes exist to pre-fuse kernels (fc_fuse,
conv_bn, fuse_elewise_add_act) — XLA's fusion subsumes them, so the fusion
passes here are *structural parity* rewrites kept for program inspection and
op-count parity, while graph_viz / is_test / memory passes carry real
behavior. The pass substrate works on the Program in place (the Program IS
the graph: ops + var def/use edges), mirroring ir::Pass::ApplyImpl.
"""

from .framework import Program

__all__ = ["Pass", "register_pass", "get_pass", "apply_passes",
           "registered_passes"]

_PASS_REGISTRY = {}


class Pass:
    """reference ir/pass.h:32. Subclasses implement apply_impl(program)."""

    name = None

    def __init__(self, **attrs):
        self.attrs = dict(attrs)

    def set(self, key, value):
        self.attrs[key] = value
        return self

    def get(self, key, default=None):
        return self.attrs.get(key, default)

    def apply(self, program):
        out = self.apply_impl(program)
        program._bump_version()
        return out if out is not None else program

    def apply_impl(self, program):
        raise NotImplementedError


def register_pass(cls):
    assert cls.name, "pass needs a name"
    _PASS_REGISTRY[cls.name] = cls
    return cls


def get_pass(name, **attrs):
    return _PASS_REGISTRY[name](**attrs)


def registered_passes():
    return sorted(_PASS_REGISTRY)


def apply_passes(program, names, **attrs):
    for n in names:
        program = get_pass(n, **attrs).apply(program)
    return program


def use_count(block, var_name, _seen=None):
    """Number of ops in `block` consuming var_name (the reference's
    intermediate-node single-consumer rule; shared by the adjacency
    passes and GraphPatternDetector). Reads hidden inside nested
    sub-blocks (conditional_block/while declare outputs={} at the parent
    level) count too — a fusion must not delete an op whose output a
    sub-block still reads."""
    _seen = _seen if _seen is not None else set()
    n_uses = 0
    for o in block.ops:
        n_uses += sum(1 for ns in o.inputs.values() for n in ns
                      if n == var_name)
        sub = o.attrs.get("sub_block")
        if sub is not None and id(sub) not in _seen:
            _seen.add(id(sub))
            n_uses += use_count(sub, var_name, _seen)
    return n_uses


# ---------------------------------------------------------------------------
# concrete passes
# ---------------------------------------------------------------------------

@register_pass
class GraphVizPass(Pass):
    """ir/graph_viz_pass.cc: dump the op/var graph as graphviz dot."""

    name = "graph_viz_pass"

    def apply_impl(self, program):
        from .debugger import draw_block_graphviz
        path = self.get("graph_viz_path", "./program.dot")
        draw_block_graphviz(program.global_block(), path=path)
        return program


@register_pass
class IsTestPass(Pass):
    """ir/is_test_pass.cc: flip is_test on inference-sensitive ops."""

    name = "is_test_pass"

    _OPS = ("dropout", "batch_norm", "lrn", "layer_norm")

    def apply_impl(self, program):
        for blk in program.blocks:
            for op in blk.ops:
                if op.type in self._OPS:
                    op.attrs["is_test"] = True
        return program


@register_pass
class FuseElewiseAddActPass(Pass):
    """ir/fuse_elewise_add_act_pass.cc: elementwise_add + activation ->
    fused_elemwise_activation. XLA fuses these anyway; the rewrite keeps
    op-count/structure parity and exercises the pattern machinery."""

    name = "fuse_elewise_add_act_pass"

    _ACTS = ("relu", "sigmoid", "tanh", "gelu")

    def apply_impl(self, program):
        blk = program.global_block()
        i = 0
        while i < len(blk.ops) - 1:
            add_op = blk.ops[i]
            act_op = blk.ops[i + 1]
            if (add_op.type == "elementwise_add" and
                    act_op.type in self._ACTS and
                    act_op.inputs.get("X", [None])[0] ==
                    add_op.outputs["Out"][0] and
                    self._single_use(blk, add_op.outputs["Out"][0])):
                fused = blk.ops[i]
                fused.type = "fused_elemwise_activation"
                # the activation's own attrs (e.g. gelu's 'approximate')
                # must survive the fusion or the fused lowering reads
                # defaults the unfused path would not have used
                for k, v in act_op.attrs.items():
                    fused.attrs.setdefault(k, v)
                fused.attrs["functor_list"] = [
                    "elementwise_add", act_op.type]
                fused.attrs["axis"] = add_op.attrs.get("axis", -1)
                fused.outputs = {"Out": list(act_op.outputs["Out"])}
                del blk.ops[i + 1]
            i += 1
        return program

    @staticmethod
    def _single_use(blk, name):
        return use_count(blk, name) == 1


@register_pass
class FCFusePass(Pass):
    """ir/fc_fuse_pass.cc: mul + elementwise_add(bias) -> fc op."""

    name = "fc_fuse_pass"

    def apply_impl(self, program):
        blk = program.global_block()
        i = 0
        while i < len(blk.ops) - 1:
            mul_op = blk.ops[i]
            add_op = blk.ops[i + 1]
            if (mul_op.type == "mul" and
                    add_op.type == "elementwise_add" and
                    add_op.inputs.get("X", [None])[0] ==
                    mul_op.outputs["Out"][0] and
                    FuseElewiseAddActPass._single_use(
                        blk, mul_op.outputs["Out"][0])):
                fused = blk.ops[i]
                fused.type = "fc"
                fused.inputs = {"Input": list(mul_op.inputs["X"]),
                                "W": list(mul_op.inputs["Y"]),
                                "Bias": list(add_op.inputs["Y"])}
                fused.attrs = {"in_num_col_dims":
                               mul_op.attrs.get("x_num_col_dims", 1)}
                fused.outputs = {"Out": list(add_op.outputs["Out"])}
                del blk.ops[i + 1]
            i += 1
        return program


@register_pass
class MultiBatchMergePass(Pass):
    """ir/multi_batch_merge_pass.cc (+ test_dist_mnist_batch_merge):
    gradient accumulation — run N micro-batches, apply ONE optimizer
    update from the averaged accumulated gradient.

    The reference rewrote the SSA graph to repeat the fwd/bwd subgraph N
    times per iteration; the TPU-idiomatic encoding keeps one jitted step
    and gates the optimizer ops instead (ops/optimizer_ops._merge_gated):
    this pass creates a persistable accumulation buffer per gradient,
    wires it into each optimizer op, and annotates `merge_n` so the gated
    lowering accumulates on micro-steps and applies+resets every Nth
    step. LR-decay counter increments are gated to count applied updates.

    Usage: get_pass("multi_batch_merge_pass", n=4).apply(main_program)
    """

    name = "multi_batch_merge_pass"

    def apply_impl(self, program):
        from ..ops.optimizer_ops import MERGEABLE_OPT_OPS
        from .layers.learning_rate_scheduler import LR_COUNTER_NAME
        n = int(self.get("n", 1))
        if n <= 1:
            return program
        blk = program.global_block()
        # adam/adamax advance their beta-pow accumulators with separate
        # in-place `scale` ops (optimizer.py _finish_update, mirroring the
        # reference) — those must gate with the optimizer update
        pow_names = set()
        for op in blk.ops:
            if op.type in MERGEABLE_OPT_OPS:
                for slot in ("Beta1Pow", "Beta2Pow"):
                    for nm in op.inputs.get(slot, []):
                        if nm:
                            pow_names.add(nm)
        for op in blk.ops:
            if op.type in MERGEABLE_OPT_OPS:
                gname = op.inputs.get("Grad", [None])[0]
                if not gname:
                    continue
                gvar = blk._find_var_recursive(gname)
                acc_name = gname + "@MERGE_ACC"
                if blk._find_var_recursive(acc_name) is None:
                    blk.create_var(
                        name=acc_name,
                        dtype=gvar.dtype if gvar is not None else "float32",
                        shape=gvar.shape if gvar is not None else None,
                        persistable=True, stop_gradient=True)
                op.inputs["GradAcc"] = [acc_name]
                op.outputs["GradAccOut"] = [acc_name]
                op.attrs["merge_n"] = n
            elif op.type == "increment":
                xn = op.inputs.get("X", [None])[0]
                if xn == LR_COUNTER_NAME:
                    op.attrs["merge_n"] = n
            elif op.type == "scale":
                xn = op.inputs.get("X", [None])[0]
                on = op.outputs.get("Out", [None])[0]
                if xn and xn == on and xn in pow_names:
                    op.attrs["merge_n"] = n
        return program


# ---------------------------------------------------------------------------
# GraphPatternDetector (reference ir/graph_pattern_detector.h: PDPattern of
# PDNodes + subgraph matcher that fusion passes build on). Program-level
# equivalent: declarative op-chain patterns where dataflow is expressed by
# shared symbols bound to concrete variable names during matching.
# ---------------------------------------------------------------------------

class GraphPatternDetector:
    """Declarative subgraph patterns over a Block.

    Usage:
        d = GraphPatternDetector()
        d.add_op("mul", types=["mul"], outputs={"Out": "mm"})
        d.add_op("add", types=["elementwise_add"], inputs={"X": "mm"},
                 single_use={"mm"})
        for m in d.detect(block):   # m: name -> Operator
            ...rewrite...

    Symbols (like "mm") bind to concrete var names; a symbol appearing in
    one node's outputs and another's inputs is a dataflow edge. `single_use`
    marks symbols that must have exactly one consumer in the block (the
    reference's intermediate-node constraint, so fusion never drops a value
    some other op still reads).
    """

    def __init__(self):
        self._nodes = []   # (name, types, in_links, out_links, single_use)

    def add_op(self, name, types, inputs=None, outputs=None,
               single_use=()):
        self._nodes.append((name, tuple(types), dict(inputs or {}),
                            dict(outputs or {}), frozenset(single_use)))
        return self

    @staticmethod
    def _uses(block, var_name):
        return use_count(block, var_name)

    def detect(self, block):
        """Yield non-overlapping matches as {node_name: Operator}."""
        matches = []
        used_ops = set()

        def bind(node_idx, binding, chosen, anchor=None):
            if node_idx == len(self._nodes):
                matches.append(dict(chosen))
                used_ops.update(id(op) for op in chosen.values())
                return True
            name, types, ins, outs, single = self._nodes[node_idx]
            for op in ([anchor] if anchor is not None else block.ops):
                if op.type not in types or id(op) in used_ops or \
                        any(op is c for c in chosen.values()):
                    continue
                b2 = dict(binding)
                ok = True
                for slot, sym in ins.items():
                    actual = op.inputs.get(slot, [None])[0]
                    if actual is None or \
                            (sym in b2 and b2[sym] != actual):
                        ok = False
                        break
                    b2[sym] = actual
                if not ok:
                    continue
                for slot, sym in outs.items():
                    actual = op.outputs.get(slot, [None])[0]
                    if actual is None or \
                            (sym in b2 and b2[sym] != actual):
                        ok = False
                        break
                    b2[sym] = actual
                if not ok:
                    continue
                if any(self._uses(block, b2[s]) != 1 for s in single
                       if s in b2):
                    continue
                chosen[name] = op
                if bind(node_idx + 1, b2, chosen):
                    return True
                del chosen[name]
            return False

        # greedily find all non-overlapping matches: each op is tried as
        # the first pattern node's anchor exactly once (no full-search
        # restart per accepted match)
        for op in list(block.ops):
            if id(op) not in used_ops:
                bind(0, {}, {}, anchor=op)
        return matches


@register_pass
class FCLstmFusePass(Pass):
    """ir/fc_lstm_fuse_pass.cc: fc (projection to 4H gates) feeding an
    lstm collapses into one fusion_lstm op (the reference's CPU-fused
    kernel; here the rewrite keeps op-structure parity and drops an IR
    level — XLA fuses either form). Built on GraphPatternDetector."""

    name = "fc_lstm_fuse_pass"

    def _rewrite(self, blk, lstm_op, x, wx, bias_x, dead_ops, xx_name):
        inputs = {"X": [x], "WeightX": [wx],
                  "WeightH": list(lstm_op.inputs["Weight"]),
                  "Bias": list(lstm_op.inputs["Bias"])}
        if bias_x:
            inputs["BiasX"] = [bias_x]
        for h0slot in ("H0", "C0"):
            if lstm_op.inputs.get(h0slot):
                inputs[h0slot] = list(lstm_op.inputs[h0slot])
        lstm_op.type = "fusion_lstm"
        lstm_op.inputs = inputs
        lstm_op.outputs = {"Hidden": list(lstm_op.outputs["Hidden"]),
                           "Cell": list(lstm_op.outputs["Cell"]),
                           "XX": [xx_name]}
        for op in dead_ops:
            blk.ops.remove(op)

    @staticmethod
    def _is_bias_var(blk, name):
        """The folded add's Y must be a real fc bias — a vector of 4H
        gate values (reference fc_lstm_fuse matches the fc pattern's bias
        node, never a residual add)."""
        v = blk._find_var_recursive(name)
        if v is None or v.shape is None:
            return False
        dims = [d for d in v.shape if d not in (1,)]
        return len(dims) <= 1

    def apply_impl(self, program):
        blk = program.global_block()
        # the fc projection appears as an `fc` op, or un-fused as
        # mul(+elementwise_add) — match all three shapes (the reference's
        # pattern is built over the fc-fuse result)
        d = GraphPatternDetector()
        d.add_op("mul", types=["mul"], outputs={"Out": "mm"})
        d.add_op("add", types=["elementwise_add"], inputs={"X": "mm"},
                 outputs={"Out": "proj"}, single_use={"mm"})
        d.add_op("lstm", types=["lstm"], inputs={"Input": "proj"},
                 single_use={"proj"})
        for m in d.detect(blk):
            bias_name = m["add"].inputs["Y"][0]
            if not self._is_bias_var(blk, bias_name):
                continue        # residual add, not an fc bias — skip
            self._rewrite(blk, m["lstm"], m["mul"].inputs["X"][0],
                          m["mul"].inputs["Y"][0],
                          bias_name,
                          [m["mul"], m["add"]],
                          m["add"].outputs["Out"][0])
        d = GraphPatternDetector()
        d.add_op("fc", types=["fc"], outputs={"Out": "proj"})
        d.add_op("lstm", types=["lstm"], inputs={"Input": "proj"},
                 single_use={"proj"})
        for m in d.detect(blk):
            fc_op = m["fc"]
            self._rewrite(blk, m["lstm"], fc_op.inputs["Input"][0],
                          fc_op.inputs["W"][0],
                          fc_op.inputs.get("Bias", [None])[0],
                          [fc_op], fc_op.outputs["Out"][0])
        d = GraphPatternDetector()
        d.add_op("mul", types=["mul"], outputs={"Out": "proj"})
        d.add_op("lstm", types=["lstm"], inputs={"Input": "proj"},
                 single_use={"proj"})
        for m in d.detect(blk):
            mul_op = m["mul"]
            self._rewrite(blk, m["lstm"], mul_op.inputs["X"][0],
                          mul_op.inputs["Y"][0], None,
                          [mul_op], mul_op.outputs["Out"][0])
        return program


@register_pass
class FuseBottleneckPass(Pass):
    """Collapse a BN-folded ResNet bottleneck (conv1x1+bias+relu ->
    conv3x3+bias+relu -> conv1x1+bias -> add(shortcut) -> relu, NHWC) into
    one `fused_bottleneck` op backed by the VMEM-resident Pallas kernel
    (ops/pallas_kernels.py).

    Reference analogue: the conv+bn+act fusion family
    (paddle/fluid/framework/ir/conv_bn_fuse_pass.cc, conv_elementwise_add_
    act_fuse_pass.cc) — the reference fuses per-conv epilogues; on TPU the
    win is fusing ACROSS the block so intermediate activations never leave
    VMEM (ROOFLINE.md "cross-layer fused conv pipelines"). Runs after
    InferenceTranspiler's BN fold, which produces exactly this op chain.
    NHWC only: the kernel keeps channels in the lane dimension; NCHW
    programs are left to XLA untouched.
    """

    name = "fuse_bottleneck_pass"

    @staticmethod
    def _norm2(v, default):
        if v is None:
            return (default, default)
        if isinstance(v, (list, tuple)):
            return (int(v[0]), int(v[1] if len(v) > 1 else v[0]))
        return (int(v), int(v))

    def _conv_geom(self, blk, op, ksize, stride=None, padding=0):
        """conv2d op is a plain kxk NHWC conv with the given geometry."""
        if op.attrs.get("data_format", "NCHW") != "NHWC":
            return None
        if op.inputs.get("Bias"):
            # the fused kernel has no slot for an inline conv bias (the
            # B0/B1/B2 inputs come from the BN-fold elementwise_adds);
            # rewriting would silently drop it and change numerics
            return None
        if int(op.attrs.get("groups", 1) or 1) != 1:
            return None
        if self._norm2(op.attrs.get("dilations"), 1) != (1, 1):
            return None
        if self._norm2(op.attrs.get("paddings"), 0) != (padding, padding):
            return None
        st = self._norm2(op.attrs.get("strides"), 1)
        if st[0] != st[1] or (stride is not None and st != (stride, stride)):
            return None
        w = blk._find_var_recursive(op.inputs["Filter"][0])
        if w is None or w.shape is None or tuple(w.shape[2:]) != (ksize,
                                                                  ksize):
            return None
        return st[0]

    @staticmethod
    def _is_channel_bias(blk, op, channels):
        """elementwise_add whose Y is a persistable per-channel vector of
        the conv's output width, broadcast over the trailing (NHWC
        channel) axis — the exact shape the BN fold emits. A vector
        riding a different axis (or length) is some other computation."""
        if op.attrs.get("axis", -1) not in (-1, 3):
            return False
        v = blk._find_var_recursive(op.inputs["Y"][0])
        if v is None or v.shape is None:
            return False
        dims = [d for d in v.shape if d != 1]
        return (len(dims) <= 1 and getattr(v, "persistable", False)
                and (not dims or dims[0] == channels))

    def _filter_shape(self, blk, op):
        w = blk._find_var_recursive(op.inputs["Filter"][0])
        return None if w is None else tuple(w.shape or ())

    def _detector(self, branch, swapped):
        d = GraphPatternDetector()
        d.add_op("conv0", types=["conv2d"], inputs={"Input": "xin"},
                 outputs={"Output": "c0"})
        d.add_op("add0", types=["elementwise_add"], inputs={"X": "c0"},
                 outputs={"Out": "a0"}, single_use={"c0"})
        d.add_op("relu0", types=["relu"], inputs={"X": "a0"},
                 outputs={"Out": "r0"}, single_use={"a0"})
        d.add_op("conv1", types=["conv2d"], inputs={"Input": "r0"},
                 outputs={"Output": "c1"}, single_use={"r0"})
        d.add_op("add1", types=["elementwise_add"], inputs={"X": "c1"},
                 outputs={"Out": "a1"}, single_use={"c1"})
        d.add_op("relu1", types=["relu"], inputs={"X": "a1"},
                 outputs={"Out": "r1"}, single_use={"a1"})
        d.add_op("conv2", types=["conv2d"], inputs={"Input": "r1"},
                 outputs={"Output": "c2"}, single_use={"r1"})
        d.add_op("add2", types=["elementwise_add"], inputs={"X": "c2"},
                 outputs={"Out": "a2"}, single_use={"c2"})
        if branch:
            d.add_op("convs", types=["conv2d"], inputs={"Input": "xin"},
                     outputs={"Output": "cs"})
            d.add_op("adds", types=["elementwise_add"], inputs={"X": "cs"},
                     outputs={"Out": "short"}, single_use={"cs"})
            res_in = {"X": "short", "Y": "a2"} if not swapped else \
                     {"X": "a2", "Y": "short"}
            single = {"a2", "short"}
        else:
            res_in = {"X": "xin", "Y": "a2"} if not swapped else \
                     {"X": "a2", "Y": "xin"}
            single = {"a2"}
        d.add_op("add_res", types=["elementwise_add"], inputs=res_in,
                 outputs={"Out": "res"}, single_use=single)
        d.add_op("relu_f", types=["relu"], inputs={"X": "res"},
                 outputs={"Out": "out"}, single_use={"res"})
        return d

    def _try_rewrite(self, blk, m, branch):
        s = self._conv_geom(blk, m["conv1"], 3, padding=1)
        if s is None or s not in (1, 2):
            return False
        if self._conv_geom(blk, m["conv0"], 1, stride=1) is None:
            return False
        if self._conv_geom(blk, m["conv2"], 1, stride=1) is None:
            return False
        if branch and self._conv_geom(blk, m["convs"], 1, stride=s) is None:
            return False
        # the kernel needs a consistent OIHW filter chain with a SQUARE
        # 3x3 (C->F->F->C4): a width-changing middle conv is a valid
        # graph but not this kernel's shape — leave it to XLA
        f0 = self._filter_shape(blk, m["conv0"])   # [F, C, 1, 1]
        f1 = self._filter_shape(blk, m["conv1"])   # [F, F, 3, 3]
        f2 = self._filter_shape(blk, m["conv2"])   # [C4, F, 1, 1]
        if not (f0 and f1 and f2):
            return False
        F, C = f0[0], f0[1]
        if f1[:2] != (F, F) or f2[1] != F:
            return False
        # measured-geometry gate: the Pallas kernel wins only for
        # narrow bottlenecks (chip sweep BENCH_recovery_r05.json,
        # tune_bottleneck: F=64 +12% vs XLA, F=128 parity-plus,
        # F=256/512 LOSE). Fusing the losing geometries made the whole
        # inference graph slower, so wide blocks stay with XLA.
        from paddle_tpu.flags import FLAGS
        if F > FLAGS.fuse_bottleneck_max_width:
            return False
        C4 = f2[0]
        if branch:
            fs = self._filter_shape(blk, m["convs"])
            if not fs or fs[:2] != (C4, C):
                return False
        elif C != C4 or s != 1:
            return False
        widths = {"add0": F, "add1": F, "add2": C4, "adds": C4}
        for a in ("add0", "add1", "add2") + (("adds",) if branch else ()):
            if not self._is_channel_bias(blk, m[a], widths[a]):
                return False
        inputs = {"X": list(m["conv0"].inputs["Input"]),
                  "W0": list(m["conv0"].inputs["Filter"]),
                  "B0": list(m["add0"].inputs["Y"]),
                  "W1": list(m["conv1"].inputs["Filter"]),
                  "B1": list(m["add1"].inputs["Y"]),
                  "W2": list(m["conv2"].inputs["Filter"]),
                  "B2": list(m["add2"].inputs["Y"])}
        if branch:
            inputs["Ws"] = list(m["convs"].inputs["Filter"])
            inputs["Bs"] = list(m["adds"].inputs["Y"])
        from .framework import Operator
        fused = Operator(blk, "fused_bottleneck", inputs=inputs,
                         outputs={"Out": list(m["relu_f"].outputs["Out"])},
                         attrs={"stride": s, "data_format": "NHWC"})
        first = min(blk.ops.index(op) for op in m.values())
        for op in m.values():
            blk.ops.remove(op)
        blk.ops.insert(first, fused)
        return True

    def apply_impl(self, program):
        blk = program.global_block()
        n = 0
        # projection-shortcut blocks first (their identity-pattern prefix
        # would otherwise shadow), then identity; both add orderings
        for branch in (True, False):
            for swapped in (False, True):
                for m in self._detector(branch, swapped).detect(blk):
                    n += self._try_rewrite(blk, m, branch)
        if n:
            program._fused_bottlenecks = n
        return program
