"""Program-level pass framework.

Reference analogue: paddle/fluid/framework/ir/ — ir::Graph (graph.h:63),
Pass/PassRegistry (pass.h:32), GraphPatternDetector, and the fusion pass
suite chained by BuildStrategy (details/build_strategy.cc:27).

TPU redesign: most reference passes exist to pre-fuse kernels (fc_fuse,
conv_bn, fuse_elewise_add_act) — XLA's fusion subsumes them, so the fusion
passes here are *structural parity* rewrites kept for program inspection and
op-count parity, while graph_viz / is_test / memory passes carry real
behavior. The pass substrate works on the Program in place (the Program IS
the graph: ops + var def/use edges), mirroring ir::Pass::ApplyImpl.
"""

from .framework import Program

__all__ = ["Pass", "register_pass", "get_pass", "apply_passes",
           "registered_passes"]

_PASS_REGISTRY = {}


class Pass:
    """reference ir/pass.h:32. Subclasses implement apply_impl(program)."""

    name = None

    def __init__(self, **attrs):
        self.attrs = dict(attrs)

    def set(self, key, value):
        self.attrs[key] = value
        return self

    def get(self, key, default=None):
        return self.attrs.get(key, default)

    def apply(self, program):
        out = self.apply_impl(program)
        program._bump_version()
        return out if out is not None else program

    def apply_impl(self, program):
        raise NotImplementedError


def register_pass(cls):
    assert cls.name, "pass needs a name"
    _PASS_REGISTRY[cls.name] = cls
    return cls


def get_pass(name, **attrs):
    return _PASS_REGISTRY[name](**attrs)


def registered_passes():
    return sorted(_PASS_REGISTRY)


def apply_passes(program, names, **attrs):
    for n in names:
        program = get_pass(n, **attrs).apply(program)
    return program


def use_count(block, var_name, _seen=None):
    """Number of ops in `block` consuming var_name (the reference's
    intermediate-node single-consumer rule; shared by the adjacency
    passes and GraphPatternDetector). Reads hidden inside nested
    sub-blocks (conditional_block/while declare outputs={} at the parent
    level) count too — a fusion must not delete an op whose output a
    sub-block still reads."""
    _seen = _seen if _seen is not None else set()
    n_uses = 0
    for o in block.ops:
        n_uses += sum(1 for ns in o.inputs.values() for n in ns
                      if n == var_name)
        sub = o.attrs.get("sub_block")
        if sub is not None and id(sub) not in _seen:
            _seen.add(id(sub))
            n_uses += use_count(sub, var_name, _seen)
    return n_uses


# ---------------------------------------------------------------------------
# concrete passes
# ---------------------------------------------------------------------------

@register_pass
class GraphVizPass(Pass):
    """ir/graph_viz_pass.cc: dump the op/var graph as graphviz dot."""

    name = "graph_viz_pass"

    def apply_impl(self, program):
        from .debugger import draw_block_graphviz
        path = self.get("graph_viz_path", "./program.dot")
        draw_block_graphviz(program.global_block(), path=path)
        return program


@register_pass
class IsTestPass(Pass):
    """ir/is_test_pass.cc: flip is_test on inference-sensitive ops."""

    name = "is_test_pass"

    _OPS = ("dropout", "batch_norm", "lrn", "layer_norm")

    def apply_impl(self, program):
        for blk in program.blocks:
            for op in blk.ops:
                if op.type in self._OPS:
                    op.attrs["is_test"] = True
        return program


@register_pass
class FuseElewiseAddActPass(Pass):
    """ir/fuse_elewise_add_act_pass.cc: elementwise_add + activation ->
    fused_elemwise_activation. XLA fuses these anyway; the rewrite keeps
    op-count/structure parity and exercises the pattern machinery."""

    name = "fuse_elewise_add_act_pass"

    _ACTS = ("relu", "sigmoid", "tanh", "gelu")

    def apply_impl(self, program):
        blk = program.global_block()
        i = 0
        while i < len(blk.ops) - 1:
            add_op = blk.ops[i]
            act_op = blk.ops[i + 1]
            if (add_op.type == "elementwise_add" and
                    act_op.type in self._ACTS and
                    act_op.inputs.get("X", [None])[0] ==
                    add_op.outputs["Out"][0] and
                    self._single_use(blk, add_op.outputs["Out"][0])):
                fused = blk.ops[i]
                fused.type = "fused_elemwise_activation"
                # the activation's own attrs (e.g. gelu's 'approximate')
                # must survive the fusion or the fused lowering reads
                # defaults the unfused path would not have used
                for k, v in act_op.attrs.items():
                    fused.attrs.setdefault(k, v)
                fused.attrs["functor_list"] = [
                    "elementwise_add", act_op.type]
                fused.attrs["axis"] = add_op.attrs.get("axis", -1)
                fused.outputs = {"Out": list(act_op.outputs["Out"])}
                del blk.ops[i + 1]
            i += 1
        return program

    @staticmethod
    def _single_use(blk, name):
        return use_count(blk, name) == 1


@register_pass
class FCFusePass(Pass):
    """ir/fc_fuse_pass.cc: mul + elementwise_add(bias) -> fc op."""

    name = "fc_fuse_pass"

    def apply_impl(self, program):
        blk = program.global_block()
        i = 0
        while i < len(blk.ops) - 1:
            mul_op = blk.ops[i]
            add_op = blk.ops[i + 1]
            if (mul_op.type == "mul" and
                    add_op.type == "elementwise_add" and
                    add_op.inputs.get("X", [None])[0] ==
                    mul_op.outputs["Out"][0] and
                    FuseElewiseAddActPass._single_use(
                        blk, mul_op.outputs["Out"][0])):
                fused = blk.ops[i]
                fused.type = "fc"
                fused.inputs = {"Input": list(mul_op.inputs["X"]),
                                "W": list(mul_op.inputs["Y"]),
                                "Bias": list(add_op.inputs["Y"])}
                fused.attrs = {"in_num_col_dims":
                               mul_op.attrs.get("x_num_col_dims", 1)}
                fused.outputs = {"Out": list(add_op.outputs["Out"])}
                del blk.ops[i + 1]
            i += 1
        return program


@register_pass
class MultiBatchMergePass(Pass):
    """ir/multi_batch_merge_pass.cc (+ test_dist_mnist_batch_merge):
    gradient accumulation — run N micro-batches, apply ONE optimizer
    update from the averaged accumulated gradient.

    The reference rewrote the SSA graph to repeat the fwd/bwd subgraph N
    times per iteration; the TPU-idiomatic encoding keeps one jitted step
    and gates the optimizer ops instead (ops/optimizer_ops._merge_gated):
    this pass creates a persistable accumulation buffer per gradient,
    wires it into each optimizer op, and annotates `merge_n` so the gated
    lowering accumulates on micro-steps and applies+resets every Nth
    step. LR-decay counter increments are gated to count applied updates.

    Usage: get_pass("multi_batch_merge_pass", n=4).apply(main_program)
    """

    name = "multi_batch_merge_pass"

    def apply_impl(self, program):
        from ..ops.optimizer_ops import MERGEABLE_OPT_OPS
        from .layers.learning_rate_scheduler import LR_COUNTER_NAME
        n = int(self.get("n", 1))
        if n <= 1:
            return program
        blk = program.global_block()
        # adam/adamax advance their beta-pow accumulators with separate
        # in-place `scale` ops (optimizer.py _finish_update, mirroring the
        # reference) — those must gate with the optimizer update
        pow_names = set()
        for op in blk.ops:
            if op.type in MERGEABLE_OPT_OPS:
                for slot in ("Beta1Pow", "Beta2Pow"):
                    for nm in op.inputs.get(slot, []):
                        if nm:
                            pow_names.add(nm)
        for op in blk.ops:
            if op.type in MERGEABLE_OPT_OPS:
                gname = op.inputs.get("Grad", [None])[0]
                if not gname:
                    continue
                gvar = blk._find_var_recursive(gname)
                acc_name = gname + "@MERGE_ACC"
                if blk._find_var_recursive(acc_name) is None:
                    blk.create_var(
                        name=acc_name,
                        dtype=gvar.dtype if gvar is not None else "float32",
                        shape=gvar.shape if gvar is not None else None,
                        persistable=True, stop_gradient=True)
                op.inputs["GradAcc"] = [acc_name]
                op.outputs["GradAccOut"] = [acc_name]
                op.attrs["merge_n"] = n
            elif op.type == "increment":
                xn = op.inputs.get("X", [None])[0]
                if xn == LR_COUNTER_NAME:
                    op.attrs["merge_n"] = n
            elif op.type == "scale":
                xn = op.inputs.get("X", [None])[0]
                on = op.outputs.get("Out", [None])[0]
                if xn and xn == on and xn in pow_names:
                    op.attrs["merge_n"] = n
        return program


# ---------------------------------------------------------------------------
# GraphPatternDetector (reference ir/graph_pattern_detector.h: PDPattern of
# PDNodes + subgraph matcher that fusion passes build on). Program-level
# equivalent: declarative op-chain patterns where dataflow is expressed by
# shared symbols bound to concrete variable names during matching.
# ---------------------------------------------------------------------------

class GraphPatternDetector:
    """Declarative subgraph patterns over a Block.

    Usage:
        d = GraphPatternDetector()
        d.add_op("mul", types=["mul"], outputs={"Out": "mm"})
        d.add_op("add", types=["elementwise_add"], inputs={"X": "mm"},
                 single_use={"mm"})
        for m in d.detect(block):   # m: name -> Operator
            ...rewrite...

    Symbols (like "mm") bind to concrete var names; a symbol appearing in
    one node's outputs and another's inputs is a dataflow edge. `single_use`
    marks symbols that must have exactly one consumer in the block (the
    reference's intermediate-node constraint, so fusion never drops a value
    some other op still reads).
    """

    def __init__(self):
        self._nodes = []   # (name, types, in_links, out_links, single_use)

    def add_op(self, name, types, inputs=None, outputs=None,
               single_use=()):
        self._nodes.append((name, tuple(types), dict(inputs or {}),
                            dict(outputs or {}), frozenset(single_use)))
        return self

    @staticmethod
    def _uses(block, var_name):
        return use_count(block, var_name)

    def detect(self, block):
        """Yield non-overlapping matches as {node_name: Operator}."""
        matches = []
        used_ops = set()

        def bind(node_idx, binding, chosen, anchor=None):
            if node_idx == len(self._nodes):
                matches.append(dict(chosen))
                used_ops.update(id(op) for op in chosen.values())
                return True
            name, types, ins, outs, single = self._nodes[node_idx]
            for op in ([anchor] if anchor is not None else block.ops):
                if op.type not in types or id(op) in used_ops or \
                        any(op is c for c in chosen.values()):
                    continue
                b2 = dict(binding)
                ok = True
                for slot, sym in ins.items():
                    actual = op.inputs.get(slot, [None])[0]
                    if actual is None or \
                            (sym in b2 and b2[sym] != actual):
                        ok = False
                        break
                    b2[sym] = actual
                if not ok:
                    continue
                for slot, sym in outs.items():
                    actual = op.outputs.get(slot, [None])[0]
                    if actual is None or \
                            (sym in b2 and b2[sym] != actual):
                        ok = False
                        break
                    b2[sym] = actual
                if not ok:
                    continue
                if any(self._uses(block, b2[s]) != 1 for s in single
                       if s in b2):
                    continue
                chosen[name] = op
                if bind(node_idx + 1, b2, chosen):
                    return True
                del chosen[name]
            return False

        # greedily find all non-overlapping matches: each op is tried as
        # the first pattern node's anchor exactly once (no full-search
        # restart per accepted match)
        for op in list(block.ops):
            if id(op) not in used_ops:
                bind(0, {}, {}, anchor=op)
        return matches


@register_pass
class FCLstmFusePass(Pass):
    """ir/fc_lstm_fuse_pass.cc: fc (projection to 4H gates) feeding an
    lstm collapses into one fusion_lstm op (the reference's CPU-fused
    kernel; here the rewrite keeps op-structure parity and drops an IR
    level — XLA fuses either form). Built on GraphPatternDetector."""

    name = "fc_lstm_fuse_pass"

    def _rewrite(self, blk, lstm_op, x, wx, bias_x, dead_ops, xx_name):
        inputs = {"X": [x], "WeightX": [wx],
                  "WeightH": list(lstm_op.inputs["Weight"]),
                  "Bias": list(lstm_op.inputs["Bias"])}
        if bias_x:
            inputs["BiasX"] = [bias_x]
        for h0slot in ("H0", "C0"):
            if lstm_op.inputs.get(h0slot):
                inputs[h0slot] = list(lstm_op.inputs[h0slot])
        lstm_op.type = "fusion_lstm"
        lstm_op.inputs = inputs
        lstm_op.outputs = {"Hidden": list(lstm_op.outputs["Hidden"]),
                           "Cell": list(lstm_op.outputs["Cell"]),
                           "XX": [xx_name]}
        for op in dead_ops:
            blk.ops.remove(op)

    @staticmethod
    def _is_bias_var(blk, name):
        """The folded add's Y must be a real fc bias — a vector of 4H
        gate values (reference fc_lstm_fuse matches the fc pattern's bias
        node, never a residual add)."""
        v = blk._find_var_recursive(name)
        if v is None or v.shape is None:
            return False
        dims = [d for d in v.shape if d not in (1,)]
        return len(dims) <= 1

    def apply_impl(self, program):
        blk = program.global_block()
        # the fc projection appears as an `fc` op, or un-fused as
        # mul(+elementwise_add) — match all three shapes (the reference's
        # pattern is built over the fc-fuse result)
        d = GraphPatternDetector()
        d.add_op("mul", types=["mul"], outputs={"Out": "mm"})
        d.add_op("add", types=["elementwise_add"], inputs={"X": "mm"},
                 outputs={"Out": "proj"}, single_use={"mm"})
        d.add_op("lstm", types=["lstm"], inputs={"Input": "proj"},
                 single_use={"proj"})
        for m in d.detect(blk):
            bias_name = m["add"].inputs["Y"][0]
            if not self._is_bias_var(blk, bias_name):
                continue        # residual add, not an fc bias — skip
            self._rewrite(blk, m["lstm"], m["mul"].inputs["X"][0],
                          m["mul"].inputs["Y"][0],
                          bias_name,
                          [m["mul"], m["add"]],
                          m["add"].outputs["Out"][0])
        d = GraphPatternDetector()
        d.add_op("fc", types=["fc"], outputs={"Out": "proj"})
        d.add_op("lstm", types=["lstm"], inputs={"Input": "proj"},
                 single_use={"proj"})
        for m in d.detect(blk):
            fc_op = m["fc"]
            self._rewrite(blk, m["lstm"], fc_op.inputs["Input"][0],
                          fc_op.inputs["W"][0],
                          fc_op.inputs.get("Bias", [None])[0],
                          [fc_op], fc_op.outputs["Out"][0])
        d = GraphPatternDetector()
        d.add_op("mul", types=["mul"], outputs={"Out": "proj"})
        d.add_op("lstm", types=["lstm"], inputs={"Input": "proj"},
                 single_use={"proj"})
        for m in d.detect(blk):
            mul_op = m["mul"]
            self._rewrite(blk, m["lstm"], mul_op.inputs["X"][0],
                          mul_op.inputs["Y"][0], None,
                          [mul_op], mul_op.outputs["Out"][0])
        return program
