"""In-graph streaming evaluators.

Reference analogue: python/paddle/fluid/evaluator.py — Evaluator base keeps
accumulator state variables in the program, `reset` zeroes them and `eval`
computes the final metric; ChunkEvaluator and EditDistance mirror the
reference's two concrete evaluators (DetectionMAP lives with the detection
suite).
"""

import numpy as np

from .framework import Program, Variable, default_main_program, program_guard
from . import layers
from .layer_helper import LayerHelper
from .executor import global_scope
from .initializer import Constant

__all__ = ["ChunkEvaluator", "EditDistance"]


class Evaluator:
    def __init__(self, name, **kwargs):
        self.states = []
        self.metrics = []
        self.helper = LayerHelper(name, **kwargs)

    def reset(self, executor, reset_program=None):
        from . import core
        scope = global_scope()
        for var in self.states:
            dtype = core.convert_dtype_to_np(var.dtype) \
                if var.dtype is not None else np.float32
            scope.set(var.name, np.zeros(
                [1 if d is None or d < 0 else d for d in (var.shape or [1])],
                dtype=dtype))

    def _create_state(self, suffix, dtype, shape):
        state = self.helper.create_variable(
            name="_".join([self.helper.name, suffix]), persistable=True,
            dtype=dtype, shape=shape)
        self.states.append(state)
        return state


class ChunkEvaluator(Evaluator):
    """Streaming chunk F1 (reference evaluator.py ChunkEvaluator): counts
    inferred/label/correct chunks via the chunk_eval op and accumulates."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None):
        super().__init__("chunk_eval")
        main_program = self.helper.main_program
        (precision, recall, f1_score, num_infer_chunks, num_label_chunks,
         num_correct_chunks) = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)
        self.num_infer_chunks = self._create_state(
            "num_infer_chunks", "int64", [1])
        self.num_label_chunks = self._create_state(
            "num_label_chunks", "int64", [1])
        self.num_correct_chunks = self._create_state(
            "num_correct_chunks", "int64", [1])
        layers.sums(input=[self.num_infer_chunks, num_infer_chunks],
                    out=self.num_infer_chunks)
        layers.sums(input=[self.num_label_chunks, num_label_chunks],
                    out=self.num_label_chunks)
        layers.sums(input=[self.num_correct_chunks, num_correct_chunks],
                    out=self.num_correct_chunks)
        self.metrics.extend([precision, recall, f1_score])

    def eval(self, executor, eval_program=None):
        scope = global_scope()
        num_infer = float(np.asarray(scope.get(self.num_infer_chunks.name)))
        num_label = float(np.asarray(scope.get(self.num_label_chunks.name)))
        num_correct = float(np.asarray(
            scope.get(self.num_correct_chunks.name)))
        precision = num_correct / num_infer if num_infer else 0.0
        recall = num_correct / num_label if num_label else 0.0
        f1 = 2 * precision * recall / (precision + recall) \
            if num_correct else 0.0
        return np.array([precision]), np.array([recall]), np.array([f1])


class EditDistance(Evaluator):
    """Streaming edit distance (reference evaluator.py EditDistance)."""

    def __init__(self, input, label, ignored_tokens=None):
        super().__init__("edit_distance")
        distances, seq_num = layers.edit_distance(
            input=input, label=label, ignored_tokens=ignored_tokens)
        self.total_distance = self._create_state(
            "total_distance", "float32", [1])
        self.seq_num = self._create_state("seq_num", "int64", [1])
        self.instance_error = self._create_state(
            "instance_error", "int64", [1])
        dist_sum = layers.reduce_sum(distances)
        err = layers.cast(distances > layers.fill_constant(
            [1], "float32", 0.0), "int64")
        err_sum = layers.reduce_sum(err)
        layers.sums(input=[self.total_distance, dist_sum],
                    out=self.total_distance)
        layers.sums(input=[self.seq_num, seq_num], out=self.seq_num)
        layers.sums(input=[self.instance_error, err_sum],
                    out=self.instance_error)

    def eval(self, executor, eval_program=None):
        scope = global_scope()
        total = float(np.asarray(scope.get(self.total_distance.name)))
        n = float(np.asarray(scope.get(self.seq_num.name)))
        errs = float(np.asarray(scope.get(self.instance_error.name)))
        avg = total / n if n else 0.0
        return np.array([avg]), np.array([errs / n if n else 0.0])
