"""Profiler (reference python/paddle/fluid/profiler.py:39-:221 over
platform/profiler.cc event tables + CUPTI device_tracer +
tools/timeline.py:115 chrome-trace conversion).

TPU redesign: jax.profiler owns the device timeline (XPlane; also emits a
chrome-trace JSON directly, subsuming tools/timeline.py's proto->chrome
conversion). On top of that this module keeps the reference's *host* story:
RecordEvent RAII spans aggregate into the sorted summary table that
``stop_profiler(sorted_key)`` prints (profiler.cc PrintProfiler), and device
XLA-op durations parsed from the captured trace join the same table, which
replaces the CUPTI kernel table.
"""

import contextlib
import glob
import gzip
import json
import os
import threading
import time

__all__ = ["cuda_profiler", "reset_profiler", "profiler", "start_profiler",
           "stop_profiler", "RecordEvent", "export_chrome_tracing"]

_trace_dir = None
_tracing = False
_host_events = {}    # name -> [calls, total_ms, min_ms, max_ms]
# _host_events is mutated from every instrumented thread (batcher lanes,
# prefetch workers, the train loop); the read-modify-write in _record is
# NOT atomic under the GIL, so concurrent RecordEvents corrupted the
# summary table before this lock existed (two threads could both see the
# same e[0] and lose a call).  tests/test_profiler.py hammers this.
_events_lock = threading.Lock()
_enabled = False


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    # accepted for API parity; routes to the jax trace
    with profiler("All", "total", output_file):
        yield


def reset_profiler():
    """reference profiler.py reset_profiler: clear collected events."""
    with _events_lock:
        _host_events.clear()


def _record(name, ms):
    with _events_lock:
        e = _host_events.get(name)
        if e is None:
            _host_events[name] = [1, ms, ms, ms]
        else:
            e[0] += 1
            e[1] += ms
            e[2] = min(e[2], ms)
            e[3] = max(e[3], ms)


def start_profiler(state="All", tracer_option=None, output_dir=None):
    global _trace_dir, _tracing, _enabled
    import jax
    from ..flags import FLAGS
    _enabled = True
    reset_profiler()
    _trace_dir = output_dir or os.environ.get(
        "PADDLE_TPU_TRACE_DIR", FLAGS.profiler_path)
    try:
        jax.profiler.start_trace(_trace_dir)
        _tracing = True
    except Exception:
        _tracing = False    # host-only profiling still works


def _is_xla_op_event(e, pids, tids):
    """Robust XLA-op detection across jax trace-format drift: primary
    signal is the event's own args (hlo_category/long_name accompany
    every XLA op in xplane-derived traces); fallback is the thread name
    CONTAINING 'XLA Ops' under a TPU/device-ish process."""
    args = e.get("args") or {}
    if "hlo_category" in args or "long_name" in args:
        return True
    tname = str(tids.get((e.get("pid"), e.get("tid")), ""))
    if "XLA Ops" not in tname:
        return False
    pname = str(pids.get(e.get("pid"), ""))
    return ("TPU" in pname) or ("device" in pname.lower()) or not pname


def _device_events(trace_dir):
    """Aggregate device XLA-op durations from the captured chrome trace
    (the CUPTI kernel-table analogue). Parse problems WARN instead of
    silently yielding an empty table."""
    import warnings
    out = {}
    files = sorted(glob.glob(os.path.join(
        trace_dir, "plugins/profile/*/*.trace.json.gz")))
    if not files:
        return out
    import zlib
    try:
        with gzip.open(files[-1]) as f:
            data = json.load(f)
    except (OSError, ValueError, EOFError, zlib.error) as e:
        # EOFError/zlib.error: jax was still flushing (or died writing)
        # the trace — degrade to host-only tables, but say so
        warnings.warn("profiler: could not parse device trace %s: %s"
                      % (files[-1], e))
        return out
    events = data.get("traceEvents", [])
    pids, tids = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        args = e.get("args") or {}
        if e.get("name") == "process_name":
            pids[e.get("pid")] = args.get("name", "")
        elif e.get("name") == "thread_name":
            tids[(e.get("pid"), e.get("tid"))] = args.get("name", "")
    for e in events:
        if e.get("ph") != "X" or "name" not in e:
            continue
        if not _is_xla_op_event(e, pids, tids):
            continue
        ms = e.get("dur", 0) / 1000.0
        name = "xla::" + e["name"]
        rec = out.get(name)
        if rec is None:
            out[name] = [1, ms, ms, ms]
        else:
            rec[0] += 1
            rec[1] += ms
            rec[2] = min(rec[2], ms)
            rec[3] = max(rec[3], ms)
    if events and not out:
        # a pure-host trace (CPU backend: every process is '/host:CPU'
        # and X events are python frames / threadpool regions) simply has
        # no device op table — only warn when a device process exists but
        # its ops failed to parse, which indicates real format drift
        has_device_pid = any(
            ("TPU" in str(n)) or ("GPU" in str(n)) or
            ("device" in str(n).lower())
            for n in pids.values())
        if has_device_pid:
            warnings.warn(
                "profiler: device trace parsed but no XLA-op events "
                "matched — the jax trace format may have changed "
                "(expected X events with hlo_category args or an "
                "'XLA Ops' thread)")
    return out


_SORT_KEYS = {"calls": 0, "total": 1, "min": 2, "max": 3, "ave": 4,
              "default": 1, None: 1}


def _format_table(rows, sorted_key):
    idx = _SORT_KEYS.get(sorted_key, 1)
    total_time = sum(r[2] for r in rows) or 1.0
    # row: (name, calls, total, min, max, ave)
    full = [(n, c, t, mn, mx, t / c if c else 0.0)
            for n, c, t, mn, mx in rows]
    full.sort(key=lambda r: r[1 + idx], reverse=True)
    lines = ["", "------------------------->     Profiling Report     "
             "<-------------------------", "",
             "%-44s %8s %12s %12s %12s %12s %8s" % (
                 "Event", "Calls", "Total(ms)", "Min(ms)", "Max(ms)",
                 "Ave(ms)", "Ratio")]
    for n, c, t, mn, mx, ave in full:
        lines.append("%-44s %8d %12.4f %12.4f %12.4f %12.4f %7.4f" % (
            n[:44], c, t, mn, mx, ave, t / total_time))
    return "\n".join(lines)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    """Stop tracing and print the sorted event summary table
    (reference DisableProfiler -> PrintProfiler, platform/profiler.cc).
    Returns the trace directory (contains the chrome-trace JSON)."""
    global _tracing, _enabled
    import jax
    if _tracing:
        jax.profiler.stop_trace()
        _tracing = False
    if not _enabled:
        return _trace_dir
    _enabled = False
    with _events_lock:
        rows = [(n, e[0], e[1], e[2], e[3])
                for n, e in _host_events.items()]
    if _trace_dir:
        rows += [(n, e[0], e[1], e[2], e[3])
                 for n, e in _device_events(_trace_dir).items()]
    if rows:
        table = _format_table(rows, sorted_key)
        print(table)
        try:
            with open(profile_path, "w") as f:
                f.write(table + "\n")
        except OSError:
            pass
    return _trace_dir


def export_chrome_tracing(trace_dir=None, output_path=None,
                          merge_obs=True):
    """tools/timeline.py:115 analogue: surface the captured trace as a
    chrome://tracing-loadable JSON file. jax already records chrome-trace
    JSON inside the XPlane dump; this decompresses the newest one and —
    with ``merge_obs`` (default) — appends the obs tracing ring's spans
    as their own process rows, so the host-side request/step stage spans
    (OBSERVABILITY.md) line up against the XLA device timeline in one
    view.  A trace whose JSON cannot be parsed is exported raw (the jax
    bytes are never lost to the merge)."""
    trace_dir = trace_dir or _trace_dir
    if trace_dir is None:
        raise ValueError("no trace captured; run the profiler first")
    files = sorted(glob.glob(os.path.join(
        trace_dir, "plugins/profile/*/*.trace.json.gz")))
    if not files:
        raise FileNotFoundError("no trace.json.gz under %s" % trace_dir)
    output_path = output_path or os.path.join(trace_dir, "timeline.json")
    with gzip.open(files[-1], "rb") as src:
        raw = src.read()
    if merge_obs:
        try:
            from ..obs import tracing as obs_tracing
            obs_spans = obs_tracing.recent_spans()
            if obs_spans:
                data = json.loads(raw)
                events = data.setdefault("traceEvents", [])
                events.extend(obs_tracing.chrome_events(obs_spans))
                raw = json.dumps(data).encode()
        except ValueError:
            pass  # unparseable device trace: export the raw bytes
    with open(output_path, "wb") as dst:
        dst.write(raw)
    return output_path


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
             tracer_option=None):
    """with fluid.profiler.profiler(sorted_key="total"): ... — prints the
    aggregated event table on exit (reference profiler.py:39)."""
    start_profiler(state, tracer_option,
                   profile_path if os.path.isdir(str(profile_path))
                   else None)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class RecordEvent:
    """Named host-side region (reference platform/profiler.h:72 RAII
    marker): aggregates into the profiler table and annotates the jax
    device trace."""

    def __init__(self, name):
        self.name = name
        self._ctx = None
        self._t0 = None

    def __enter__(self):
        import jax
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *args):
        if self._t0 is not None:
            _record(self.name, (time.perf_counter() - self._t0) * 1e3)
        self._ctx.__exit__(*args)
