"""Profiler (reference python/paddle/fluid/profiler.py:39-:221 over
platform/profiler.cc + CUPTI device_tracer).

TPU redesign: jax.profiler owns both host and device timelines (XPlane →
Perfetto/TensorBoard), replacing the RecordEvent tables + CUPTI tracer +
tools/timeline.py chrome-trace pipeline. The RAII named-region design is kept
via profiler.scope()/RecordEvent."""

import contextlib
import os
import time

__all__ = ["cuda_profiler", "reset_profiler", "profiler", "start_profiler",
           "stop_profiler", "RecordEvent"]

_trace_dir = None


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    # accepted for API parity; routes to the jax trace
    with profiler("All", "total", output_file):
        yield


def reset_profiler():
    pass


def start_profiler(state="All", tracer_option=None, output_dir=None):
    global _trace_dir
    import jax
    _trace_dir = output_dir or os.environ.get(
        "PADDLE_TPU_TRACE_DIR", "/tmp/paddle_tpu_trace")
    jax.profiler.start_trace(_trace_dir)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    import jax
    jax.profiler.stop_trace()
    return _trace_dir


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
             tracer_option=None):
    """with fluid.profiler.profiler(...): — wraps jax.profiler.trace."""
    start_profiler(state, tracer_option,
                   profile_path if os.path.isdir(str(profile_path))
                   else None)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class RecordEvent:
    """Named host-side region (reference platform/profiler.h:72 RAII marker);
    shows up in the jax trace via TraceAnnotation."""

    def __init__(self, name):
        self.name = name
        self._ctx = None

    def __enter__(self):
        import jax
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()
        return self

    def __exit__(self, *args):
        self._ctx.__exit__(*args)
