"""Program/Block/Operator/Variable graph IR.

This is the TPU-native re-design of the reference's two-layer IR:
python/paddle/fluid/framework.py (Variable :204, Operator :494, Block :920,
Program :1404, Parameter :1977) over paddle/fluid/framework/framework.proto
(ProgramDesc :184, BlockDesc :171, OpDesc :43, VarDesc :165).

Design difference from the reference (deliberate, see SURVEY.md §7): there is a
single in-memory graph object — no separate protobuf "Desc" layer that Python
mirrors — because the execution substrate is XLA: an entire block is
functionalized at trace time into one HLO computation (see executor.py), so the
IR's job is program *construction*, autodiff and serialization, not per-op
dispatch. Serialization to/from a stable dict/JSON format replaces the
protobuf round-trip (framework.py Program.desc / parse_from_string parity).

Shape/dtype inference is delegated to the op registry, which runs the op's JAX
lowering under jax.eval_shape (paddle_tpu/ops/registry.py) — the reference's
per-op C++ InferShape (op_desc.cc:660) falls out of the lowering for free.
"""

import collections
import contextlib
import json

import numpy as np

from . import core, unique_name
from .core import VarDesc, convert_np_dtype_to_dtype_

__all__ = [
    "Program", "Block", "Variable", "Operator", "Parameter",
    "default_startup_program", "default_main_program", "program_guard",
    "name_scope", "grad_var_name", "in_dygraph_mode",
]

GRAD_VAR_SUFFIX = "@GRAD"
ZERO_VAR_SUFFIX = "@ZERO"


def grad_var_name(var_name):
    return var_name + GRAD_VAR_SUFFIX


def in_dygraph_mode():
    # The rebuild is graph-first; imperative mode is provided by the `imperative`
    # module (later milestone), which never flips this global.
    return False


_name_scope_stack = []


@contextlib.contextmanager
def name_scope(prefix=None):
    """Debug name scoping (reference framework.py:80)."""
    if prefix:
        _name_scope_stack.append(prefix)
    try:
        yield
    finally:
        if prefix:
            _name_scope_stack.pop()


def _current_name_scope():
    return "/".join(_name_scope_stack)


class Variable:
    """A named tensor slot in a Block (reference framework.py:204).

    LoD (ragged sequence) support: `lod_level > 0` marks the variable as
    carrying ragged rows; at runtime the value is a LoDArray (dense data +
    row-split metadata) — see paddle_tpu/fluid/lod.py. This reproduces the
    reference's LoDTensor capability (lod_tensor.h:110) in the dense
    segment-id encoding idiomatic to XLA's static shapes.
    """

    def __init__(self, block, type=VarDesc.VarType.LOD_TENSOR, name=None,
                 shape=None, dtype=None, lod_level=None, capacity=None,
                 persistable=None, error_clip=None, stop_gradient=False,
                 is_data=False, initializer=None, **kwargs):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        self.name = name
        self.type = type
        self.shape = tuple(shape) if shape is not None else None
        if dtype is not None and not isinstance(dtype, int):
            dtype = convert_np_dtype_to_dtype_(dtype)
        self.dtype = dtype if dtype is not None else VarDesc.VarType.FP32
        self.lod_level = lod_level if lod_level is not None else 0
        self.persistable = bool(persistable) if persistable is not None else False
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.error_clip = error_clip
        self.op = None  # generating op, set by Block.append_op

    # ---- fluid API surface ----
    def astype(self, dtype):
        from .layers import tensor as tensor_layers
        return tensor_layers.cast(self, dtype)

    @property
    def grad_name(self):
        return grad_var_name(self.name)

    @property
    def np_dtype(self):
        return core.convert_dtype_to_np(self.dtype)

    # ---- static shape metadata (analysis/resources.py) ----
    def numel_hint(self, batch=1):
        """Static element count with every dynamic dim (-1/None)
        substituted by `batch` — the size the resource analyzer plans
        memory with.  None when the shape was never recorded."""
        if self.shape is None:
            return None
        n = 1
        for d in self.shape:
            n *= int(batch) if (d is None or int(d) < 0) else int(d)
        return int(n)

    def nbytes_hint(self, batch=1):
        """Static byte size under the `batch` hint (numel_hint x dtype
        size; int8 vars read one byte/elem — the quantized lane's
        footprint falls out of the recorded dtype)."""
        n = self.numel_hint(batch=batch)
        if n is None:
            return None
        return n * core.dtype_size(self.dtype)

    def to_string(self, throw_on_error=True, with_details=False):
        return repr(self)

    def __repr__(self):
        return "Variable(name=%s, shape=%s, dtype=%s, lod_level=%d%s)" % (
            self.name, self.shape, self.np_dtype.name if self.dtype is not None
            else None, self.lod_level,
            ", persistable" if self.persistable else "")

    __str__ = __repr__

    def _serialize(self):
        return {
            "name": self.name, "type": self.type,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype, "lod_level": self.lod_level,
            "persistable": self.persistable, "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
            "is_parameter": isinstance(self, Parameter),
            "trainable": getattr(self, "trainable", None),
        }


class Parameter(Variable):
    """A persistable, trainable Variable (reference framework.py:1977)."""

    def __init__(self, block, shape, dtype, **kwargs):
        if shape is None or dtype is None:
            raise ValueError("Parameter needs shape and dtype")
        kwargs.setdefault("persistable", True)
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)
        self.trainable = kwargs.get("trainable", True)
        self.optimize_attr = kwargs.get("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.get("regularizer", None)
        self.gradient_clip_attr = kwargs.get("gradient_clip_attr", None)
        self.do_model_average = kwargs.get("do_model_average", None)


class Operator:
    """One node in a Block (reference framework.py:494 / OpDesc framework.proto:43).

    inputs/outputs map slot name -> list of variable names. Attributes are
    plain python values (the protobuf Attr variants collapse to JSON types,
    plus Block references for control-flow ops).
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs = {}   # slot -> [var names]
        self.outputs = {}  # slot -> [var names]
        self.attrs = dict(attrs) if attrs else {}
        if _name_scope_stack:
            self.attrs.setdefault("op_namescope", _current_name_scope())
        # uid is PER-PROGRAM creation order (not a process-global counter):
        # the per-op RNG stream folds in uid, so two identically-built
        # programs draw identical random values — the reference's
        # deterministic per-op `seed` assignment under a fixed
        # program.random_seed
        self.uid = block.program._next_op_uid()

        def norm(d, target):
            if d is None:
                return
            for slot, vs in d.items():
                if vs is None:
                    target[slot] = []
                    continue
                if not isinstance(vs, (list, tuple)):
                    vs = [vs]
                target[slot] = [v.name if isinstance(v, Variable) else v
                                for v in vs]

        norm(inputs, self.inputs)
        norm(outputs, self.outputs)

    # ---- accessors (fluid parity) ----
    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    @property
    def output_arg_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    @property
    def input_names(self):
        return list(self.inputs.keys())

    @property
    def output_names(self):
        return list(self.outputs.keys())

    def attr(self, name):
        return self.attrs[name]

    def has_attr(self, name):
        return name in self.attrs

    def _set_attr(self, name, val):
        self.attrs[name] = val

    def all_attrs(self):
        return dict(self.attrs)

    def __repr__(self):
        return "Operator(%s: %s -> %s)" % (self.type, self.inputs, self.outputs)

    __str__ = __repr__

    @staticmethod
    def _encode_attr(v):
        """JSON-encodable form of one attr value.  Recursive: a Block
        (or ndarray / numpy scalar) may sit INSIDE a container attr —
        e.g. recurrent_grad's stashed fwd_attrs dict carries the
        forward sub_block — and a program holding one must still
        clone/serialize."""
        if isinstance(v, Block):
            return {"__block__": v.idx}
        if isinstance(v, np.ndarray):
            return {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.floating):
            return float(v)
        if isinstance(v, dict):
            return {k: Operator._encode_attr(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [Operator._encode_attr(x) for x in v]
        return v

    def _serialize(self):
        attrs = {k: self._encode_attr(v) for k, v in self.attrs.items()}
        # uid round-trips so per-op RNG streams (registry.ExecContext.rng_key
        # folds in op.uid) are identical in clones — the reference's per-op
        # `seed` attr semantics under Program.clone
        return {"type": self.type, "inputs": self.inputs,
                "outputs": self.outputs, "attrs": attrs, "uid": self.uid}


class Block:
    """An ordered op list + var map (reference framework.py:920)."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = collections.OrderedDict()  # name -> Variable
        self.ops = []
        self.forward_block_idx = -1

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    def var(self, name):
        v = self.vars.get(name)
        if v is None:
            raise ValueError("var %s not in block %d" % (name, self.idx))
        return v

    def has_var(self, name):
        return name in self.vars

    def _var_recursive(self, name):
        """Look up through parent scopes (reference Block._var_recursive)."""
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        raise ValueError("var %s not found in block hierarchy" % name)

    def _find_var_recursive(self, name):
        try:
            return self._var_recursive(name)
        except ValueError:
            return None

    def create_var(self, **kwargs):
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            return self.vars[name]
        v = Variable(self, **kwargs)
        self.vars[v.name] = v
        return v

    def create_parameter(self, **kwargs):
        p = Parameter(self, **kwargs)
        # Parameters live in the global (root) block, like the reference.
        gb = self.program.global_block()
        gb.vars[p.name] = p
        p.block = gb
        return p

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def append_op(self, type=None, inputs=None, outputs=None, attrs=None,
                  infer_shape=True):
        op = Operator(self, type=type, inputs=inputs, outputs=outputs,
                      attrs=attrs)
        self.ops.append(op)
        self.program._bump_version()
        for vs in op.outputs.values():
            for name in vs:
                v = self._find_var_recursive(name)
                if v is not None:
                    v.op = op
        if infer_shape:
            from ..ops import registry
            registry.infer_shape(op, self)
        return op

    def _prepend_op(self, type=None, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type=type, inputs=inputs, outputs=outputs,
                      attrs=attrs)
        self.ops.insert(0, op)
        self.program._bump_version()
        return op

    def _insert_op(self, index, type=None, inputs=None, outputs=None,
                   attrs=None):
        op = Operator(self, type=type, inputs=inputs, outputs=outputs,
                      attrs=attrs)
        self.ops.insert(index, op)
        self.program._bump_version()
        return op

    def _remove_op(self, index):
        del self.ops[index]
        self.program._bump_version()

    def _serialize(self):
        return {
            "idx": self.idx, "parent_idx": self.parent_idx,
            "forward_block_idx": self.forward_block_idx,
            "vars": [v._serialize() for v in self.vars.values()],
            "ops": [op._serialize() for op in self.ops],
        }


class Program:
    """Whole-model IR: a list of Blocks (reference framework.py:1404 /
    ProgramDesc framework.proto:184). Executors consume this directly."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self._seed = 0
        self._version = 0
        self._op_role = "Forward"
        self._op_role_var = []
        self._op_uid = 0
        # executor cache invalidation token
        self._cache_id = id(self)

    def _next_op_uid(self):
        self._op_uid += 1
        return self._op_uid

    # ---- version / cache token ----
    def _bump_version(self):
        self._version += 1

    @property
    def random_seed(self):
        return self._seed

    @random_seed.setter
    def random_seed(self, seed):
        self._seed = int(seed)

    # ---- block management ----
    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def block(self, idx):
        return self.blocks[idx]

    @property
    def num_blocks(self):
        return len(self.blocks)

    def _create_block(self, parent_idx=None):
        new_idx = len(self.blocks)
        parent = self.current_block_idx if parent_idx is None else parent_idx
        self.blocks.append(Block(self, new_idx, parent))
        self.current_block_idx = new_idx
        self._bump_version()
        return self.current_block()

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    @contextlib.contextmanager
    def _block_guard(self, parent_idx=None):
        self._create_block(parent_idx)
        try:
            yield self.current_block()
        finally:
            self._rollback()

    # ---- parameters ----
    def all_parameters(self):
        return self.global_block().all_parameters()

    def list_vars(self):
        for blk in self.blocks:
            for v in blk.vars.values():
                yield v

    # ---- clone / prune (reference framework.py Program.clone/prune) ----
    def clone(self, for_test=False):
        p = Program.parse_from_string(self.serialize_to_string())
        if for_test:
            for blk in p.blocks:
                for op in blk.ops:
                    if "is_test" in _default_test_attrs.get(op.type, ()):
                        op.attrs["is_test"] = True
                    if op.type == "dropout":
                        op.attrs["is_test"] = True
                    if op.type == "batch_norm":
                        op.attrs["is_test"] = True
        return p

    def _prune(self, feeds, fetches):
        """Return a clone containing only ops needed to compute `fetches`
        from `feeds` (reference Program.prune, used by save_inference_model)."""
        p = self.clone()
        blk = p.global_block()
        feed_names = set(feeds)
        needed = set(fetches)
        keep = []
        for op in reversed(blk.ops):
            if set(op.output_arg_names) & needed:
                keep.append(op)
                for n in op.input_arg_names:
                    if n not in feed_names:
                        needed.add(n)
        keep.reverse()
        blk.ops = keep
        used = set()
        for op in blk.ops:
            used.update(op.input_arg_names)
            used.update(op.output_arg_names)
        used |= feed_names | set(fetches)
        blk.vars = collections.OrderedDict(
            (k, v) for k, v in blk.vars.items() if k in used)
        p._bump_version()
        return p

    # ---- serialization (replaces protobuf round-trip) ----
    def serialize_to_string(self):
        return json.dumps({
            "version": 1,
            "seed": self._seed,
            "blocks": [b._serialize() for b in self.blocks],
        })

    @staticmethod
    def parse_from_string(s):
        data = json.loads(s)
        p = Program()
        p._seed = data.get("seed", 0)
        p.blocks = []
        for bdata in data["blocks"]:
            blk = Block(p, bdata["idx"], bdata["parent_idx"])
            blk.forward_block_idx = bdata.get("forward_block_idx", -1)
            p.blocks.append(blk)
        for blk, bdata in zip(p.blocks, data["blocks"]):
            for vd in bdata["vars"]:
                cls = Parameter if vd.pop("is_parameter", False) else Variable
                trainable = vd.pop("trainable", None)
                v = cls(blk, **vd)
                if trainable is not None:
                    v.trainable = trainable
                blk.vars[v.name] = v
            def _decode_attr(av):
                if isinstance(av, dict):
                    if "__block__" in av:
                        return p.blocks[av["__block__"]]
                    if "__ndarray__" in av:
                        return np.array(av["__ndarray__"],
                                        dtype=av["dtype"])
                    return {k: _decode_attr(x) for k, x in av.items()}
                if isinstance(av, list):
                    return [_decode_attr(x) for x in av]
                return av

            for od in bdata["ops"]:
                attrs = {k: _decode_attr(av)
                         for k, av in od["attrs"].items()}
                op = Operator(blk, od["type"], od["inputs"], od["outputs"],
                              attrs)
                if "uid" in od:
                    op.uid = od["uid"]
                    p._op_uid = max(p._op_uid, op.uid)
                blk.ops.append(op)
        p._bump_version()
        return p

    def to_string(self, throw_on_error=True, with_details=False):
        lines = []
        for blk in self.blocks:
            lines.append("-- block %d (parent %d) --" % (blk.idx,
                                                         blk.parent_idx))
            for v in blk.vars.values():
                lines.append("  " + repr(v))
            for op in blk.ops:
                lines.append("  " + repr(op))
        return "\n".join(lines)

    __str__ = to_string


_default_test_attrs = {
    "dropout": ("is_test",),
    "batch_norm": ("is_test",),
}

# ---- default programs & guards (reference framework.py:2061-:2129) ----

_main_program_ = Program()
_startup_program_ = Program()


def default_startup_program():
    return _startup_program_


def default_main_program():
    return _main_program_


def switch_main_program(program):
    global _main_program_
    prev = _main_program_
    _main_program_ = program
    return prev


def switch_startup_program(program):
    global _startup_program_
    prev = _startup_program_
    _startup_program_ = program
    return prev


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)
