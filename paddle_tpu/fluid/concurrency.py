"""CSP concurrency: Go blocks + channels.

Reference analogue: paddle/fluid/operators/csp/go_op.cc (GoOp spawns a
detached thread running a sub-block via a nested Executor) and the Fluid
CHANNEL variable type (framework.proto:105 VarType CHANNEL) with the
channel_send/recv/close kernels of that era.

TPU redesign: channels are host-side synchronized queues living in the
interpreter env, and a Go block is a daemon thread interpreting its
sub-block over a snapshot env sharing the channel objects — so programs
using CSP run on the Executor's eager host path (the ops are HOST_OPS),
exactly like the reference ran these on CPU outside any device stream.
Device compute inside a Go block still jits per op group.
"""

from .layer_helper import LayerHelper
from .layers.control_flow import BlockGuard, _external_block_io

__all__ = ["Go", "make_channel", "channel_send", "channel_recv",
           "channel_close"]


def make_channel(dtype, capacity=0):
    """Create a channel variable (CHANNEL VarType analogue). capacity=0
    means an unbuffered (rendezvous-free, size-1 handoff) channel like
    the reference's default."""
    helper = LayerHelper("channel_create")
    ch = helper.create_variable_for_type_inference(dtype)
    ch.stop_gradient = True
    helper.append_op(type="channel_create", inputs={},
                     outputs={"Out": [ch.name]},
                     attrs={"capacity": int(capacity)},
                     infer_shape=False)
    return ch


def channel_send(channel, value):
    """Blocking send (bounded-queue put)."""
    helper = LayerHelper("channel_send")
    status = helper.create_variable_for_type_inference("bool")
    helper.append_op(type="channel_send",
                     inputs={"Channel": [channel.name],
                             "X": [value.name]},
                     outputs={"Status": [status.name]},
                     infer_shape=False)
    return status


def channel_recv(channel, return_value):
    """Blocking receive into `return_value`; Status is False once the
    channel is closed and drained."""
    helper = LayerHelper("channel_recv")
    status = helper.create_variable_for_type_inference("bool")
    helper.append_op(type="channel_recv",
                     inputs={"Channel": [channel.name]},
                     outputs={"Out": [return_value.name],
                              "Status": [status.name]},
                     infer_shape=False)
    return status


def channel_close(channel):
    helper = LayerHelper("channel_close")
    helper.append_op(type="channel_close",
                     inputs={"Channel": [channel.name]}, outputs={},
                     infer_shape=False)


class Go:
    """reference go_op.cc: `with fluid.Go():` builds a sub-block that runs
    concurrently (daemon thread) when execution reaches the go op."""

    def __init__(self, name=None):
        self.helper = LayerHelper("go", name=name)

    def __enter__(self):
        self._guard = BlockGuard(self.helper.main_program)
        self._guard.__enter__()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        ok = self._guard.__exit__(exc_type, exc_val, exc_tb)
        if exc_type is None:
            self._construct_go_op()
        return ok

    def _construct_go_op(self):
        main_program = self.helper.main_program
        # the guard already rolled back: current block is the parent,
        # the go body is the block created inside __enter__
        parent_block = main_program.current_block()
        go_block = None
        # the body is the highest-index block whose parent is the current
        # block and which is not yet owned by another control-flow op
        owned = set()
        for blk in main_program.blocks:
            for op in blk.ops:
                sb = op.attrs.get("sub_block")
                if sb is not None:
                    owned.add(sb.idx)
        for blk in main_program.blocks:
            if blk.parent_idx == parent_block.idx and blk.idx not in owned:
                go_block = blk
        assert go_block is not None, "Go body block not found"
        reads, _ = _external_block_io(go_block, parent_block)
        parent_block.append_op(
            type="go", inputs={"X": reads}, outputs={},
            attrs={"sub_block": go_block}, infer_shape=False)
