"""Streaming Python-side metric accumulators (reference
python/paddle/fluid/metrics.py).

Deliberate deviation (r5 audit): the reference's binary Precision and
Recall classes are buggy in this era — Precision.update conditions on
``label == 1`` (measuring something closer to recall) and Recall counts
false negatives from ``label != 1`` samples; both also misread
``labels[0]`` as the sample count. This module implements the textbook
definitions instead (precision conditions on predicted positives,
recall on actual positives); the in-graph `precision_recall` op is
audited against its reference kernel, which is correct."""

import numpy as np

__all__ = ["DetectionMAP",
           "MetricBase", "CompositeMetric", "Precision", "Recall", "Accuracy",
           "ChunkEvaluator", "EditDistance", "Auc"]


class MetricBase:
    def __init__(self, name=None):
        self._name = str(name) if name is not None else self.__class__.__name__

    def reset(self):
        for attr in self.__dict__:
            if not attr.startswith("_"):
                v = self.__dict__[attr]
                if isinstance(v, int):
                    setattr(self, attr, 0)
                elif isinstance(v, float):
                    setattr(self, attr, 0.0)

    def update(self, preds, labels):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += value * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no updates")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype("int32").flatten()
        labels = np.asarray(labels).astype("int32").flatten()
        for p, l in zip(preds, labels):
            if p == 1:
                if p == l:
                    self.tp += 1
                else:
                    self.fp += 1

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap != 0 else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype("int32").flatten()
        labels = np.asarray(labels).astype("int32").flatten()
        for p, l in zip(preds, labels):
            if l == 1:
                if p == l:
                    self.tp += 1
                else:
                    self.fn += 1

    def eval(self):
        recall = self.tp + self.fn
        return float(self.tp) / recall if recall != 0 else 0.0


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        seq_num = int(np.asarray(seq_num))
        self.seq_num += seq_num
        self.instance_error += int(np.sum(np.asarray(distances) > 0))
        self.total_distance += float(np.sum(np.asarray(distances)))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance: no updates")
        return self.total_distance / self.seq_num, \
            float(self.instance_error) / self.seq_num


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1)
        self._stat_neg = np.zeros(num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).flatten()
        for i, lbl in enumerate(labels):
            value = preds[i, 1] if preds.ndim == 2 else preds[i]
            bin_idx = int(value * self._num_thresholds)
            bin_idx = min(max(bin_idx, 0), self._num_thresholds)
            if lbl:
                self._stat_pos[bin_idx] += 1.0
            else:
                self._stat_neg[bin_idx] += 1.0

    def eval(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for idx in range(self._num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[idx]
            new_neg = tot_neg + self._stat_neg[idx]
            auc += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        return auc / (tot_pos * tot_neg) if tot_pos > 0 and tot_neg > 0 \
            else 0.0


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks))
        self.num_label_chunks += int(np.asarray(num_label_chunks))
        self.num_correct_chunks += int(np.asarray(num_correct_chunks))

    def eval(self):
        precision = float(self.num_correct_chunks) / self.num_infer_chunks \
            if self.num_infer_chunks else 0.0
        recall = float(self.num_correct_chunks) / self.num_label_chunks \
            if self.num_label_chunks else 0.0
        f1 = 2 * precision * recall / (precision + recall) \
            if self.num_correct_chunks else 0.0
        return precision, recall, f1


class DetectionMAP(object):
    """Detection mean average precision evaluator (reference
    fluid/metrics.py DetectionMAP): wires two detection_map layers — the
    per-batch mAP and a streaming one whose accumulator states thread
    across batches — plus reset()."""

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0,
                 overlap_threshold=0.5, evaluate_difficult=True,
                 ap_version="integral"):
        from . import layers
        from .layer_helper import LayerHelper
        from .initializer import Constant
        from . import core

        self.helper = LayerHelper("map_eval")
        gt_label = layers.cast(gt_label, "float32")
        if gt_difficult is not None:
            gt_difficult = layers.cast(gt_difficult, "float32")
            label = layers.concat([gt_label, gt_difficult, gt_box],
                                  axis=1)
        else:
            label = layers.concat([gt_label, gt_box], axis=1)
        label.lod_level = max(getattr(gt_box, "lod_level", 0), 1)

        self.cur_map = layers.detection_map(
            input, label, class_num=class_num,
            background_label=background_label,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult,
            ap_version=ap_version)

        self._state_names = []
        states = [
            self._create_state(core.VarDesc.VarType.INT32,
                               "accum_pos_count", [1, 2]),
            self._create_state("float32", "accum_true_pos", [1, 3]),
            self._create_state("float32", "accum_false_pos", [1, 3]),
        ]
        self.states = states
        self.has_state = self._create_state(
            core.VarDesc.VarType.INT32, "has_state", [1])
        self.helper.set_variable_initializer(self.has_state, Constant(0))

        self.accum_map = layers.detection_map(
            input, label, class_num=class_num,
            background_label=background_label,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult,
            has_state=self.has_state, input_states=states,
            out_states=states, ap_version=ap_version)
        layers.fill_constant(shape=[1], dtype="int32", value=1,
                             out=self.has_state)

    def _create_state(self, dtype, suffix, shape):
        from . import unique_name
        var = self.helper.create_global_variable(
            name=unique_name.generate("map_eval_%s" % suffix),
            dtype=dtype, shape=shape, persistable=True,
            stop_gradient=True)
        self._state_names.append(var.name)
        return var

    def get_map_var(self):
        """(current-batch mAP var, accumulative mAP var)."""
        return self.cur_map, self.accum_map

    def reset(self, executor, reset_program=None):
        """Zero the accumulators (reference metrics.py DetectionMAP
        reset): neutral single-row states — class 0 is background, so a
        (0, ...) row contributes nothing."""
        from . import framework
        from . import layers
        if reset_program is None:
            reset_program = framework.Program()
        with framework.program_guard(reset_program):
            for name, shape, dtype in (
                    (self._state_names[0], [1, 2], "int32"),
                    (self._state_names[1], [1, 3], "float32"),
                    (self._state_names[2], [1, 3], "float32"),
                    (self.has_state.name, [1], "int32")):
                var = reset_program.global_block().create_var(
                    name=name, dtype=dtype, persistable=True)
                layers.fill_constant(shape=shape, dtype=dtype, value=0,
                                     out=var)
        executor.run(reset_program)
