"""Streaming Python-side metric accumulators (reference python/paddle/fluid/metrics.py)."""

import numpy as np

__all__ = ["MetricBase", "CompositeMetric", "Precision", "Recall", "Accuracy",
           "ChunkEvaluator", "EditDistance", "Auc"]


class MetricBase:
    def __init__(self, name=None):
        self._name = str(name) if name is not None else self.__class__.__name__

    def reset(self):
        for attr in self.__dict__:
            if not attr.startswith("_"):
                v = self.__dict__[attr]
                if isinstance(v, int):
                    setattr(self, attr, 0)
                elif isinstance(v, float):
                    setattr(self, attr, 0.0)

    def update(self, preds, labels):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += value * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no updates")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype("int32").flatten()
        labels = np.asarray(labels).astype("int32").flatten()
        for p, l in zip(preds, labels):
            if p == 1:
                if p == l:
                    self.tp += 1
                else:
                    self.fp += 1

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap != 0 else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype("int32").flatten()
        labels = np.asarray(labels).astype("int32").flatten()
        for p, l in zip(preds, labels):
            if l == 1:
                if p == l:
                    self.tp += 1
                else:
                    self.fn += 1

    def eval(self):
        recall = self.tp + self.fn
        return float(self.tp) / recall if recall != 0 else 0.0


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        seq_num = int(np.asarray(seq_num))
        self.seq_num += seq_num
        self.instance_error += int(np.sum(np.asarray(distances) > 0))
        self.total_distance += float(np.sum(np.asarray(distances)))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance: no updates")
        return self.total_distance / self.seq_num, \
            float(self.instance_error) / self.seq_num


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1)
        self._stat_neg = np.zeros(num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).flatten()
        for i, lbl in enumerate(labels):
            value = preds[i, 1] if preds.ndim == 2 else preds[i]
            bin_idx = int(value * self._num_thresholds)
            bin_idx = min(max(bin_idx, 0), self._num_thresholds)
            if lbl:
                self._stat_pos[bin_idx] += 1.0
            else:
                self._stat_neg[bin_idx] += 1.0

    def eval(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for idx in range(self._num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[idx]
            new_neg = tot_neg + self._stat_neg[idx]
            auc += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        return auc / (tot_pos * tot_neg) if tot_pos > 0 and tot_neg > 0 \
            else 0.0


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks))
        self.num_label_chunks += int(np.asarray(num_label_chunks))
        self.num_correct_chunks += int(np.asarray(num_correct_chunks))

    def eval(self):
        precision = float(self.num_correct_chunks) / self.num_infer_chunks \
            if self.num_infer_chunks else 0.0
        recall = float(self.num_correct_chunks) / self.num_label_chunks \
            if self.num_label_chunks else 0.0
        f1 = 2 * precision * recall / (precision + recall) \
            if self.num_correct_chunks else 0.0
        return precision, recall, f1
