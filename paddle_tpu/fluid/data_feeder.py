"""DataFeeder (reference python/paddle/fluid/data_feeder.py:83): converts
lists/tuples of numpy samples into feed dicts, with multi-device split."""

import numpy as np

from .framework import Variable
from . import core
from .lod import LoDTensor

__all__ = ["DataFeeder"]


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        for each_var in feed_list:
            if isinstance(each_var, str):
                from .framework import default_main_program
                each_var = (program or default_main_program()
                            ).global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("feed_list should be a list of Variable")
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(each_var.shape)
            self.feed_dtypes.append(core.convert_dtype_to_np(each_var.dtype))
        self.place = place

    def feed(self, iterable):
        """iterable: list of samples; each sample is a tuple matching
        feed_list order. Returns {name: ndarray-or-LoDTensor}."""
        columns = list(zip(*iterable))
        ret = {}
        for name, dtype, shape, lod_level, col in zip(
                self.feed_names, self.feed_dtypes, self.feed_shapes,
                self.feed_lod_level, columns):
            if lod_level == 0:
                arr = np.asarray(col, dtype=dtype)
                if len(shape) and shape[-1] == 1 and arr.ndim == 1:
                    arr = arr.reshape(-1, 1)
                # restore static trailing dims when the flat sample size
                # matches (e.g. dense_vector fed to a [C,H,W] image layer —
                # the reference reshapes in the C++ feed path)
                static = [d for d in shape[1:]]
                if (static and all(isinstance(d, int) and d > 0
                                   for d in static)
                        and arr.ndim >= 1
                        and tuple(arr.shape[1:]) != tuple(static)
                        and int(np.prod(arr.shape[1:])) ==
                        int(np.prod(static))):
                    arr = arr.reshape((-1,) + tuple(static))
                ret[name] = arr
            elif lod_level == 2:
                # nested: each sample is a list of inner sequences
                from .lod import nested_samples_to_lod_tensor
                ret[name] = nested_samples_to_lod_tensor(col, dtype)
            elif lod_level > 2:
                raise NotImplementedError(
                    "lod_level %d feeds: the runtime carries two LoD "
                    "levels (inner lengths + outer counts)" % lod_level)
            else:
                seq_lens = [len(s) for s in col]
                flat = np.concatenate(
                    [np.asarray(s, dtype=dtype).reshape(len(s), -1)
                     for s in col], axis=0)
                if len(shape) and shape[-1] == 1 and flat.shape[-1] == 1:
                    pass
                ret[name] = LoDTensor(flat)
                ret[name].set_recursive_sequence_lengths([seq_lens])
        return ret

    def _get_number_of_places_(self, num_places):
        if num_places is not None:
            return int(num_places)
        import os
        if "CPU_NUM" in os.environ:
            return int(os.environ["CPU_NUM"])
        import jax
        return jax.local_device_count()

    def decorate_reader(self, reader, multi_devices, num_places=None,
                        drop_last=True):
        """Wrap a batch reader into one yielding ready feed dicts — one
        dict per step, or a list of per-device dicts when multi_devices
        (reference data_feeder.py:251; the multi-device path consumes one
        batch per device per step, matching ParallelExecutor.run's
        per-device feed list)."""

        def __reader_creator__():
            if not multi_devices:
                for item in reader():
                    yield self.feed(item)
            else:
                num = self._get_number_of_places_(num_places)
                item = []
                for batch in reader():
                    item.append(batch)
                    if len(item) == num:
                        yield [self.feed(b) for b in item]
                        item = []
                if not drop_last and item:
                    raise ValueError(
                        "The data batch which cannot fit for devices will "
                        "be dropped is not implementation.")

        return __reader_creator__

    def feed_parallel(self, iterable, num_places=None):
        """split one batch into per-device feeds (reference :83 multi-device
        path); with the mesh-sharded ParallelExecutor a single dict is
        preferred, but the API is kept."""
        full = self.feed(iterable)
        if num_places is None or num_places <= 1:
            return [full]
        out = []
        n = len(iterable)
        per = (n + num_places - 1) // num_places
        for i in range(num_places):
            part = {}
            for k, v in full.items():
                arr = np.asarray(v)
                part[k] = arr[i * per:(i + 1) * per]
            out.append(part)
        return out
