"""Gradient/error clipping (reference python/paddle/fluid/clip.py — value/norm/
global_norm :212 clipping appended as ops before the optimizer update)."""

from .framework import Variable, default_main_program
from .layer_helper import LayerHelper
from . import layers

__all__ = ["ErrorClipByValue", "GradientClipByValue", "GradientClipByNorm",
           "GradientClipByGlobalNorm", "set_gradient_clip",
           "append_gradient_clip_ops"]


class BaseErrorClipAttr:
    def _append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def _append_clip_op(self, block, grad_name):
        block.append_op(type="clip", inputs={"X": grad_name},
                        outputs={"Out": grad_name},
                        attrs={"min": self.min, "max": self.max},
                        infer_shape=False)

    def _insert_clip_op(self, block, idx, grad_name):
        block._insert_op(idx, type="clip", inputs={"X": [grad_name]},
                         outputs={"Out": [grad_name]},
                         attrs={"min": self.min, "max": self.max})


class BaseGradientClipAttr:
    def _process_context(self, context, param, grad):
        raise NotImplementedError

    def _create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        new_grad = layers.clip(x=grad, min=self.min, max=self.max)
        return param, new_grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        new_grad = layers.clip_by_norm(x=grad, max_norm=self.clip_norm)
        return param, new_grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """reference clip.py:212 — scale all grads by
    clip_norm / max(global_norm, clip_norm)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + "_clip_value"] = self.clip_norm
        else:
            if context[self.group_name + "_clip_value"] != self.clip_norm:
                raise ValueError("all parameters in a group should share the "
                                 "same clip norm")
        sq = layers.squared_l2_norm_layer(grad) if hasattr(
            layers, "squared_l2_norm_layer") else _squared_l2_norm(grad)
        context[self.group_name].append(sq)
        self.context = context

    def _create_operators(self, param, grad):
        group_scale_name = self.group_name + "_scale"
        if group_scale_name not in self.context:
            group_norm = layers.sums(input=self.context[self.group_name])
            group_norm = layers.sqrt(x=group_norm)
            clip_var = layers.fill_constant(shape=[1], dtype="float32",
                                            value=self.clip_norm)
            group_scale = layers.elementwise_div(
                x=clip_var,
                y=layers.elementwise_max(x=clip_var, y=group_norm))
            self.context[group_scale_name] = group_scale
        new_grad = layers.elementwise_mul(
            x=grad, y=self.context[group_scale_name])
        return param, new_grad


def _squared_l2_norm(grad):
    helper = LayerHelper("squared_l2_norm")
    out = helper.create_variable_for_type_inference(grad.dtype)
    helper.append_op(type="squared_l2_norm", inputs={"X": grad},
                     outputs={"Out": out}, infer_shape=False)
    return out


_clip_attr_holder = {}


def set_gradient_clip(clip, param_list=None, program=None):
    if program is None:
        program = default_main_program()
    if param_list is None:
        param_list = program.global_block().all_parameters()
    param_list = [p.name if isinstance(p, Variable) else p
                  for p in param_list]
    for name in param_list:
        p = program.global_block().var(name)
        p.gradient_clip_attr = clip


def error_clip_callback(block, context):
    """Clip gradients of vars that declare `error_clip` (reference
    clip.py error_clip_callback, invoked from append_backward). The clip
    op is INSERTED right after each producing op so downstream grad
    consumers — which execute in block order — see the clipped value."""
    i = 0
    while i < len(block.ops):
        op = block.ops[i]
        inserted = 0
        for grad_n in op.output_arg_names:
            if not grad_n.endswith("@GRAD"):
                continue
            fwd_var = block._find_var_recursive(grad_n[:-5])
            if fwd_var is None:
                continue
            error_clip = getattr(fwd_var, "error_clip", None)
            if error_clip is not None:
                error_clip._insert_clip_op(block, i + 1 + inserted,
                                           grad_n)
                inserted += 1
        i += 1 + inserted    # skip the clip ops we just inserted


def append_gradient_clip_ops(param_grads):
    context = {}
    any_clip = False
    for p, g in param_grads:
        if g is None:
            continue
        clip_attr = getattr(p, "gradient_clip_attr", None)
        if clip_attr is None:
            clip_attr = NullGradientClipAttr()
        if not isinstance(clip_attr, NullGradientClipAttr):
            any_clip = True
        clip_attr._process_context(context, p, g)
    if not any_clip:
        return param_grads
    clipped = []
    for p, g in param_grads:
        if g is None:
            clipped.append((p, g))
            continue
        clip_attr = getattr(p, "gradient_clip_attr", None)
        if clip_attr is None or isinstance(clip_attr, NullGradientClipAttr):
            clipped.append((p, g))
        else:
            clipped.append(clip_attr._create_operators(p, g))
    return clipped
