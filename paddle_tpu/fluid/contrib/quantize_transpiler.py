"""Quantization-aware-training transpiler.

Reference analogue: python/paddle/fluid/contrib/quantize/quantize_transpiler.py
— rewrites a training program so every quantizable op (conv2d,
depthwise_conv2d, mul) sees fake-quantized weights and activations, and
freezes a trained program into a simulated-int8 inference program.

TPU note: the fake_quantize_dequantize lowering uses a straight-through
estimator, so the rewritten program trains with ordinary float gradients
while forward activations/weights see 8-bit rounding — identical in spirit
to the reference's paired quant/dequant ops, collapsed into one op that XLA
fuses into the surrounding matmul.
"""

from ..framework import Program

__all__ = ["QuantizeTranspiler"]

_QUANTIZABLE = ("conv2d", "depthwise_conv2d", "mul")
_QUANT_SLOTS = {"conv2d": ("Input", "Filter"),
                "depthwise_conv2d": ("Input", "Filter"),
                "mul": ("X", "Y")}


class QuantizeTranspiler:
    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", window_size=10000):
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.window_size = window_size

    # -- training rewrite -------------------------------------------------
    def training_transpile(self, program=None, startup_program=None):
        """Insert fake_quantize_dequantize before every quantizable op
        input (reference quantize_transpiler.py training_transpile)."""
        from ..framework import default_main_program
        program = program if program is not None else default_main_program()
        block = program.global_block()
        quantized = {}   # original var name -> quantized var name

        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type in _QUANTIZABLE:
                for slot in _QUANT_SLOTS[op.type]:
                    names = op.inputs.get(slot, [])
                    for j, name in enumerate(names):
                        v = block._find_var_recursive(name)
                        if v is None or v.dtype is None:
                            continue
                        qname = quantized.get(name)
                        if qname is None:
                            qname = name + ".quantized.dequantized"
                            qv = block.create_var(
                                name=qname, dtype=v.dtype, shape=v.shape)
                            sv = block.create_var(
                                name=name + ".quant_scale", dtype=v.dtype,
                                shape=[1])
                            bits = self.weight_bits if slot in (
                                "Filter", "Y") else self.activation_bits
                            block._insert_op(
                                i, type="fake_quantize_dequantize_abs_max",
                                inputs={"X": name},
                                outputs={"Out": qv, "OutScale": sv},
                                attrs={"bit_length": bits})
                            quantized[name] = qname
                            i += 1
                        op.inputs[slot][j] = qname
            i += 1
        program._bump_version()
        return program

    # -- inference freeze --------------------------------------------------
    def freeze_program(self, program, place=None, fuse_bn=False):
        """Freeze a QAT program for inference: quant-dequant stays in the
        graph (simulated int8), scales computed from the trained weights at
        run time; the reference converts to int8 kernels, which on TPU is
        XLA's job (int8 matmul lowering)."""
        program._bump_version()
        return program

    def convert_to_int8(self, program, place=None):
        return program
