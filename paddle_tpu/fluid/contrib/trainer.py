"""High-level Trainer with events + checkpointing.

Reference analogue: python/paddle/fluid/contrib/trainer.py — Trainer (:169),
train loop with events (BeginEpochEvent/EndEpochEvent/BeginStepEvent/
EndStepEvent :40-:94), CheckpointConfig auto-save/resume (:100), Tester, and
env-driven distributed transpile (:324).

Async training pipeline (PIPELINE.md): with
``FLAGS.async_dispatch_depth > 0`` the train loop keeps up to N steps in
flight as FetchFutures (Executor.run(as_future=True)) and drains loss
bookkeeping, the sentinel's NaN/Inf screen and EndStepEvent callbacks
from the pipeline tail — host sync happens once per drain (one batched
jax.device_get), not once per step.  ``FLAGS.reader_prefetch_depth > 0``
additionally stages the NEXT batch on device from a background thread
(reader.prefetch_to_device) while the current step computes.  The async
trajectory is bit-exact vs the sync loop on finite runs: the feeds, the
dispatch order, and the executor's RNG step folds are identical — only
WHEN the host looks at the results changes.
"""

import collections
import os

import numpy as np

from .. import core
from ..framework import Program, default_main_program, default_startup_program
from ..executor import Executor, global_scope
from .. import io as fluid_io

__all__ = ["BeginEpochEvent", "EndEpochEvent", "BeginStepEvent",
           "EndStepEvent", "CheckpointConfig", "Trainer"]


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """reference contrib/trainer.py:100.  `max_num_checkpoints` drives
    the vault's keep-N rotation (fluid/checkpoint.py); `async_save`
    commits checkpoints on the background saver thread so the train loop
    doesn't stall on IO (Trainer joins pending saves at train() exit)."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10, async_save=False):
        self.checkpoint_dir = checkpoint_dir or os.path.join(
            ".", "checkpoints")
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(int(epoch_interval), 1)
        self.step_interval = max(int(step_interval), 1)
        self.async_save = bool(async_save)
        self.epoch_id = 0
        self.step_id = 0
        self.epoch_step = 0
        self.load_serial = None


class _PendingStep:
    """One dispatched-but-undrained step of the async pipeline: the
    FetchFuture plus everything the drain needs — the (epoch, step) ids
    for the deferred EndStepEvent, the feed/fetch lists so a sentinel
    recovery can re-dispatch this batch from a restored state, and the
    pre/post persistable ref snapshots (immutable jax arrays: snapshots
    are free) that make the skip/rollback machinery depth-aware."""

    __slots__ = ("epoch", "step", "feed", "fetch", "future", "pre", "post")

    def __init__(self, epoch, step, feed, fetch, future, pre, post):
        self.epoch = epoch
        self.step = step
        self.feed = feed
        self.fetch = fetch
        self.future = future
        self.pre = pre
        self.post = post


class Trainer:
    """reference contrib/trainer.py:169. `train_func` builds the loss (and
    optionally extra metrics) in the current program; `optimizer_func`
    returns an optimizer."""

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        self.checkpoint_cfg = checkpoint_config
        self.place = place if place is not None else core.TPUPlace(0)
        self.parallel = parallel
        self.train_program = Program()
        self.startup_program = Program()
        from ..framework import program_guard
        from .. import unique_name
        with program_guard(self.train_program, self.startup_program), \
                unique_name.guard():
            ret = train_func()
            if isinstance(ret, (list, tuple)):
                self.train_outputs = list(ret)
            else:
                self.train_outputs = [ret]
            loss = self.train_outputs[0]
            optimizer = optimizer_func()
            optimizer.minimize(loss)
        self.loss = loss
        self.exe = Executor(self.place)
        self.exe.run(self.startup_program)
        if param_path:
            fluid_io.load_persistables(self.exe, param_path,
                                       main_program=self.train_program)
        if self.checkpoint_cfg and os.path.isdir(
                self.checkpoint_cfg.checkpoint_dir):
            try:
                self._restore_checkpoint()
            except FileNotFoundError:
                pass  # empty dir: fresh run; corruption still raises
        self._stop = False

    def _restore_checkpoint(self):
        """Load the last-good checkpoint and adopt its canonical
        {"epoch", "step"} meta (+ optional "epoch_step" for exact
        mid-epoch resume).  load_checkpoint always returns that schema —
        the legacy int-step metas are normalized on the way out, so both
        sides of the round-trip speak one format."""
        meta = fluid_io.load_checkpoint(
            self.exe, self.checkpoint_cfg.checkpoint_dir,
            main_program=self.train_program)
        self.checkpoint_cfg.epoch_id = int(meta.get("epoch", 0))
        self.checkpoint_cfg.step_id = int(meta.get("step", 0))
        self.checkpoint_cfg.epoch_step = int(meta.get("epoch_step", 0))
        return meta

    def stop(self):
        self._stop = True

    def _make_sentinel(self, pipeline_depth=0):
        from ...flags import FLAGS
        if not FLAGS.sentinel_nan_check:
            return None
        from .. import sentinel as sentinel_mod
        return sentinel_mod.AnomalySentinel(
            max_bad_steps=FLAGS.sentinel_max_bad_steps,
            policy=FLAGS.sentinel_policy,
            check_params=FLAGS.sentinel_check_params,
            pipeline_depth=pipeline_depth)

    def _run_step(self, feed, fetch, sentinel, step_id=None):
        """One executor step, optionally screened by the anomaly
        sentinel: on a non-finite step the pre-step persistable refs are
        restored (jax arrays are immutable, so the snapshot is free) and
        after K consecutive bad steps the policy escalates to a reload
        of the last-good checkpoint (or SentinelError)."""
        from ...obs import tracing as obs_tracing
        if sentinel is None:
            with obs_tracing.trace("train/step", kind="train",
                                   step=step_id):
                return self.exe.run(self.train_program, feed=feed,
                                    fetch_list=fetch)
        from .. import functionalizer, sentinel as sentinel_mod
        scope = global_scope()
        names = functionalizer.persistable_names(self.train_program)
        pre = {n: scope.get(n) for n in names if scope.has(n)}
        with obs_tracing.trace("train/step", kind="train", step=step_id):
            metrics = self.exe.run(self.train_program, feed=feed,
                                   fetch_list=fetch)
        named = list(zip((getattr(f, "name", str(f)) for f in fetch),
                         metrics))
        if sentinel.check_params:
            named += [(n, scope.get(n)) for n in names if scope.has(n)]
        verdict = sentinel.observe(named, step=step_id)
        if verdict == sentinel_mod.SKIP:
            for n, v in pre.items():
                scope.set(n, v)
            self._warn_skip(sentinel, 0)
        elif verdict == sentinel_mod.ROLLBACK:
            self._rollback_last_good(sentinel)
        return metrics

    @staticmethod
    def _warn_skip(sentinel, discarded):
        import warnings
        extra = ""
        if discarded:
            extra = (" (pipeline: %d in-flight step(s) discarded "
                     "un-observed and re-dispatched from the reverted "
                     "state)" % discarded)
        warnings.warn(
            "sentinel: non-finite step (%s) reverted — %d/%d "
            "consecutive%s" % (", ".join(sentinel.last_bad_names),
                               sentinel.consecutive_bad,
                               sentinel.max_bad_steps, extra))

    def _rollback_last_good(self, sentinel):
        import warnings
        from .. import sentinel as sentinel_mod
        if not self.checkpoint_cfg:
            raise sentinel_mod.SentinelError(
                "sentinel policy 'rollback' needs a checkpoint_config "
                "with a last-good checkpoint, and this Trainer has "
                "none")
        try:
            meta = fluid_io.load_checkpoint(
                self.exe, self.checkpoint_cfg.checkpoint_dir,
                main_program=self.train_program)
        except FileNotFoundError:
            raise sentinel_mod.SentinelError(
                "sentinel: rollback requested but no checkpoint "
                "exists yet under %s"
                % self.checkpoint_cfg.checkpoint_dir)
        sentinel.note_rollback_done()
        warnings.warn(
            "sentinel: %d consecutive non-finite steps — rolled back "
            "to last-good checkpoint (epoch %s, step %s)"
            % (sentinel.consecutive_bad, meta.get("epoch"),
               meta.get("step")))
        return meta

    # ---- async pipeline: in-flight dispatch + deferred drain --------

    def _dispatch_step(self, epoch_id, step_id, feed, fetch, sentinel):
        """Dispatch one step WITHOUT host sync (Executor.run as_future)
        and record what its eventual drain needs.  The pre/post scope
        snapshots bracket this step's persistable refs: `pre` is the
        restore target if THIS step turns out non-finite, `post` is the
        state the sentinel screens under check_params (at drain time
        the live scope already holds later in-flight steps' state, so
        screening it would attribute a later step's corruption here)."""
        from ...obs import tracing as obs_tracing
        scope = global_scope()
        pre = post = None
        names = None
        if sentinel is not None:
            from .. import functionalizer
            names = functionalizer.persistable_names(self.train_program)
            pre = {n: scope.get(n) for n in names if scope.has(n)}
        with obs_tracing.trace("train/dispatch", kind="train",
                               step=step_id):
            future = self.exe.run(self.train_program, feed=feed,
                                  fetch_list=fetch, as_future=True)
        if sentinel is not None:
            post = {n: scope.get(n) for n in names if scope.has(n)}
        return _PendingStep(epoch_id, step_id, feed, fetch, future,
                            pre, post)

    def _discard_and_redispatch(self, pending, sentinel):
        """Depth-aware recovery: every in-flight step was dispatched
        from state downstream of the step just reverted/rolled back, so
        its results must never be observed.  Drop them un-resolved and
        re-dispatch the SAME batches (same feeds, original event ids)
        from the restored state — no data is lost to a bad step; only
        the RNG step folds of the replayed steps differ, exactly as the
        sync loop's post-anomaly trajectory would differ anyway."""
        dropped = list(pending)
        pending.clear()
        if dropped:
            sentinel.note_inflight_discarded(len(dropped))
        for d in dropped:
            pending.append(self._dispatch_step(
                d.epoch, d.step, d.feed, d.fetch, sentinel))
        return len(dropped)

    def _drain_step(self, pending, sentinel):
        """Resolve the OLDEST in-flight step (ONE batched host sync via
        FetchFuture.result — the watchdog wraps this drain, scaled by
        how many steps the resolve may be waiting behind) and run the
        sentinel screen that dispatch deferred."""
        from .. import sentinel as sentinel_mod
        ent = pending.popleft()
        metrics = ent.future.result(watchdog_scale=len(pending) + 2,
                                    step=ent.step)
        if sentinel is None:
            return ent, metrics
        scope = global_scope()
        named = list(zip((getattr(f, "name", str(f)) for f in ent.fetch),
                         metrics))
        if sentinel.check_params:
            named += sorted(ent.post.items())
        verdict = sentinel.observe(named, step=ent.step)
        if verdict == sentinel_mod.SKIP:
            for n, v in ent.pre.items():
                scope.set(n, v)
            discarded = self._discard_and_redispatch(pending, sentinel)
            self._warn_skip(sentinel, discarded)
        elif verdict == sentinel_mod.ROLLBACK:
            self._rollback_last_good(sentinel)
            self._discard_and_redispatch(pending, sentinel)
        return ent, metrics

    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        from ...flags import FLAGS
        from ..data_feeder import DataFeeder
        feeder = DataFeeder(feed_list=[
            self.train_program.global_block().var(n) for n in feed_order],
            place=self.place, program=self.train_program) \
            if feed_order else None
        cfg = self.checkpoint_cfg
        start_epoch = cfg.epoch_id if cfg else 0
        global_step = cfg.step_id if cfg else 0
        # exact mid-epoch resume: the checkpoint records how many steps
        # of its epoch were already trained; replaying the (deterministic)
        # reader and skipping them reproduces the uninterrupted trajectory
        resume_skip = cfg.epoch_step if cfg else 0
        depth = max(int(FLAGS.async_dispatch_depth), 0)
        if FLAGS.check_nan_inf or FLAGS.benchmark:
            # both modes force a per-step host sync by definition — the
            # pipeline would only defer what they exist to observe
            depth = 0
        sentinel = self._make_sentinel(pipeline_depth=depth)
        feed_fn = feeder.feed if feeder else (lambda d: d)
        prefetch = max(int(FLAGS.reader_prefetch_depth), 0)
        if prefetch > 0 and reader is not None:
            # device prefetch queue: prepare_feeds (dtype casts, LoD
            # padding, async device_put) for the NEXT batch runs on the
            # prefetch thread while the current step computes; items
            # arrive device-staged, so the per-step feed path below is
            # a pass-through
            from ...reader import prefetch_to_device
            from ..executor import prepare_feeds
            prog, make_feed = self.train_program, feed_fn
            reader = prefetch_to_device(
                reader, prefetch,
                prepare=lambda d: prepare_feeds(prog, make_feed(d)))
            feed_fn = lambda d: d  # noqa: E731
        pending = collections.deque()

        def drain_one():
            nonlocal global_step
            ent, metrics = self._drain_step(pending, sentinel)
            event_handler(EndStepEvent(ent.epoch, ent.step, metrics))
            global_step += 1
            return ent

        def drain_and_maybe_checkpoint():
            ent = drain_one()
            if cfg and global_step % cfg.step_interval == 0:
                # a checkpoint is a sync boundary: flush the window so
                # the scope state matches the step ids the vault
                # records (saves coalesce when step_interval < depth)
                while pending:
                    ent = drain_one()
                self._save_checkpoint(ent.epoch, global_step,
                                      ent.step + 1)

        try:
            for epoch_id in range(start_epoch, num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                batches = reader()
                try:
                    for step_id, data in enumerate(batches):
                        if epoch_id == start_epoch and \
                                step_id < resume_skip:
                            continue
                        if self._stop:
                            # stop() lands within <= depth steps: the
                            # in-flight window still drains (its events
                            # fire; state already includes those steps)
                            while pending:
                                drain_one()
                            return
                        begin = BeginStepEvent(epoch_id, step_id)
                        event_handler(begin)
                        fetch = self.train_outputs if begin.fetch_metrics \
                            else []
                        feed = feed_fn(data)
                        if depth == 0:
                            metrics = self._run_step(feed, fetch, sentinel,
                                                     step_id=step_id)
                            event_handler(EndStepEvent(epoch_id, step_id,
                                                       metrics))
                            global_step += 1
                            if cfg and global_step % cfg.step_interval == 0:
                                self._save_checkpoint(epoch_id, global_step,
                                                      step_id + 1)
                        else:
                            pending.append(self._dispatch_step(
                                epoch_id, step_id, feed, fetch, sentinel))
                            while len(pending) > depth:
                                drain_and_maybe_checkpoint()
                finally:
                    # explicit close, not GC: the prefetch worker (and
                    # any generator-held resource) must die with the
                    # epoch even when the loop exits early
                    close = getattr(batches, "close", None)
                    if close is not None:
                        close()
                while pending:
                    drain_and_maybe_checkpoint()
                event_handler(EndEpochEvent(epoch_id))
                if cfg and (epoch_id + 1) % cfg.epoch_interval == 0:
                    self._save_checkpoint(epoch_id + 1, global_step, 0)
        finally:
            if cfg and cfg.async_save:
                from .. import checkpoint as _ckpt
                _ckpt.wait_for_async_saves()

    def test(self, reader, feed_order):
        test_program = self.train_program.clone(for_test=True)
        from ...flags import FLAGS
        from ..data_feeder import DataFeeder
        feeder = DataFeeder(feed_list=[
            test_program.global_block().var(n) for n in feed_order],
            place=self.place, program=test_program)
        # deferred-drain eval: dispatch up to async_dispatch_depth
        # batches before resolving; each drain converts the step's
        # fetches with ONE batched device_get (FetchFuture.result), not
        # a per-item float64 asarray loop per step
        depth = max(int(FLAGS.async_dispatch_depth), 0)
        pending = collections.deque()
        accum, count = None, 0

        def drain():
            nonlocal accum, count
            fut = pending.popleft()
            res = fut.result(watchdog_scale=len(pending) + 2)
            vals = [np.asarray(r).astype(np.float64) for r in res]
            accum = vals if accum is None else [
                a + v for a, v in zip(accum, vals)]
            count += 1

        for data in reader():
            pending.append(self.exe.run(
                test_program, feed=feeder.feed(data),
                fetch_list=self.train_outputs, as_future=True))
            while len(pending) > depth:
                drain()
        while pending:
            drain()
        return [a / max(count, 1) for a in accum] if accum else []

    def save_params(self, param_path):
        fluid_io.save_persistables(self.exe, param_path,
                                   main_program=self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        fluid_io.save_inference_model(
            param_path, feeded_var_names,
            [self.train_outputs[i] for i in target_var_indexes],
            self.exe, main_program=self.train_program)

    def _save_checkpoint(self, epoch_id, step_id, epoch_step=0):
        cfg = self.checkpoint_cfg
        from ...obs import tracing as obs_tracing
        # the ckpt ms of the per-step breakdown: what the train loop
        # actually pays at the sync boundary (async_save hides the
        # commit itself; the vault emits its own committed event)
        with obs_tracing.trace("train/ckpt", kind="train", step=step_id,
                               epoch=epoch_id):
            fluid_io.save_checkpoint(
                self.exe, cfg.checkpoint_dir,
                main_program=self.train_program,
                step=step_id, epoch=epoch_id, epoch_step=epoch_step,
                max_num_checkpoints=cfg.max_num_checkpoints,
                async_save=cfg.async_save)
