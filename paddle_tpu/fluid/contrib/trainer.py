"""High-level Trainer with events + checkpointing.

Reference analogue: python/paddle/fluid/contrib/trainer.py — Trainer (:169),
train loop with events (BeginEpochEvent/EndEpochEvent/BeginStepEvent/
EndStepEvent :40-:94), CheckpointConfig auto-save/resume (:100), Tester, and
env-driven distributed transpile (:324).
"""

import os

import numpy as np

from .. import core
from ..framework import Program, default_main_program, default_startup_program
from ..executor import Executor, global_scope
from .. import io as fluid_io

__all__ = ["BeginEpochEvent", "EndEpochEvent", "BeginStepEvent",
           "EndStepEvent", "CheckpointConfig", "Trainer"]


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """reference contrib/trainer.py:100."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10):
        self.checkpoint_dir = checkpoint_dir or os.path.join(
            ".", "checkpoints")
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(int(epoch_interval), 1)
        self.step_interval = max(int(step_interval), 1)
        self.epoch_id = 0
        self.step_id = 0
        self.load_serial = None


class Trainer:
    """reference contrib/trainer.py:169. `train_func` builds the loss (and
    optionally extra metrics) in the current program; `optimizer_func`
    returns an optimizer."""

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        self.checkpoint_cfg = checkpoint_config
        self.place = place if place is not None else core.TPUPlace(0)
        self.parallel = parallel
        self.train_program = Program()
        self.startup_program = Program()
        from ..framework import program_guard
        from .. import unique_name
        with program_guard(self.train_program, self.startup_program), \
                unique_name.guard():
            ret = train_func()
            if isinstance(ret, (list, tuple)):
                self.train_outputs = list(ret)
            else:
                self.train_outputs = [ret]
            loss = self.train_outputs[0]
            optimizer = optimizer_func()
            optimizer.minimize(loss)
        self.loss = loss
        self.exe = Executor(self.place)
        self.exe.run(self.startup_program)
        if param_path:
            fluid_io.load_persistables(self.exe, param_path,
                                       main_program=self.train_program)
        if self.checkpoint_cfg and os.path.isdir(
                self.checkpoint_cfg.checkpoint_dir):
            try:
                meta = fluid_io.load_checkpoint(
                    self.exe, self.checkpoint_cfg.checkpoint_dir,
                    main_program=self.train_program)
                if meta:
                    self.checkpoint_cfg.epoch_id = int(
                        meta.get("epoch", 0))
                    self.checkpoint_cfg.step_id = int(meta.get("step", 0))
            except FileNotFoundError:
                pass
        self._stop = False

    def stop(self):
        self._stop = True

    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        from ..data_feeder import DataFeeder
        feeder = DataFeeder(feed_list=[
            self.train_program.global_block().var(n) for n in feed_order],
            place=self.place, program=self.train_program) \
            if feed_order else None
        start_epoch = (self.checkpoint_cfg.epoch_id
                       if self.checkpoint_cfg else 0)
        global_step = (self.checkpoint_cfg.step_id
                       if self.checkpoint_cfg else 0)
        for epoch_id in range(start_epoch, num_epochs):
            event_handler(BeginEpochEvent(epoch_id))
            for step_id, data in enumerate(reader()):
                if self._stop:
                    return
                begin = BeginStepEvent(epoch_id, step_id)
                event_handler(begin)
                fetch = self.train_outputs if begin.fetch_metrics else []
                feed = feeder.feed(data) if feeder else data
                metrics = self.exe.run(self.train_program, feed=feed,
                                       fetch_list=fetch)
                event_handler(EndStepEvent(epoch_id, step_id, metrics))
                global_step += 1
                if self.checkpoint_cfg and \
                        global_step % self.checkpoint_cfg.step_interval == 0:
                    self._save_checkpoint(epoch_id, global_step)
            event_handler(EndEpochEvent(epoch_id))
            if self.checkpoint_cfg and \
                    (epoch_id + 1) % self.checkpoint_cfg.epoch_interval == 0:
                self._save_checkpoint(epoch_id + 1, global_step)

    def test(self, reader, feed_order):
        test_program = self.train_program.clone(for_test=True)
        from ..data_feeder import DataFeeder
        feeder = DataFeeder(feed_list=[
            test_program.global_block().var(n) for n in feed_order],
            place=self.place, program=test_program)
        accum, count = None, 0
        for data in reader():
            res = self.exe.run(test_program, feed=feeder.feed(data),
                               fetch_list=self.train_outputs)
            vals = [np.asarray(r).astype(np.float64) for r in res]
            accum = vals if accum is None else [
                a + v for a, v in zip(accum, vals)]
            count += 1
        return [a / max(count, 1) for a in accum] if accum else []

    def save_params(self, param_path):
        fluid_io.save_persistables(self.exe, param_path,
                                   main_program=self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        fluid_io.save_inference_model(
            param_path, feeded_var_names,
            [self.train_outputs[i] for i in target_var_indexes],
            self.exe, main_program=self.train_program)

    def _save_checkpoint(self, epoch_id, step_id):
        fluid_io.save_checkpoint(
            self.exe, self.checkpoint_cfg.checkpoint_dir,
            main_program=self.train_program,
            step={"epoch": epoch_id, "step": step_id})
