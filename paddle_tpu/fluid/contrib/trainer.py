"""High-level Trainer with events + checkpointing.

Reference analogue: python/paddle/fluid/contrib/trainer.py — Trainer (:169),
train loop with events (BeginEpochEvent/EndEpochEvent/BeginStepEvent/
EndStepEvent :40-:94), CheckpointConfig auto-save/resume (:100), Tester, and
env-driven distributed transpile (:324).
"""

import os

import numpy as np

from .. import core
from ..framework import Program, default_main_program, default_startup_program
from ..executor import Executor, global_scope
from .. import io as fluid_io

__all__ = ["BeginEpochEvent", "EndEpochEvent", "BeginStepEvent",
           "EndStepEvent", "CheckpointConfig", "Trainer"]


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """reference contrib/trainer.py:100.  `max_num_checkpoints` drives
    the vault's keep-N rotation (fluid/checkpoint.py); `async_save`
    commits checkpoints on the background saver thread so the train loop
    doesn't stall on IO (Trainer joins pending saves at train() exit)."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10, async_save=False):
        self.checkpoint_dir = checkpoint_dir or os.path.join(
            ".", "checkpoints")
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(int(epoch_interval), 1)
        self.step_interval = max(int(step_interval), 1)
        self.async_save = bool(async_save)
        self.epoch_id = 0
        self.step_id = 0
        self.epoch_step = 0
        self.load_serial = None


class Trainer:
    """reference contrib/trainer.py:169. `train_func` builds the loss (and
    optionally extra metrics) in the current program; `optimizer_func`
    returns an optimizer."""

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        self.checkpoint_cfg = checkpoint_config
        self.place = place if place is not None else core.TPUPlace(0)
        self.parallel = parallel
        self.train_program = Program()
        self.startup_program = Program()
        from ..framework import program_guard
        from .. import unique_name
        with program_guard(self.train_program, self.startup_program), \
                unique_name.guard():
            ret = train_func()
            if isinstance(ret, (list, tuple)):
                self.train_outputs = list(ret)
            else:
                self.train_outputs = [ret]
            loss = self.train_outputs[0]
            optimizer = optimizer_func()
            optimizer.minimize(loss)
        self.loss = loss
        self.exe = Executor(self.place)
        self.exe.run(self.startup_program)
        if param_path:
            fluid_io.load_persistables(self.exe, param_path,
                                       main_program=self.train_program)
        if self.checkpoint_cfg and os.path.isdir(
                self.checkpoint_cfg.checkpoint_dir):
            try:
                self._restore_checkpoint()
            except FileNotFoundError:
                pass  # empty dir: fresh run; corruption still raises
        self._stop = False

    def _restore_checkpoint(self):
        """Load the last-good checkpoint and adopt its canonical
        {"epoch", "step"} meta (+ optional "epoch_step" for exact
        mid-epoch resume).  load_checkpoint always returns that schema —
        the legacy int-step metas are normalized on the way out, so both
        sides of the round-trip speak one format."""
        meta = fluid_io.load_checkpoint(
            self.exe, self.checkpoint_cfg.checkpoint_dir,
            main_program=self.train_program)
        self.checkpoint_cfg.epoch_id = int(meta.get("epoch", 0))
        self.checkpoint_cfg.step_id = int(meta.get("step", 0))
        self.checkpoint_cfg.epoch_step = int(meta.get("epoch_step", 0))
        return meta

    def stop(self):
        self._stop = True

    def _make_sentinel(self):
        from ...flags import FLAGS
        if not FLAGS.sentinel_nan_check:
            return None
        from .. import sentinel as sentinel_mod
        return sentinel_mod.AnomalySentinel(
            max_bad_steps=FLAGS.sentinel_max_bad_steps,
            policy=FLAGS.sentinel_policy,
            check_params=FLAGS.sentinel_check_params)

    def _run_step(self, feed, fetch, sentinel):
        """One executor step, optionally screened by the anomaly
        sentinel: on a non-finite step the pre-step persistable refs are
        restored (jax arrays are immutable, so the snapshot is free) and
        after K consecutive bad steps the policy escalates to a reload
        of the last-good checkpoint (or SentinelError)."""
        if sentinel is None:
            return self.exe.run(self.train_program, feed=feed,
                                fetch_list=fetch)
        import warnings
        from .. import functionalizer, sentinel as sentinel_mod
        scope = global_scope()
        names = functionalizer.persistable_names(self.train_program)
        pre = {n: scope.get(n) for n in names if scope.has(n)}
        metrics = self.exe.run(self.train_program, feed=feed,
                               fetch_list=fetch)
        named = list(zip((getattr(f, "name", str(f)) for f in fetch),
                         metrics))
        if sentinel.check_params:
            named += [(n, scope.get(n)) for n in names if scope.has(n)]
        verdict = sentinel.observe(named)
        if verdict == sentinel_mod.SKIP:
            for n, v in pre.items():
                scope.set(n, v)
            warnings.warn(
                "sentinel: non-finite step (%s) reverted — %d/%d "
                "consecutive" % (", ".join(sentinel.last_bad_names),
                                 sentinel.consecutive_bad,
                                 sentinel.max_bad_steps))
        elif verdict == sentinel_mod.ROLLBACK:
            if not self.checkpoint_cfg:
                raise sentinel_mod.SentinelError(
                    "sentinel policy 'rollback' needs a checkpoint_config "
                    "with a last-good checkpoint, and this Trainer has "
                    "none")
            try:
                meta = fluid_io.load_checkpoint(
                    self.exe, self.checkpoint_cfg.checkpoint_dir,
                    main_program=self.train_program)
            except FileNotFoundError:
                raise sentinel_mod.SentinelError(
                    "sentinel: rollback requested but no checkpoint "
                    "exists yet under %s"
                    % self.checkpoint_cfg.checkpoint_dir)
            sentinel.note_rollback_done()
            warnings.warn(
                "sentinel: %d consecutive non-finite steps — rolled back "
                "to last-good checkpoint (epoch %s, step %s)"
                % (sentinel.consecutive_bad, meta.get("epoch"),
                   meta.get("step")))
        return metrics

    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        from ..data_feeder import DataFeeder
        feeder = DataFeeder(feed_list=[
            self.train_program.global_block().var(n) for n in feed_order],
            place=self.place, program=self.train_program) \
            if feed_order else None
        cfg = self.checkpoint_cfg
        start_epoch = cfg.epoch_id if cfg else 0
        global_step = cfg.step_id if cfg else 0
        # exact mid-epoch resume: the checkpoint records how many steps
        # of its epoch were already trained; replaying the (deterministic)
        # reader and skipping them reproduces the uninterrupted trajectory
        resume_skip = cfg.epoch_step if cfg else 0
        sentinel = self._make_sentinel()
        try:
            for epoch_id in range(start_epoch, num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                for step_id, data in enumerate(reader()):
                    if epoch_id == start_epoch and step_id < resume_skip:
                        continue
                    if self._stop:
                        return
                    begin = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin)
                    fetch = self.train_outputs if begin.fetch_metrics \
                        else []
                    feed = feeder.feed(data) if feeder else data
                    metrics = self._run_step(feed, fetch, sentinel)
                    event_handler(EndStepEvent(epoch_id, step_id, metrics))
                    global_step += 1
                    if cfg and global_step % cfg.step_interval == 0:
                        self._save_checkpoint(epoch_id, global_step,
                                              step_id + 1)
                event_handler(EndEpochEvent(epoch_id))
                if cfg and (epoch_id + 1) % cfg.epoch_interval == 0:
                    self._save_checkpoint(epoch_id + 1, global_step, 0)
        finally:
            if cfg and cfg.async_save:
                from .. import checkpoint as _ckpt
                _ckpt.wait_for_async_saves()

    def test(self, reader, feed_order):
        test_program = self.train_program.clone(for_test=True)
        from ..data_feeder import DataFeeder
        feeder = DataFeeder(feed_list=[
            test_program.global_block().var(n) for n in feed_order],
            place=self.place, program=test_program)
        accum, count = None, 0
        for data in reader():
            res = self.exe.run(test_program, feed=feeder.feed(data),
                               fetch_list=self.train_outputs)
            vals = [np.asarray(r).astype(np.float64) for r in res]
            accum = vals if accum is None else [
                a + v for a, v in zip(accum, vals)]
            count += 1
        return [a / max(count, 1) for a in accum] if accum else []

    def save_params(self, param_path):
        fluid_io.save_persistables(self.exe, param_path,
                                   main_program=self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        fluid_io.save_inference_model(
            param_path, feeded_var_names,
            [self.train_outputs[i] for i in target_var_indexes],
            self.exe, main_program=self.train_program)

    def _save_checkpoint(self, epoch_id, step_id, epoch_step=0):
        cfg = self.checkpoint_cfg
        fluid_io.save_checkpoint(
            self.exe, cfg.checkpoint_dir,
            main_program=self.train_program,
            step=step_id, epoch=epoch_id, epoch_step=epoch_step,
            max_num_checkpoints=cfg.max_num_checkpoints,
            async_save=cfg.async_save)
