"""StateCell / TrainingDecoder / BeamSearchDecoder (reference
python/paddle/fluid/contrib/decoder/beam_search_decoder.py).

The reference builds these on LoD beams: a While op over LoD-shrinking
TensorArrays with sequence_expand/lod_reset gymnastics per step. The TPU
redesign keeps the user contract — a StateCell whose `state_updater`
defines one decode step, a TrainingDecoder that trains it over ragged
targets, and a BeamSearchDecoder whose `decode()` emits beam-search
generation sharing the cell — but realizes generation as a DENSE
unrolled loop: every source keeps exactly `beam_size` rows, the
beam_search op returns parent pointers, and states reorder with one
`gather` per step (MXU/XLA-friendly static shapes; same design as
models/machine_translation.py generation, which validates the encoding
end to end)."""

import contextlib

from ...layer_helper import LayerHelper
from ... import layers, unique_name

__all__ = ["InitState", "StateCell", "TrainingDecoder",
           "BeamSearchDecoder"]


class _DecoderType:
    TRAINING = 1
    BEAM_SEARCH = 2


class InitState(object):
    """Initial state of a decoder cell (reference
    beam_search_decoder.py:43). Either an explicit batch-sized `init`
    Variable, or a constant `value` whose batch size derives from
    `init_boot`."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                "init_boot must be provided to infer the init batch size")
        else:
            d = (shape[-1] if shape else init_boot.shape[-1])
            self._init = layers.fill_constant_batch_size_like(
                input=init_boot, value=value, shape=[-1, d], dtype=dtype)
        self._need_reorder = need_reorder

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class _MemoryState(object):
    """Training-side state: a DynamicRNN memory (reference :100)."""

    def __init__(self, state_name, rnn_obj, init_state):
        self._state_name = state_name
        self._rnn_obj = rnn_obj
        self._state_mem = self._rnn_obj.memory(init=init_state.value)

    def get_state(self):
        return self._state_mem

    def update_state(self, state):
        self._rnn_obj.update_memory(self._state_mem, state)


class _BeamState(object):
    """Generation-side state: a plain dense var, reordered by parent
    pointers between steps (replaces the reference's _ArrayState)."""

    def __init__(self, state_name, init_value):
        self._state_name = state_name
        self._value = init_value

    def get_state(self):
        return self._value

    def update_state(self, state):
        self._value = state


class StateCell(object):
    """Carrier of decode-step inputs/states (reference :159). Define the
    step with the `state_updater` decorator; both decoders invoke it via
    `compute_state`."""

    def __init__(self, inputs, states, out_state, name=None):
        self._helper = LayerHelper("state_cell", name=name)
        self._cur_states = {}
        self._state_names = []
        for state_name, state in states.items():
            if not isinstance(state, InitState):
                raise ValueError("state must be an InitState object")
            self._cur_states[state_name] = state
            self._state_names.append(state_name)
        self._inputs = dict(inputs)
        self._cur_decoder_obj = None
        self._in_decoder = False
        self._states_holder = {}
        self._switched_decoder = False
        self._state_updater = None
        self._out_state = out_state
        self._cur_inputs = {}

    def _enter_decoder(self, decoder_obj):
        if self._in_decoder or self._cur_decoder_obj is not None:
            raise ValueError("StateCell has already entered a decoder")
        self._in_decoder = True
        self._cur_decoder_obj = decoder_obj
        self._switched_decoder = False

    def _leave_decoder(self, decoder_obj):
        if not self._in_decoder or self._cur_decoder_obj != decoder_obj:
            raise ValueError("not in this decoder")
        self._in_decoder = False
        self._cur_decoder_obj = None
        self._switched_decoder = False

    def _switch_decoder(self):
        """Materialize per-decoder state holders lazily (reference
        :231)."""
        if not self._in_decoder:
            raise ValueError("not in a decoder block")
        if self._switched_decoder:
            raise ValueError("already switched")
        for state_name in self._state_names:
            if state_name not in self._states_holder:
                self._states_holder[state_name] = {}
            init = self._cur_states[state_name]
            if not isinstance(init, InitState):
                raise ValueError("state %s must start as InitState"
                                 % state_name)
            obj = self._cur_decoder_obj
            if obj.type == _DecoderType.TRAINING:
                holder = _MemoryState(state_name, obj.dynamic_rnn, init)
            else:
                holder = _BeamState(
                    state_name, obj._expand_to_beam(
                        init.value, reorder=init.need_reorder))
            self._states_holder[state_name][id(obj)] = holder
            self._cur_states[state_name] = holder.get_state()
        self._switched_decoder = True

    def state_updater(self, updater):
        """Decorator registering the one-step state transition
        (reference :314)."""
        self._state_updater = updater

        def _decorator(state_cell):
            if state_cell == self:
                raise TypeError("updater should only be called by decoders")
            updater(state_cell)

        return _decorator

    def get_state(self, state_name):
        if state_name not in self._cur_states:
            raise ValueError("unknown state %s" % state_name)
        cur = self._cur_states[state_name]
        if isinstance(cur, InitState):
            raise ValueError(
                "state %s read outside a decoder block" % state_name)
        return cur

    def get_input(self, input_name):
        if input_name not in self._cur_inputs:
            raise ValueError("unknown input %s" % input_name)
        return self._cur_inputs[input_name]

    def set_state(self, state_name, state_value):
        self._cur_states[state_name] = state_value

    def compute_state(self, inputs):
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        self._cur_inputs = dict(inputs)
        if self._state_updater is None:
            raise ValueError("no state_updater registered")
        self._state_updater(self)

    def update_states(self):
        """Commit the step's new states back to their holders
        (reference :360)."""
        if not self._in_decoder:
            raise ValueError("update_states outside a decoder")
        obj_id = id(self._cur_decoder_obj)
        for state_name, holders in self._states_holder.items():
            holders[obj_id].update_state(self._cur_states[state_name])

    def out_state(self):
        return self._cur_states[self._out_state]


class TrainingDecoder(object):
    """Train the cell over ragged target sequences (reference :384):
    a thin veneer over DynamicRNN whose memories are the cell states."""

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None):
        self._helper = LayerHelper("training_decoder", name=name)
        self._status = TrainingDecoder.BEFORE_DECODER
        self._dynamic_rnn = layers.DynamicRNN()
        self._type = _DecoderType.TRAINING
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)

    @property
    def state_cell(self):
        self._assert_in_decoder_block("state_cell")
        return self._state_cell

    @property
    def dynamic_rnn(self):
        return self._dynamic_rnn

    @property
    def type(self):
        return self._type

    def block(self):
        """Context manager defining one timestep."""
        import contextlib

        @contextlib.contextmanager
        def _block():
            if self._status != TrainingDecoder.BEFORE_DECODER:
                raise ValueError("decoder.block() can only be invoked once")
            self._status = TrainingDecoder.IN_DECODER
            with self._dynamic_rnn.block():
                yield
            self._status = TrainingDecoder.AFTER_DECODER
            self._state_cell._leave_decoder(self)

        return _block()

    def step_input(self, x):
        self._assert_in_decoder_block("step_input")
        return self._dynamic_rnn.step_input(x)

    def static_input(self, x):
        self._assert_in_decoder_block("static_input")
        return self._dynamic_rnn.static_input(x)

    def output(self, *outputs):
        self._assert_in_decoder_block("output")
        self._dynamic_rnn.output(*outputs)

    def __call__(self, *args, **kwargs):
        if self._status != TrainingDecoder.AFTER_DECODER:
            raise ValueError("call TrainingDecoder after its block")
        return self._dynamic_rnn(*args, **kwargs)

    def _assert_in_decoder_block(self, method):
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError("%s must be invoked inside block()" % method)


class BeamSearchDecoder(object):
    """Generate with beam search from a trained StateCell (reference
    :523). `decode()` builds the whole search; calling the decoder
    afterwards returns (translation_ids, translation_scores) as ragged
    LoD tensors.

    Dense redesign: rows = batch x beam_size throughout, parent pointers
    from the beam_search op reorder states (one gather per step), and
    the per-step selections stack into [T, B*W] tensors consumed by
    beam_search_decode — no TensorArray/While needed under XLA.
    `emb_param_attr` / `score_param_attr` / `score_bias_attr` pin the
    embedding and scoring-fc parameter names for weight sharing with the
    training network."""

    BEFORE_BEAM_SEARCH_DECODER = 0
    IN_BEAM_SEARCH_DECODER = 1
    AFTER_BEAM_SEARCH_DECODER = 2

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50,
                 sparse_emb=True, max_len=100, beam_size=1, end_id=1,
                 name=None, emb_param_attr=None, score_param_attr=None,
                 score_bias_attr=None):
        self._helper = LayerHelper("beam_search_decoder", name=name)
        self._type = _DecoderType.BEAM_SEARCH
        self._status = BeamSearchDecoder.BEFORE_BEAM_SEARCH_DECODER
        self._state_cell = state_cell
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._word_dim = word_dim
        self._input_var_dict = dict(input_var_dict or {})
        self._topk_size = topk_size
        self._sparse_emb = sparse_emb
        self._max_len = max_len
        self._beam_size = beam_size
        self._end_id = end_id
        self._emb_param_attr = emb_param_attr
        self._score_param_attr = score_param_attr
        self._score_bias_attr = score_bias_attr
        self._sentence_ids = None
        self._sentence_scores = None
        # custom-block decode state (block/read_array/update_array)
        self._counter = None
        self._cond = None
        self._zero_idx = None
        self._array_dict = {}
        self._array_link = []
        self._ids_array = None
        self._scores_array = None

    @property
    def type(self):
        return self._type

    @property
    def state_cell(self):
        return self._state_cell

    # -- dense-beam helpers ------------------------------------------------
    def _expand_to_beam(self, var, reorder=False):
        """[B, D] -> [B*W, D] by repeating each source row W times.
        (`reorder` kept for API parity; dense rows never need the
        reference's rank-table reordering.)"""
        W = self._beam_size
        if W == 1:
            return var
        e = layers.unsqueeze(var, axes=[1])                 # [B, 1, D]
        e = layers.expand(e, expand_times=[1, W] +
                          [1] * (len(var.shape) - 1))       # [B, W, ...]
        return layers.reshape(e, shape=[-1] + list(var.shape[1:]))

    def _dup_beam_mask(self, ref):
        """[B*W, 1] additive mask: 0 for slot 0 of each source, -1e9 for
        duplicate start beams (so step 0 expands one beam per source)."""
        W = self._beam_size
        ones = layers.fill_constant_batch_size_like(
            input=ref, shape=[-1, 1], value=1.0, dtype="float32")
        ramp = layers.cumsum(ones, axis=0, exclusive=True)
        slot = layers.elementwise_sub(
            ramp, layers.scale(
                layers.floor(layers.scale(ramp, scale=1.0 / W)),
                scale=float(W)))
        return layers.scale(layers.elementwise_min(slot, ones),
                            scale=-1e9)

    def decode(self):
        """Build the beam search (reference :652). Override for custom
        per-step behavior."""
        if self._status != BeamSearchDecoder.BEFORE_BEAM_SEARCH_DECODER:
            raise ValueError("decode() can only be invoked once")
        self._status = BeamSearchDecoder.IN_BEAM_SEARCH_DECODER
        cell = self._state_cell
        cell._enter_decoder(self)
        W = self._beam_size

        prev_ids = self._expand_to_beam(self._init_ids)
        prev_scores = self._expand_to_beam(self._init_scores)

        # feed vars expand once; reordered by parent pointers per step
        feed_vars = {}
        for name, var in self._input_var_dict.items():
            if name not in cell._inputs:
                raise ValueError(
                    "Variable %s not found in StateCell" % name)
            feed_vars[name] = self._expand_to_beam(var)

        step_ids, step_scores, step_parents = [], [], []
        first = True
        for _t in range(self._max_len):
            emb = layers.embedding(
                prev_ids, size=[self._target_dict_dim, self._word_dim],
                dtype="float32", is_sparse=self._sparse_emb,
                param_attr=self._emb_param_attr)
            emb = layers.reshape(emb, shape=[-1, self._word_dim])
            feed_dict = {}
            for name in cell._inputs:
                feed_dict[name] = feed_vars.get(name, emb)
            cell.compute_state(inputs=feed_dict)
            out = cell.out_state()
            scores = layers.fc(out, size=self._target_dict_dim,
                               act="softmax",
                               param_attr=self._score_param_attr,
                               bias_attr=self._score_bias_attr)
            log_probs = layers.log(scores)
            accu = layers.elementwise_add(log_probs, prev_scores, axis=0)
            if first:
                first = False
                accu = layers.elementwise_add(
                    accu, self._dup_beam_mask(prev_scores), axis=0)
            sel_ids, sel_scores, parent = layers.beam_search(
                prev_ids, prev_scores, None, accu, beam_size=W,
                end_id=self._end_id, return_parent_idx=True)
            step_ids.append(sel_ids)
            step_scores.append(sel_scores)
            step_parents.append(parent)
            prev_ids, prev_scores = sel_ids, sel_scores
            # reorder every state and feed var by the surviving parents
            cell.update_states()
            obj_id = id(self)
            for state_name, holders in cell._states_holder.items():
                h = holders[obj_id]
                h.update_state(layers.gather(h.get_state(), parent))
                cell._cur_states[state_name] = h.get_state()
            for name in list(feed_vars):
                feed_vars[name] = layers.gather(feed_vars[name], parent)

        ids_arr = layers.stack([layers.reshape(i, shape=[-1])
                                for i in step_ids], axis=0)
        scores_arr = layers.stack([layers.reshape(s, shape=[-1])
                                   for s in step_scores], axis=0)
        parents_arr = layers.stack(step_parents, axis=0)
        self._sentence_ids, self._sentence_scores = \
            layers.beam_search_decode(
                ids_arr, scores_arr, beam_size=W, end_id=self._end_id,
                parent_idx=parents_arr)
        self._status = BeamSearchDecoder.AFTER_BEAM_SEARCH_DECODER
        cell._leave_decoder(self)

    # -- custom-block decoding (reference :616-:800) ----------------------
    # decode() above is the canonical DENSE search; block() exposes the
    # reference's build-your-own-step contract: ops recorded inside run
    # once per generation step in a While owned by the decoder, with
    # TensorArrays threading per-step selections. Data-dependent array
    # indices/lengths need concrete values, so the loop runs on the
    # host-interpreted path (force_host — the reference's WhileOp ran a
    # nested Executor per iteration too, while_op.cc:50).

    @contextlib.contextmanager
    def block(self):
        """Define custom per-step decode behavior (reference :616)."""
        if self._status != BeamSearchDecoder.BEFORE_BEAM_SEARCH_DECODER:
            raise ValueError("block() can only be invoked once")
        self._status = BeamSearchDecoder.IN_BEAM_SEARCH_DECODER
        self._state_cell._enter_decoder(self)
        self._counter = layers.zeros(shape=[1], dtype="int64")
        self._counter.stop_gradient = True
        max_len_var = layers.fill_constant([1], "int64", self._max_len)
        self._cond = layers.less_than(self._counter, max_len_var)
        self._zero_idx = layers.fill_constant([1], "int64", 0,
                                              force_cpu=True)
        while_op = layers.While(self._cond, force_host=True)
        with while_op.block():
            yield
            with layers.Switch() as switch:
                with switch.case(self._cond):
                    layers.increment(self._counter, value=1.0,
                                     in_place=True)
                    for value, array in self._array_link:
                        layers.array_write(value, i=self._counter,
                                           array=array)
                    layers.less_than(self._counter, max_len_var,
                                     cond=self._cond)
        self._status = BeamSearchDecoder.AFTER_BEAM_SEARCH_DECODER
        self._state_cell._leave_decoder(self)

    def early_stop(self):
        """Stop generation before max_len — a "break" (reference :646)."""
        self._assert_in_decoder_block("early_stop")
        layers.fill_constant(shape=[1], dtype="bool", value=0,
                             force_cpu=True, out=self._cond)

    def read_array(self, init, is_ids=False, is_scores=False):
        """Read this step's value of a loop-carried array; `init` seeds
        step 0 (reference :731)."""
        self._assert_in_decoder_block("read_array")
        if is_ids and is_scores:
            raise ValueError("an array cannot be both ids and scores")
        parent_block = self._parent_block()
        array = parent_block.create_var(
            name=unique_name.generate("beam_search_decoder_array"),
            dtype=init.dtype)
        parent_block.append_op(
            type="write_to_array",
            inputs={"X": [init], "I": [self._zero_idx]},
            outputs={"Out": [array]}, attrs={}, infer_shape=False)
        if is_ids:
            self._ids_array = array
        elif is_scores:
            self._scores_array = array
        read_value = layers.array_read(array=array, i=self._counter)
        self._array_dict[read_value.name] = array
        return read_value

    def update_array(self, array, value):
        """Store this step's `value` into the array `read_array` returned
        (written at counter+1 as the loop advances; reference :780)."""
        self._assert_in_decoder_block("update_array")
        array = self._array_dict.get(array.name)
        if array is None:
            raise ValueError("invoke read_array before update_array")
        self._array_link.append((value, array))

    def _parent_block(self):
        program = self._helper.main_program
        parent_idx = program.current_block().parent_idx
        if parent_idx < 0:
            raise ValueError("decoder block has no parent block")
        return program.block(parent_idx)

    def _assert_in_decoder_block(self, method):
        if self._status != BeamSearchDecoder.IN_BEAM_SEARCH_DECODER:
            raise ValueError("%s must be invoked inside block()" % method)

    def __call__(self):
        if self._status != BeamSearchDecoder.AFTER_BEAM_SEARCH_DECODER:
            raise ValueError("call BeamSearchDecoder after decode()")
        if self._sentence_ids is None and self._ids_array is None:
            # the symmetric misuse cases both get explicit messages —
            # returning (None, None) here would surface as an unrelated
            # error at the caller's fetch
            raise ValueError(
                "custom decoder block never marked an ids array — "
                "beam_search_decode needs both (mark the ids array with "
                "read_array(..., is_ids=True)%s)"
                % ("" if self._scores_array is None
                   else "; is_scores was marked"))
        if self._sentence_ids is None and self._ids_array is not None:
            if self._scores_array is None:
                raise ValueError(
                    "custom decoder block marked is_ids on a read_array "
                    "but never is_scores — beam_search_decode needs both "
                    "(mark the scores array with read_array(..., "
                    "is_scores=True))")
            # custom-block path: decode straight from the TensorArrays
            # (the op stacks list-valued inputs)
            self._sentence_ids, self._sentence_scores = \
                layers.beam_search_decode(
                    self._ids_array, self._scores_array,
                    beam_size=self._beam_size, end_id=self._end_id)
        return self._sentence_ids, self._sentence_scores
