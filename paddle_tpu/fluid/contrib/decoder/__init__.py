"""fluid.contrib.decoder (reference python/paddle/fluid/contrib/decoder)."""

from .beam_search_decoder import (InitState, StateCell,  # noqa: F401
                                  TrainingDecoder, BeamSearchDecoder)

__all__ = ["InitState", "StateCell", "TrainingDecoder",
           "BeamSearchDecoder"]
