"""High-level Inferencer (reference contrib/inferencer.py:31) — the
companion to contrib.Trainer: rebuilds the inference topology from the
user's infer_func, loads trained parameters from param_path, and serves
`infer(feed_dict)` through the jitted executor."""

import numpy as np

from .. import core
from .. import executor
from .. import framework
from .. import io as fluid_io
from .. import unique_name

__all__ = ["Inferencer"]


class Inferencer(object):
    def __init__(self, infer_func, param_path, place=None,
                 parallel=False):
        self.param_path = param_path
        self.scope = executor.Scope()
        self.inference_program = framework.Program()
        startup = framework.Program()
        with framework.program_guard(self.inference_program, startup):
            # fresh name stream (reference inferencer.py:63): rebuilding
            # the same topology must regenerate the trained param names
            with unique_name.guard():
                self.predict_var = infer_func()
        with self._prog_and_scope_guard():
            self.exe = executor.Executor(place or core.TPUPlace(0))
            self.exe.run(startup)
            fluid_io.load_params(self.exe, param_path,
                                 main_program=self.inference_program)
        self.inference_program = self.inference_program.clone(
            for_test=True)

    def _prog_and_scope_guard(self):
        return executor.scope_guard(self.scope)

    def infer(self, inputs, return_numpy=True):
        """inputs: {feed_name: ndarray} (reference inferencer.py infer)."""
        if not isinstance(inputs, dict):
            raise ValueError(
                "inputs should be a map of {'input_name': input_var}")
        with self._prog_and_scope_guard():
            results = self.exe.run(self.inference_program, feed=inputs,
                                   fetch_list=[self.predict_var],
                                   return_numpy=return_numpy)
        return [np.asarray(r) for r in results] if return_numpy \
            else results
