"""fluid.contrib namespace (reference python/paddle/fluid/contrib/)."""

from .trainer import (Trainer, CheckpointConfig, BeginEpochEvent,
                      EndEpochEvent, BeginStepEvent, EndStepEvent)
from .quantize_transpiler import QuantizeTranspiler
from .memory_usage_calc import memory_usage
from .hdfs_utils import HDFSClient, multi_upload, multi_download
from .inferencer import Inferencer
from .op_frequence import op_freq_statistic
from . import decoder
from .decoder import (InitState, StateCell, TrainingDecoder,
                      BeamSearchDecoder)

__all__ = ["Trainer", "CheckpointConfig", "BeginEpochEvent", "EndEpochEvent",
           "BeginStepEvent", "EndStepEvent", "QuantizeTranspiler",
           "memory_usage", "HDFSClient", "multi_upload", "multi_download",
           "Inferencer", "op_freq_statistic", "decoder", "InitState",
           "StateCell", "TrainingDecoder", "BeamSearchDecoder"]
