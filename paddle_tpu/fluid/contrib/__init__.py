"""fluid.contrib namespace (reference python/paddle/fluid/contrib/)."""

from .trainer import (Trainer, CheckpointConfig, BeginEpochEvent,
                      EndEpochEvent, BeginStepEvent, EndStepEvent)
from .quantize_transpiler import QuantizeTranspiler
from .memory_usage_calc import memory_usage

__all__ = ["Trainer", "CheckpointConfig", "BeginEpochEvent", "EndEpochEvent",
           "BeginStepEvent", "EndStepEvent", "QuantizeTranspiler",
           "memory_usage"]
