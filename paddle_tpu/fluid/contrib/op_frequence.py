"""Op-frequency statistics (reference contrib/op_frequence.py:23
op_freq_statistic): count op types (and adjacent op-pair patterns) over a
Program — the quick profile used to pick fusion-pass targets."""

from collections import Counter, OrderedDict

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program):
    """Returns (uni_op_freq, adj_2_op_freq): per-op-type counts and
    adjacent-pair counts across every block, most-common first."""
    if program is None:
        raise ValueError("program is None")
    uni, adj = Counter(), Counter()
    for block in program.blocks:
        prev = None
        for op in block.ops:
            uni[op.type] += 1
            if prev is not None:
                adj["%s->%s" % (prev, op.type)] += 1
            prev = op.type
    uni_sorted = OrderedDict(uni.most_common())
    adj_sorted = OrderedDict(adj.most_common())
    return uni_sorted, adj_sorted
