"""HDFS client utility (reference
python/paddle/fluid/contrib/utils/hdfs_utils.py HDFSClient).

The reference shells out to `hadoop fs` for upload/download/ls/mkdir of
checkpoints and datasets. This environment has no Hadoop cluster (or
network egress), so the same API is backed by either:

- a real `hadoop` binary when `hadoop_home` points at one, or
- a local-filesystem sandbox (`fs:///...` semantics) otherwise — the
  path layout, return conventions, and multi-file helpers behave the
  same, so training scripts that stage checkpoints through HDFSClient
  run unmodified.
"""

import os
import shutil
import subprocess

__all__ = ["HDFSClient", "multi_upload", "multi_download"]


class HDFSClient(object):
    def __init__(self, hadoop_home=None, configs=None):
        self.hadoop_home = hadoop_home
        self.configs = dict(configs or {})
        self._bin = None
        if hadoop_home:
            cand = os.path.join(hadoop_home, "bin", "hadoop")
            if os.path.exists(cand):
                self._bin = cand
        # local sandbox root used when no hadoop binary exists
        self.local_root = self.configs.get(
            "fs.local.root", "/tmp/paddle_tpu_hdfs")

    # -- path mapping ------------------------------------------------------
    def _local(self, hdfs_path):
        return os.path.join(self.local_root, hdfs_path.lstrip("/"))

    def _run(self, args, retry_times=5):
        import time
        cmd = [self._bin, "fs"] + [
            "-D%s=%s" % kv for kv in self.configs.items()
            if kv[0] != "fs.local.root"] + args
        ret = None
        for i in range(max(1, retry_times)):
            if i:
                time.sleep(0.5 * i)   # backoff between transient retries
            ret = subprocess.run(cmd, capture_output=True, text=True)
            if ret.returncode == 0:
                return True, ret.stdout
        return False, ret.stderr

    # -- API (reference hdfs_utils.py:68-:382) -----------------------------
    def upload(self, hdfs_path, local_path, overwrite=False,
               retry_times=5):
        if self._bin:
            args = ["-put"] + (["-f"] if overwrite else []) + \
                [local_path, hdfs_path]
            return self._run(args, retry_times)[0]
        dst = self._local(hdfs_path)
        if os.path.exists(dst) and not overwrite:
            return False
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if os.path.isdir(local_path):
            if os.path.exists(dst):
                shutil.rmtree(dst)
            shutil.copytree(local_path, dst)
        else:
            shutil.copy2(local_path, dst)
        return True

    def download(self, hdfs_path, local_path, overwrite=False,
                 unzip=False):
        if self._bin:
            if os.path.exists(local_path) and not overwrite:
                return False
            # fetch beside the target and swap only on success — the
            # existing local copy must survive a failed transfer
            tmp = local_path + ".hdfs_dl_tmp"
            if os.path.exists(tmp):
                shutil.rmtree(tmp) if os.path.isdir(tmp) \
                    else os.remove(tmp)
            if not self._run(["-get", hdfs_path, tmp])[0]:
                if os.path.exists(tmp):   # drop the partial transfer
                    shutil.rmtree(tmp) if os.path.isdir(tmp) \
                        else os.remove(tmp)
                return False
            if os.path.exists(local_path):
                shutil.rmtree(local_path) if os.path.isdir(local_path) \
                    else os.remove(local_path)
            os.rename(tmp, local_path)
            return True
        src = self._local(hdfs_path)
        if not os.path.exists(src):
            return False
        if os.path.exists(local_path) and not overwrite:
            return False
        os.makedirs(os.path.dirname(os.path.abspath(local_path)),
                    exist_ok=True)
        if os.path.isdir(src):
            if os.path.exists(local_path):
                shutil.rmtree(local_path)
            shutil.copytree(src, local_path)
        else:
            shutil.copy2(src, local_path)
        return True

    def is_exist(self, hdfs_path=None):
        if self._bin:
            return self._run(["-test", "-e", hdfs_path], 1)[0]
        return os.path.exists(self._local(hdfs_path))

    def is_dir(self, hdfs_path=None):
        if self._bin:
            return self._run(["-test", "-d", hdfs_path], 1)[0]
        return os.path.isdir(self._local(hdfs_path))

    def delete(self, hdfs_path):
        if self._bin:
            # deterministic outcome — no point re-running 5 times
            return self._run(["-rm", "-r", hdfs_path], 1)[0]
        p = self._local(hdfs_path)
        if not os.path.exists(p):
            return False
        shutil.rmtree(p) if os.path.isdir(p) else os.remove(p)
        return True

    def rename(self, hdfs_src_path, hdfs_dst_path, overwrite=False):
        if self._bin:
            if overwrite and self.is_exist(hdfs_dst_path):
                self._run(["-rm", "-r", hdfs_dst_path], 1)
            return self._run(["-mv", hdfs_src_path, hdfs_dst_path], 1)[0]
        src, dst = self._local(hdfs_src_path), self._local(hdfs_dst_path)
        if not os.path.exists(src):
            return False
        if os.path.exists(dst):
            if not overwrite:
                return False
            self.delete(hdfs_dst_path)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        os.rename(src, dst)
        return True

    def makedirs(self, hdfs_path):
        if self._bin:
            # '-mkdir -p' is idempotent: retrying transient failures is safe
            return self._run(["-mkdir", "-p", hdfs_path])[0]
        os.makedirs(self._local(hdfs_path), exist_ok=True)
        return True

    @staticmethod
    def make_local_dirs(local_path):
        os.makedirs(local_path, exist_ok=True)

    def ls(self, hdfs_path):
        if self._bin:
            ok, out = self._run(["-ls", hdfs_path], 3)
            if not ok:
                return []
            # 8 fields, maxsplit=7: spaces in the path stay intact
            return [parts[7] for parts in
                    (line.split(None, 7) for line in out.splitlines()
                     if line and not line.startswith("Found"))
                    if len(parts) >= 8]
        p = self._local(hdfs_path)
        if not os.path.isdir(p):
            return []
        return sorted(
            os.path.join(hdfs_path, n) for n in os.listdir(p))

    def lsr(self, hdfs_path, only_file=True, sort=True):
        if self._bin:
            ok, out_text = self._run(["-ls", "-R", hdfs_path], 3)
            if not ok:
                return []
            out = []
            for line in out_text.splitlines():
                # `hadoop fs -ls` emits 8 whitespace-separated fields;
                # maxsplit=7 keeps paths containing spaces intact
                parts = line.split(None, 7)
                if len(parts) < 8:
                    continue
                if only_file and parts[0].startswith("d"):
                    continue
                out.append(parts[7])
            return sorted(out) if sort else out
        p = self._local(hdfs_path)
        out = []
        for root, dirs, files in os.walk(p):
            rel = os.path.relpath(root, self.local_root)
            names = files if only_file else files + dirs
            for n in names:
                out.append("/" + os.path.join(rel, n))
        return sorted(out) if sort else out


def multi_upload(client, hdfs_path, local_path, multi_processes=5,
                 overwrite=False, sync=True):
    """Upload a local tree (reference hdfs_utils.py multi_upload; the
    process fan-out is an I/O optimization — semantics preserved).
    Returns the list of destinations that FAILED to upload (empty on
    full success) so partial staging is visible to the caller."""
    failed = []
    for root, _, files in os.walk(local_path):
        rel = os.path.relpath(root, local_path)
        for n in files:
            dst = os.path.join(hdfs_path, "" if rel == "." else rel, n)
            client.makedirs(os.path.dirname(dst))
            if not client.upload(dst, os.path.join(root, n),
                                 overwrite=overwrite):
                failed.append(dst)
    return failed


def multi_download(client, hdfs_path, local_path, trainer_id=0,
                   trainers=1, multi_processes=5):
    """Download this trainer's shard of an HDFS tree (reference
    hdfs_utils.py multi_download: files round-robin by trainer id)."""
    files = client.lsr(hdfs_path)
    mine = [f for i, f in enumerate(files)
            if i % max(trainers, 1) == trainer_id]
    got = []
    for f in mine:
        rel = os.path.relpath(f, hdfs_path)
        dst = os.path.join(local_path, rel)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if client.download(f, dst, overwrite=True):
            got.append(dst)
    return got
