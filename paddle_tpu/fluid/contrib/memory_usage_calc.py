"""Pre-run memory estimate.

Reference analogue: python/paddle/fluid/contrib/memory_usage_calc.py — sums
per-variable byte sizes over a program for a given batch size, reporting a
(low, high) usage window.
"""

from .. import core

__all__ = ["memory_usage"]

DTYPE_TO_SIZE = {"float16": 2, "bfloat16": 2, "float32": 4, "float64": 8,
                 "int8": 1, "uint8": 1, "int16": 2, "int32": 4, "int64": 8,
                 "bool": 1}


def memory_usage(program, batch_size=1):
    """Return (min_mb, max_mb) estimated device memory for one iteration.
    XLA fuses and reuses buffers aggressively, so the true footprint is
    usually near the low end; the high end assumes every var is live."""
    total = 0.0
    for var in program.list_vars():
        if var.shape is None:
            continue
        numel = 1
        for d in var.shape:
            numel *= batch_size if (d is None or d < 0) else int(d)
        np_dtype = core.convert_dtype_to_np(var.dtype) if var.dtype else None
        size = DTYPE_TO_SIZE.get(str(np_dtype), 4) if np_dtype is not None \
            else 4
        total += numel * size
    mb = total / (1024.0 * 1024.0)
    return mb * 0.5, mb * 1.5
