"""Device/place and dtype plumbing — the TPU-native analogue of the reference's
paddle/fluid/platform/place.h (CPUPlace/CUDAPlace variants, place.h:26,37,52) and
the dtype enum in framework.proto:105 (VarType).

On TPU there is no user-managed device context: XLA owns streams and memory
(SURVEY.md §2.5 note). A Place therefore just names a jax.Device (or the
host-CPU backend used for testing with a forced multi-device topology).
"""

import numpy as np

__all__ = [
    "CPUPlace", "TPUPlace", "CUDAPlace", "CUDAPinnedPlace", "VarDesc",
    "is_compiled_with_tpu", "get_tpu_device_count",
]


class Place:
    """Base device designator. Resolves lazily to a jax.Device so that merely
    importing the framework never initialises the backend."""

    _backend = None  # subclass override

    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    def jax_device(self):
        import jax
        devs = jax.devices(self._backend) if self._backend else jax.devices()
        return devs[self.device_id % len(devs)]

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self.device_id)


class CPUPlace(Place):
    _backend = "cpu"


class TPUPlace(Place):
    """The TPU analogue of CUDAPlace (reference place.h:37). Uses the default
    jax backend so it also works under a forced host-platform topology."""
    _backend = None


# The reference's benchmark scripts say CUDAPlace; accept the name and route it
# to the accelerator backend so scripts run unmodified (BASELINE.json north star).
CUDAPlace = TPUPlace


class CUDAPinnedPlace(CPUPlace):
    pass


def is_compiled_with_tpu():
    import jax
    try:
        return any(d.platform not in ("cpu",) for d in jax.devices())
    except RuntimeError:
        return False


# Kept for API parity with fluid scripts that call core.get_cuda_device_count().
def get_tpu_device_count():
    import jax
    return len(jax.devices())


get_cuda_device_count = get_tpu_device_count


class VarDesc:
    """Mirror of framework.proto:105 VarType enum (the dtype/var-kind tags)."""

    class VarType:
        # var kinds
        LOD_TENSOR = 7
        SELECTED_ROWS = 8
        FEED_MINIBATCH = 9
        FETCH_LIST = 10
        STEP_SCOPES = 11
        LOD_RANK_TABLE = 12
        LOD_TENSOR_ARRAY = 13
        READER = 15
        RAW = 17
        # dtypes
        BOOL = 0
        INT16 = 1
        INT32 = 2
        INT64 = 3
        FP16 = 4
        FP32 = 5
        FP64 = 6
        UINT8 = 20
        INT8 = 21
        BF16 = 22


_DTYPE_TO_NP = {
    VarDesc.VarType.BOOL: np.bool_,
    VarDesc.VarType.INT16: np.int16,
    VarDesc.VarType.INT32: np.int32,
    VarDesc.VarType.INT64: np.int64,
    VarDesc.VarType.FP16: np.float16,
    VarDesc.VarType.FP32: np.float32,
    VarDesc.VarType.FP64: np.float64,
    VarDesc.VarType.UINT8: np.uint8,
    VarDesc.VarType.INT8: np.int8,
}


def convert_np_dtype_to_dtype_(np_dtype):
    """numpy dtype / string -> VarType enum (reference framework.py behavior)."""
    if isinstance(np_dtype, int):
        return np_dtype
    if str(np_dtype) == "bfloat16":
        return VarDesc.VarType.BF16
    dtype = np.dtype(np_dtype)
    for enum, nd in _DTYPE_TO_NP.items():
        if np.dtype(nd) == dtype:
            return enum
    raise ValueError("Not supported numpy dtype %s" % dtype)


def convert_dtype_to_np(dtype):
    """VarType enum / string -> canonical numpy-compatible dtype object.

    BF16 maps to ml_dtypes.bfloat16 (jax's numpy-compatible bfloat16)."""
    if dtype == VarDesc.VarType.BF16 or str(dtype) == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    if isinstance(dtype, int):
        return np.dtype(_DTYPE_TO_NP[dtype])
    return np.dtype(dtype)


def dtype_size(dtype):
    """Bytes per element of a VarType enum / numpy dtype / string —
    the static byte accounting the resource analyzer (analysis/
    resources.py) sums var shapes with.  BF16 is 2 bytes, INT8 one (the
    quantized lane's weight-footprint win reads straight from this)."""
    if dtype == VarDesc.VarType.BF16 or str(dtype) == "bfloat16":
        return 2
    return int(convert_dtype_to_np(dtype).itemsize)
