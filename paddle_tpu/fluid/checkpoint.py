"""Checkpoint vault: atomic, CRC-verified, rotated checkpoint directories.

Reference analogue: the Go pserver's checkpoint story — go/pserver/
service.go:119 `checkpointMeta` (path + CRC32 + timestamp kept in etcd)
and :145 `parameterCheckpoint` (write temp file, fsync, rename), whose
LoadCheckpoint (:174) rejects a shard whose CRC32 no longer matches.
The Python side (fluid/io.py save_checkpoint + CheckpointConfig) numbered
checkpoint directories and pruned old serials (_scroll_delete).

TPU redesign: one vault layout serves both the trainer and the pserver
shards.  A checkpoint is a *directory* committed atomically:

    <root>/
      checkpoint_<step>/
        __manifest__.json        # schema, meta {epoch, step, ...}, per-array
                                 #   {file, crc32, shape, dtype, nbytes}
        <array files>.npy        # one file per persistable (the "shards")
      latest                     # text file naming a fully-committed dir
      _tmp.checkpoint_<step>.*   # in-flight save (ignored by readers)

Commit protocol: write every array + the manifest into a temp directory,
fsync each file, fsync the temp dir, `os.rename` it to its final numbered
name, fsync the root dir, then atomically rewrite `latest` (temp + fsync +
rename).  A `kill -9` at ANY point leaves either (a) a stale `_tmp.*` dir
(swept by the next save) with `latest` still naming the previous good
checkpoint, or (b) a fully-committed new dir — never a half-written
checkpoint that `latest` points at.  Loads verify every array's CRC32 and
raise `CheckpointCorruptionError` naming the first corrupt array.

Chaos hooks: `PADDLE_TPU_CHAOS="<point>=<action>[@<n>]"` (or an in-process
hook via `set_chaos_hook`) fires a fault at a named protocol point — the
fault-injection surface tools/chaos.py and tests/test_fault_tolerance.py
drive.  Points, in commit order: `array_written`, `arrays_written`,
`manifest_written`, `committed`, `latest_updated`.  Actions: `exit`
(os._exit(137) — the kill -9 analogue) and `pause[:secs]` (print a
`CHAOS_PAUSE <point>` marker and sleep so a parent process can SIGKILL
for real).  `@<n>` fires on the n-th arrival at that point (1-based).
"""

import binascii
import io as _io
import json
import os
import re
import shutil
import threading
import time

import numpy as np

__all__ = [
    "CheckpointError", "CheckpointCorruptionError", "MANIFEST_NAME",
    "LATEST_NAME", "save_checkpoint_dir", "load_checkpoint_dir",
    "verify_checkpoint_dir", "read_manifest", "list_checkpoints",
    "latest_checkpoint", "rotate_checkpoints", "normalize_meta",
    "AsyncCheckpointSaver", "async_saver", "wait_for_async_saves",
    "set_chaos_hook", "atomic_write",
]

MANIFEST_NAME = "__manifest__.json"
LATEST_NAME = "latest"
SCHEMA_VERSION = 1
_DIR_RE = re.compile(r"^checkpoint_(\d+)$")
_TMP_PREFIX = "_tmp.checkpoint_"


class CheckpointError(RuntimeError):
    """A checkpoint directory is missing or structurally unusable."""


class CheckpointCorruptionError(CheckpointError):
    """An array shard failed its CRC32 / shape / dtype verification.
    The message names the offending array and file."""


# ---------------------------------------------------------------------------
# chaos / fault-injection hooks
# ---------------------------------------------------------------------------

_CHAOS_ENV = "PADDLE_TPU_CHAOS"
_chaos_hook = None
_chaos_hits = {}
_chaos_lock = threading.Lock()


def set_chaos_hook(fn):
    """Install an in-process fault hook `fn(point_name)` (None clears).
    Used by tests to interrupt a save at an exact protocol point without
    spawning a subprocess; the env-var spec serves real-kill scenarios."""
    global _chaos_hook
    _chaos_hook = fn
    _chaos_hits.clear()


def _chaos(point):
    if _chaos_hook is not None:
        _chaos_hook(point)
        return
    spec = os.environ.get(_CHAOS_ENV)
    if not spec:
        return
    with _chaos_lock:
        hits = _chaos_hits[point] = _chaos_hits.get(point, 0) + 1
    for part in spec.split(","):
        name, _, action = part.partition("=")
        nth = 1
        if "@" in action:
            action, _, n = action.rpartition("@")
            nth = int(n)
        if name != point or hits != nth:
            continue
        if action == "exit":
            os._exit(137)  # kill -9 semantics: no cleanup, no atexit
        if action.startswith("pause"):
            secs = float(action.split(":", 1)[1]) if ":" in action else 60.0
            print("CHAOS_PAUSE %s" % point, flush=True)
            time.sleep(secs)


# ---------------------------------------------------------------------------
# layout helpers
# ---------------------------------------------------------------------------

def _fsync_file(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse fsync on directories
    finally:
        os.close(fd)


def _atomic_write(path, data, fsync=True, chaos_point=None):
    """Write bytes to `path` via temp + fsync + rename.  `chaos_point`
    names an optional fault-injection point fired between the durable
    temp write and the rename — a crash there must leave the previous
    file intact plus a stale `.tmp.*`, never a truncated target (the
    kill-mid-write scenarios in tools/chaos.py)."""
    tmp = "%s.tmp.%d.%x" % (path, os.getpid(), threading.get_ident())
    with open(tmp, "wb") as f:
        f.write(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    if chaos_point:
        _chaos(chaos_point)
    os.replace(tmp, path)
    if fsync:
        _fsync_dir(os.path.dirname(path) or ".")


# the SHARED commit helper: compile_cache.py (AOT store + kernel-tuning
# registry) and ops/attention_tuning.py ride the same discipline
atomic_write = _atomic_write


def checkpoint_dir_name(step):
    return "checkpoint_%d" % int(step)


def list_checkpoints(root):
    """[(step, abs_path)] of committed checkpoint dirs, ascending step."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _DIR_RE.match(name)
        path = os.path.join(root, name)
        if m and os.path.isdir(path) and \
                os.path.exists(os.path.join(path, MANIFEST_NAME)):
            out.append((int(m.group(1)), path))
    out.sort()
    return out


def latest_checkpoint(root):
    """Resolve the `latest` pointer -> absolute dir path, or None.
    Falls back to the highest committed step when the pointer is missing
    (e.g. a crash landed between commit and pointer update — the new dir
    is fully committed, so it is safe to prefer it)."""
    ptr = os.path.join(root, LATEST_NAME)
    if os.path.exists(ptr):
        with open(ptr) as f:
            name = f.read().strip()
        cand = os.path.join(root, name)
        if _DIR_RE.match(name) and \
                os.path.exists(os.path.join(cand, MANIFEST_NAME)):
            return cand
    cks = list_checkpoints(root)
    return cks[-1][1] if cks else None


def normalize_meta(meta):
    """One explicit meta schema for save/load/Trainer: a dict with at
    least integer `epoch` and `step`.  Accepts the legacy forms the old
    io.save_checkpoint produced (a bare int step, a {"epoch","step"}
    dict, or None) and always returns the canonical dict."""
    if meta is None:
        return {"epoch": 0, "step": 0}
    if isinstance(meta, (int, np.integer)):
        return {"epoch": 0, "step": int(meta)}
    if isinstance(meta, dict):
        out = dict(meta)
        out["epoch"] = int(out.get("epoch", 0) or 0)
        out["step"] = int(out.get("step", 0) or 0)
        return out
    raise TypeError("checkpoint meta must be an int step or a dict with "
                    "'epoch'/'step', got %r" % (meta,))


def _array_filename(name, used):
    base = name.replace("/", "__")
    fname = base + ".npy"
    k = 0
    while fname in used:  # sanitization collision: disambiguate
        k += 1
        fname = "%s.%d.npy" % (base, k)
    used.add(fname)
    return fname


def _npy_bytes(arr):
    buf = _io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


# ---------------------------------------------------------------------------
# save / load / verify
# ---------------------------------------------------------------------------

def _sweep_stale_tmp(root, keep=None):
    for name in os.listdir(root):
        if name.startswith(_TMP_PREFIX):
            path = os.path.join(root, name)
            if path != keep:
                shutil.rmtree(path, ignore_errors=True)


def save_checkpoint_dir(root, arrays, meta, max_num_checkpoints=None,
                        fsync=True):
    """Commit one checkpoint of `arrays` (name -> array-like) under
    `root` as `checkpoint_<meta['step']>/`, update `latest`, rotate.
    Returns the committed directory path."""
    meta = normalize_meta(meta)
    step = meta["step"]
    os.makedirs(root, exist_ok=True)
    tmp = os.path.join(root, "%s%d.%d.%x" % (
        _TMP_PREFIX, step, os.getpid(), threading.get_ident()))
    _sweep_stale_tmp(root, keep=tmp)
    os.makedirs(tmp)
    manifest = {"schema": SCHEMA_VERSION, "meta": meta, "arrays": {}}
    used = set()
    for name in sorted(arrays):
        arr = np.ascontiguousarray(np.asarray(arrays[name]))
        fname = _array_filename(name, used)
        data = _npy_bytes(arr)
        with open(os.path.join(tmp, fname), "wb") as f:
            f.write(data)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        manifest["arrays"][name] = {
            "file": fname,
            "crc32": binascii.crc32(data) & 0xFFFFFFFF,
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "nbytes": int(arr.nbytes),
        }
        _chaos("array_written")
    _chaos("arrays_written")
    mpath = os.path.join(tmp, MANIFEST_NAME)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    _chaos("manifest_written")
    if fsync:
        _fsync_dir(tmp)
    final = os.path.join(root, checkpoint_dir_name(step))
    if os.path.isdir(final):
        # re-save at the same step (e.g. rollback then retrain): move the
        # old dir aside first — rename onto a non-empty dir fails
        trash = final + ".old.%d" % os.getpid()
        os.rename(final, trash)
        shutil.rmtree(trash, ignore_errors=True)
    os.rename(tmp, final)
    _chaos("committed")
    if fsync:
        _fsync_dir(root)
    _atomic_write(os.path.join(root, LATEST_NAME),
                  (checkpoint_dir_name(step) + "\n").encode(), fsync=fsync)
    _chaos("latest_updated")
    # lifecycle record (OBSERVABILITY.md): the commit is durable and
    # the `latest` pointer names it — stamped with the step id so the
    # event log cross-references the train-side ckpt spans
    from ..obs import events as _obs_events
    _obs_events.emit("checkpoint_committed", step=int(step),
                     epoch=meta.get("epoch"), path=final)
    if max_num_checkpoints:
        rotate_checkpoints(root, max_num_checkpoints)
    return final


def rotate_checkpoints(root, max_num_checkpoints):
    """Keep the newest `max_num_checkpoints` committed dirs (reference
    CheckpointConfig.max_num_checkpoints / _scroll_delete).  The dir the
    `latest` pointer names is never deleted, whatever its step."""
    keep = max(int(max_num_checkpoints), 1)
    cks = list_checkpoints(root)
    if len(cks) <= keep:
        return []
    latest = latest_checkpoint(root)
    removed = []
    for _, path in cks[:-keep]:
        if path == latest:
            continue
        shutil.rmtree(path, ignore_errors=True)
        removed.append(path)
    return removed


def read_manifest(dirname):
    mpath = os.path.join(dirname, MANIFEST_NAME)
    if not os.path.exists(mpath):
        raise CheckpointError("no %s in %s — not a committed checkpoint "
                              "directory" % (MANIFEST_NAME, dirname))
    with open(mpath) as f:
        manifest = json.load(f)
    if manifest.get("schema") != SCHEMA_VERSION:
        raise CheckpointError("checkpoint %s has manifest schema %r, this "
                              "build reads schema %d"
                              % (dirname, manifest.get("schema"),
                                 SCHEMA_VERSION))
    return manifest


def _load_one(dirname, name, ent):
    path = os.path.join(dirname, ent["file"])
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise CheckpointCorruptionError(
            "checkpoint %s: array %r is missing its shard file %s (%s)"
            % (dirname, name, ent["file"], e))
    crc = binascii.crc32(data) & 0xFFFFFFFF
    if crc != ent["crc32"]:
        raise CheckpointCorruptionError(
            "checkpoint %s: array %r failed CRC32 verification "
            "(manifest %d != file %d) — shard %s is corrupt"
            % (dirname, name, ent["crc32"], crc, ent["file"]))
    arr = np.load(_io.BytesIO(data), allow_pickle=False)
    if list(arr.shape) != list(ent["shape"]) or \
            str(arr.dtype) != ent["dtype"]:
        raise CheckpointCorruptionError(
            "checkpoint %s: array %r decoded as %s%s but the manifest "
            "says %s%s" % (dirname, name, arr.dtype, list(arr.shape),
                           ent["dtype"], ent["shape"]))
    return arr


def load_checkpoint_dir(dirname, names=None):
    """Load a committed checkpoint dir -> (arrays dict, meta dict),
    CRC-verifying every shard (or just `names` when given)."""
    manifest = read_manifest(dirname)
    out = {}
    for name, ent in manifest["arrays"].items():
        if names is not None and name not in names:
            continue
        out[name] = _load_one(dirname, name, ent)
    return out, normalize_meta(manifest.get("meta"))


def verify_checkpoint_dir(dirname):
    """CRC-verify every shard without keeping the arrays; returns the
    manifest.  Raises CheckpointCorruptionError naming the first bad
    array."""
    manifest = read_manifest(dirname)
    for name, ent in manifest["arrays"].items():
        _load_one(dirname, name, ent)
    return manifest


# ---------------------------------------------------------------------------
# async save
# ---------------------------------------------------------------------------

class AsyncCheckpointSaver:
    """One background worker draining save jobs in submit order, so the
    train loop never stalls on checkpoint IO.  jax arrays are immutable,
    so passing the live state refs is snapshot-safe; the host transfer
    and file IO both happen off-thread.  Errors are re-raised on the next
    `submit` or on `wait` — a failed checkpoint must not stay silent."""

    def __init__(self):
        self._lock = threading.Lock()
        self._jobs = []
        self._error = None
        self._thread = None
        self._wake = threading.Condition(self._lock)
        self._busy = 0

    def _worker(self):
        while True:
            with self._wake:
                while not self._jobs:
                    self._wake.wait()
                job = self._jobs.pop(0)
                self._busy += 1
            try:
                if job is None:
                    return
                save_checkpoint_dir(*job)
            except BaseException as e:  # surfaced on wait()/next submit()
                with self._wake:
                    self._error = e
            finally:
                with self._wake:
                    self._busy -= 1
                    self._wake.notify_all()

    def submit(self, root, arrays, meta, max_num_checkpoints=None):
        self._raise_pending()
        with self._wake:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._worker, daemon=True,
                    name="paddle-tpu-ckpt-saver")
                self._thread.start()
            self._jobs.append((root, dict(arrays), normalize_meta(meta),
                               max_num_checkpoints))
            self._wake.notify_all()

    def wait(self, timeout=None):
        """Block until every submitted save has committed; re-raises the
        first background error."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._wake:
            while self._jobs or self._busy:
                rem = None if deadline is None \
                    else max(deadline - time.monotonic(), 0.0)
                if rem == 0.0:
                    raise TimeoutError("async checkpoint save still "
                                       "running after %.1fs" % timeout)
                self._wake.wait(rem)
        self._raise_pending()

    def _raise_pending(self):
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise CheckpointError(
                "background checkpoint save failed: %r" % (err,)) from err


_async_saver = None


def async_saver():
    global _async_saver
    if _async_saver is None:
        _async_saver = AsyncCheckpointSaver()
    return _async_saver


def wait_for_async_saves(timeout=None):
    """Join all pending background checkpoint saves (no-op when none)."""
    if _async_saver is not None:
        _async_saver.wait(timeout)
