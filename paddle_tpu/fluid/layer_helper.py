"""LayerHelper — shared parameter/op plumbing for layer functions.

Reference analogue: python/paddle/fluid/layer_helper.py — creates parameters
in the startup+main programs, appends ops, applies default initializers,
activations and bias.
"""

from . import unique_name
from .framework import default_main_program, default_startup_program, Variable
from .initializer import ConstantInitializer, XavierInitializer
from .param_attr import ParamAttr

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = self.kwargs.get("name")
        if name is None:
            self.kwargs["name"] = unique_name.generate(layer_type)

    @property
    def name(self):
        return self.kwargs["name"]

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    def append_op(self, *args, **kwargs):
        op = self.main_program.current_block().append_op(*args, **kwargs)
        self._propagate_build_lod_level(kwargs)
        return op

    @staticmethod
    def _propagate_build_lod_level(kwargs):
        """Build-time analogue of the runtime companion propagation: a
        LoD-oblivious op's outputs inherit the max input lod_level, so
        downstream layers can gate on nestedness (e.g. kmax_seq_score
        force_host) without the var having been fed directly."""
        from .framework import Variable
        from .functionalizer import _LOD_DROP_OPS
        if kwargs.get("type") in _LOD_DROP_OPS:
            return
        level = 0
        for names in (kwargs.get("inputs") or {}).values():
            vs = names if isinstance(names, (list, tuple)) else [names]
            for v in vs:
                if isinstance(v, Variable):
                    level = max(level, getattr(v, "lod_level", 0) or 0)
        if level:
            for names in (kwargs.get("outputs") or {}).values():
                vs = names if isinstance(names, (list, tuple)) else [names]
                for v in vs:
                    if isinstance(v, Variable) and \
                            not getattr(v, "lod_level", 0):
                        v.lod_level = level

    # ---- inputs ----
    def multiple_input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if isinstance(inputs, Variable):
            inputs = [inputs]
        return list(inputs)

    def input(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError("%s layer needs exactly one input" %
                             self.layer_type)
        return inputs[0]

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr", None))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr", None))

    def multiple_param_attr(self, length):
        attr = self.param_attr
        if isinstance(attr, ParamAttr):
            attr = [attr]
        if len(attr) != 1 and len(attr) != length:
            raise ValueError("parameter number mismatch")
        if len(attr) == 1 and length != 1:
            attr = [attr[0]] + [ParamAttr(**attr[0].__dict__.copy())
                                for _ in range(length - 1)]
        return attr

    def iter_inputs_and_params(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        param_attrs = self.multiple_param_attr(len(inputs))
        for ipt, param_attr in zip(inputs, param_attrs):
            yield ipt, param_attr

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for each in inputs:
            if dtype is None:
                dtype = each.dtype
            elif dtype != each.dtype:
                raise ValueError("Data Type mismatch: %d to %d" %
                                 (dtype, each.dtype))
        return dtype

    # ---- parameters ----
    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None):
        if attr is False:
            return None
        attr = ParamAttr._to_attr(attr)
        if default_initializer is None:
            if is_bias:
                attr._set_default_bias_initializer()
            else:
                attr._set_default_param_initializer()
        else:
            attr._set_default_initializer(default_initializer)
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "w" if
                                                       not is_bias else "b"]))
        startup_block = self.startup_program.global_block()
        if attr.name in startup_block.vars:
            # shared parameter (same explicit name created again, e.g. an
            # unrolled decode loop re-building its step): one startup
            # init, one runtime array — return the existing main var
            existing = self.main_program.global_block().vars.get(attr.name)
            if existing is not None:
                return existing
        else:
            startup_p = startup_block.create_parameter(
                shape=shape, dtype=dtype, **attr._to_kwargs())
            attr.initializer(startup_p, startup_block)
        main_p = self.main_program.global_block().create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs())
        return main_p

    def get_parameter(self, name):
        param = self.main_program.global_block().var(name)
        return param

    # ---- temp vars ----
    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype, persistable=False, stop_gradient=stop_gradient)

    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, *args, **kwargs):
        return self.main_program.current_block().create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, **kwargs)

    def create_or_get_global_variable(self, name, *args, **kwargs):
        gb = self.main_program.global_block()
        if not gb.has_var(name):
            return self.create_global_variable(name=name, *args, **kwargs)
        return gb.var(name)

    def set_variable_initializer(self, var, initializer):
        sb = self.startup_program.global_block()
        sv = sb.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                           persistable=True)
        initializer(sv, sb)

    # ---- bias / activation ----
    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.bias_attr
        if bias_attr is False:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        b = self.create_parameter(attr=bias_attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True)
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start})
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act", None)
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(type=act_type, inputs={"X": [input_var]},
                       outputs={"Out": [tmp]}, attrs=act)
        return tmp

    def is_instance(self, param_name, cls):
        param = self.kwargs.get(param_name, None)
        if not isinstance(param, cls):
            raise TypeError("%s of %s must be %s" %
                            (param_name, self.layer_type, cls))
